"""Memory reporting.

Analog of the reference's `see_memory_usage` (sprinkled through engine/ZeRO). On TPU we
read per-device HBM stats from `device.memory_stats()` plus host RSS from /proc.
"""

import os

from deepspeed_tpu.utils.logging import logger


def _host_rss_gb():
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / (1024**2)
    except Exception:
        pass
    return 0.0


def device_memory_stats(device=None):
    """Return dict of bytes_in_use / peak_bytes_in_use / bytes_limit for a device."""
    import jax
    if device is None:
        device = jax.devices()[0]
    stats = {}
    try:
        raw = device.memory_stats() or {}
        stats["bytes_in_use"] = raw.get("bytes_in_use", 0)
        stats["peak_bytes_in_use"] = raw.get("peak_bytes_in_use", 0)
        stats["bytes_limit"] = raw.get("bytes_limit", 0)
    except Exception:
        pass
    return stats


def see_memory_usage(message, force=False, ranks=None):
    """Log device HBM + host RSS. `force` gate mirrors the reference's signature."""
    if not force:
        return
    import jax
    if ranks is not None and jax.process_index() not in ranks:
        return
    stats = device_memory_stats()
    gb = 1024**3
    logger.info(
        f"{message} | HBM in use: {stats.get('bytes_in_use', 0)/gb:.2f} GB | "
        f"HBM peak: {stats.get('peak_bytes_in_use', 0)/gb:.2f} GB | "
        f"HBM limit: {stats.get('bytes_limit', 0)/gb:.2f} GB | "
        f"host RSS: {_host_rss_gb():.2f} GB")


def get_hbm_capacity_bytes(device=None):
    return device_memory_stats(device).get("bytes_limit", 0)
