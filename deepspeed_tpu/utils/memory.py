"""Memory reporting.

Analog of the reference's `see_memory_usage` (sprinkled through engine/ZeRO). On TPU we
read per-device HBM stats from `device.memory_stats()` plus host RSS from /proc.
When handed a `Telemetry` object the reading also lands in the metrics registry
(`mem/bytes_in_use` / `mem/peak_bytes`), so scraping dashboards see the same
numbers the log line prints; the full byte-attribution ledger lives in
`deepspeed_tpu/telemetry/memscope.py`.
"""

import os

from deepspeed_tpu.utils.logging import logger


def _host_rss_gb():
    """Host resident-set size in GiB, from procfs. Platforms without /proc
    (macOS, some sandboxes) report 0.0 — never a crash."""
    path = f"/proc/{os.getpid()}/status"
    if not os.path.exists(path):
        return 0.0
    try:
        with open(path) as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / (1024**2)
    except Exception:
        pass
    return 0.0


def device_memory_stats(device=None):
    """Return dict of bytes_in_use / peak_bytes_in_use / bytes_limit for a device."""
    import jax
    if device is None:
        device = jax.devices()[0]
    stats = {}
    try:
        raw = device.memory_stats() or {}
        stats["bytes_in_use"] = raw.get("bytes_in_use", 0)
        stats["peak_bytes_in_use"] = raw.get("peak_bytes_in_use", 0)
        stats["bytes_limit"] = raw.get("bytes_limit", 0)
    except Exception:
        pass
    return stats


def see_memory_usage(message, force=False, ranks=None, telemetry=None):
    """Log device HBM + host RSS. `force` gate mirrors the reference's
    signature. With `telemetry` (an enabled `Telemetry`), the same reading
    sets the `mem/bytes_in_use` / `mem/peak_bytes` gauges — the call sites
    sprinkled through the engine become scrape points, not just log lines."""
    if not force:
        return
    import jax
    if ranks is not None and jax.process_index() not in ranks:
        return
    stats = device_memory_stats()
    if telemetry is not None and getattr(telemetry, "enabled", False):
        telemetry.set_gauge("mem/bytes_in_use", stats.get("bytes_in_use", 0))
        telemetry.set_gauge("mem/peak_bytes",
                            stats.get("peak_bytes_in_use", 0))
    gb = 1024**3
    logger.info(
        f"{message} | HBM in use: {stats.get('bytes_in_use', 0)/gb:.2f} GB | "
        f"HBM peak: {stats.get('peak_bytes_in_use', 0)/gb:.2f} GB | "
        f"HBM limit: {stats.get('bytes_limit', 0)/gb:.2f} GB | "
        f"host RSS: {_host_rss_gb():.2f} GB")


def get_hbm_capacity_bytes(device=None):
    return device_memory_stats(device).get("bytes_limit", 0)
