"""Wall-clock + throughput timers.

Analog of the reference's `deepspeed/utils/timer.py` (`SynchronizedWallClockTimer`,
`ThroughputTimer`). "Synchronized" here means blocking on outstanding device work via
`jax.block_until_ready`-style barriers rather than cuda events: on TPU the dispatch is
async, so an honest timer must fence the device.
"""

import time

from deepspeed_tpu.utils.logging import logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _device_sync():
    try:
        import jax
        # Block on a trivial computation to drain the dispatch queue.
        jax.effects_barrier()
    except Exception:
        pass


class Timer:
    def __init__(self, name, synchronize=True):
        self.name = name
        self.synchronize = synchronize
        self.started = False
        self.start_time = 0.0
        self.elapsed_total = 0.0
        self.count = 0

    def start(self):
        if self.started:
            return
        if self.synchronize:
            _device_sync()
        self.start_time = time.perf_counter()
        self.started = True

    def stop(self, reset=False):
        if not self.started:
            return
        if self.synchronize:
            _device_sync()
        self.elapsed_total += time.perf_counter() - self.start_time
        self.count += 1
        self.started = False

    def elapsed(self, reset=True):
        value = self.elapsed_total
        if reset:
            self.reset()
        return value

    def mean(self):
        return self.elapsed_total / max(self.count, 1)

    def reset(self):
        self.elapsed_total = 0.0
        self.count = 0
        self.started = False


class SynchronizedWallClockTimer:
    """Named-timer registry; `log()` prints ms per timer like the reference."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    def has_timer(self, name):
        return name in self.timers

    def log(self, names=None, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        names = names if names is not None else list(self.timers.keys())
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        logger.info(string)
        return string

    def get_mean(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                means[name] = self.timers[name].mean() * 1000.0 / normalizer
                if reset:
                    self.timers[name].reset()
        return means


class ThroughputTimer:
    """Tracks samples/sec across steps, skipping warmup steps.

    Mirrors the reference `ThroughputTimer` (`utils/timer.py`): per-step latency,
    global samples/sec, optional flops-per-sample -> TFLOPs report.
    """

    def __init__(self, batch_size, start_step=2, steps_per_output=50, monitor_memory=False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            _device_sync()
            self.start_time = time.perf_counter()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _device_sync()
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step:
                if report_speed and self.global_step_count % self.steps_per_output == 0:
                    self.logging(
                        f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                        f"global_step={self.global_step_count}, RunningAvgSamplesPerSec={self.avg_samples_per_sec():.6g}, "
                        f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.6g}")
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self):
        if self.total_elapsed_time > 0:
            samples = self.batch_size * max(self.global_step_count - self.start_step, 1)
            return samples / self.total_elapsed_time
        return float("-inf")
