"""Access to partitioned optimizer/master state — the `safe_get_full_*` API.

Reference: `deepspeed/utils/tensor_fragment.py:101-190` — public helpers that
reassemble a full fp32 param / gradient / optimizer-state tensor from its ZeRO
shards so user code can inspect or edit them mid-training.

On TPU the shards are global arrays with NamedShardings, so "gathering" is a
resharding to replicated + device_get; editing is a functional update + re-placement.
The engine is passed explicitly (no hidden registry): these helpers take
(engine, path) where path is a tuple of pytree keys, or a '/'-joined string.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger


def _resolve(tree, path):
    if isinstance(path, str):
        path = tuple(path.split("/"))
    node = tree
    for k in path:
        if isinstance(node, (list, tuple)):
            node = node[int(k)]
        else:
            node = node[k]
    return node


def _set(tree, path, value):
    """Functional set returning a new pytree."""
    if isinstance(path, str):
        path = tuple(path.split("/"))

    def rec(node, keys):
        if not keys:
            return value
        k = keys[0]
        if isinstance(node, dict):
            return {**node, k: rec(node[k], keys[1:])}
        if isinstance(node, (list, tuple)):
            i = int(k)
            items = list(node)
            items[i] = rec(items[i], keys[1:])
            return type(node)(items)
        raise TypeError(f"cannot descend into {type(node)}")

    return rec(tree, path)


def _gather(arr):
    mesh = arr.sharding.mesh if hasattr(arr.sharding, "mesh") else None
    if mesh is not None:
        arr = jax.device_put(arr, NamedSharding(mesh, P()))
    return np.asarray(jax.device_get(arr))


def safe_get_full_fp32_param(engine, path):
    """Full fp32 master weight for a param (reference same name)."""
    source = engine.state.master if engine.keep_master else engine.state.params
    return _gather(_resolve(source, path)).astype(np.float32)


def safe_set_full_fp32_param(engine, path, value):
    source_name = "master" if engine.keep_master else "params"
    source = getattr(engine.state, source_name)
    leaf = _resolve(source, path)
    new_leaf = jax.device_put(jnp.asarray(value, leaf.dtype), leaf.sharding)
    new_source = _set(source, path, new_leaf)
    engine.state = engine.state._replace(**{source_name: new_source})
    if engine.keep_master:
        # propagate to the compute-dtype copy
        params_leaf = _resolve(engine.state.params, path)
        new_params = _set(engine.state.params, path,
                          jax.device_put(jnp.asarray(value, params_leaf.dtype),
                                         params_leaf.sharding))
        engine.state = engine.state._replace(params=new_params)


def safe_get_full_optimizer_state(engine, path, optim_state_key):
    """Full fp32 optimizer state (e.g. optim_state_key='mu'/'nu' ~ exp_avg/exp_avg_sq)."""
    alias = {"exp_avg": "mu", "exp_avg_sq": "nu"}
    key = alias.get(optim_state_key, optim_state_key)

    # walk the optax state tuple looking for a field named `key`
    def find(node):
        if hasattr(node, "_fields") and key in getattr(node, "_fields", ()):
            return getattr(node, key)
        if isinstance(node, (tuple, list)):
            for child in node:
                r = find(child)
                if r is not None:
                    return r
        if isinstance(node, dict):
            for child in node.values():
                r = find(child)
                if r is not None:
                    return r
        return None

    sub = find(engine.state.opt_state)
    if sub is None:
        raise KeyError(f"optimizer state '{optim_state_key}' not found")
    return _gather(_resolve(sub, path)).astype(np.float32)


def safe_set_full_optimizer_state(engine, path, value, optim_state_key):
    """Overwrite one param's fp32 optimizer-state tensor (reference same name,
    `tensor_fragment.py:150`). Accepts the reference's exp_avg/exp_avg_sq
    spellings for optax's mu/nu; the value is cast + resharded to the
    existing leaf's dtype/sharding."""
    alias = {"exp_avg": "mu", "exp_avg_sq": "nu"}
    key = alias.get(optim_state_key, optim_state_key)

    found = [False]

    def rebuild(node):
        if hasattr(node, "_fields"):
            if key in node._fields and not found[0]:
                found[0] = True
                sub = getattr(node, key)
                leaf = _resolve(sub, path)
                new_leaf = jax.device_put(jnp.asarray(value, leaf.dtype),
                                          leaf.sharding)
                return node._replace(**{key: _set(sub, path, new_leaf)})
            return type(node)(*[rebuild(c) for c in node])
        if isinstance(node, (tuple, list)):
            return type(node)(rebuild(c) for c in node)
        if isinstance(node, dict):
            return type(node)((k, rebuild(v)) for k, v in node.items())
        return node

    new_opt_state = rebuild(engine.state.opt_state)
    if not found[0]:
        raise KeyError(f"optimizer state '{optim_state_key}' not found")
    engine.state = engine.state._replace(opt_state=new_opt_state)


def safe_get_full_grad(engine, path):
    """Last accumulated full gradient (only available between backward() and step()
    on the parity API — the fused train_batch consumes grads inside one program)."""
    acc = getattr(engine, "_grad_acc", None)
    if acc is None:
        logger.warning("no pending gradients: call after forward/backward, before step")
        return None
    return _gather(_resolve(acc, path))
