"""Pytree helpers used across the runtime (flat-buffer bookkeeping analogues).

The reference flattens params into contiguous buffers (`csrc/utils/flatten_unflatten.cpp`,
ZeRO flat fp32 groups); in JAX, pytrees + XLA buffer donation subsume that, so these are
thin accounting/cast utilities.
"""

import jax
import jax.numpy as jnp
import numpy as np


def tree_num_params(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(np.prod(l.shape) if hasattr(l, "shape") else 1 for l in leaves))


def tree_size_bytes(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    total = 0
    for l in leaves:
        if hasattr(l, "shape") and hasattr(l, "dtype"):
            total += int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
    return total

def tree_cast(tree, dtype, only_float=True):
    """Cast floating leaves of a pytree to `dtype` (non-float leaves untouched)."""

    def cast(leaf):
        if hasattr(leaf, "dtype") and (not only_float or jnp.issubdtype(leaf.dtype, jnp.floating)):
            return leaf.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(cast, tree)


def tree_zeros_like(tree, dtype=None):
    return jax.tree_util.tree_map(lambda l: jnp.zeros(l.shape, dtype or l.dtype), tree)


def tree_global_norm(tree):
    """L2 norm over all leaves (used for gradient clipping / grad-norm logging)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))


def tree_all_finite(tree):
    """Scalar bool: every element of every leaf is finite (overflow check)."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "dtype")]
    if not leaves:
        return jnp.asarray(True)
    finite = [jnp.all(jnp.isfinite(l)) for l in leaves if jnp.issubdtype(l.dtype, jnp.floating)]
    if not finite:
        return jnp.asarray(True)
    return jnp.stack(finite).all()
