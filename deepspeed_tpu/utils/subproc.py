"""One subprocess recipe: env knobs in, JSON result out.

Every child-process harness in the repo speaks the same protocol — the
parent sets environment knobs, the child runs one lane/trial and prints
its result as a JSON object on the LAST line of stdout (progress chatter
above it is fine). `bench.py`'s dozen `BENCH_*_CHILD` sub-lanes, the
weak-scaling arms, and the autotuner's measured-trial runner
(`autotuning/measure.py`) all route through this module so the recipe —
env filtering, spawn, last-JSON-line parse, stderr salvage — exists
exactly once.
"""

import json
import os
import subprocess
import sys
from typing import Dict, Optional, Sequence, Tuple


def last_json_line(text: str, key: Optional[str] = None) -> Optional[dict]:
    """The last stdout line that parses as a JSON object (optionally
    required to carry `key`), or None. Children print progress freely;
    only the final JSON object is the result."""
    for line in reversed((text or "").strip().splitlines()):
        try:
            cand = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(cand, dict) and (key is None or key in cand):
            return cand
    return None


def child_env(overrides: Dict[str, str],
              clear_prefixes: Sequence[str] = (),
              base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The child's environment: the parent's, minus every variable whose
    name starts with a `clear_prefixes` entry (stray knobs meant for the
    parent must not silently reshape a pinned child config), plus
    `overrides` (stringified)."""
    env = {k: v for k, v in (base if base is not None else os.environ).items()
           if not any(k.startswith(p) for p in clear_prefixes)}
    env.update({k: str(v) for k, v in overrides.items()})
    return env


def run_json_child(argv: Sequence[str], overrides: Dict[str, str],
                   clear_prefixes: Sequence[str] = (), key: Optional[str] = None,
                   timeout: Optional[float] = None,
                   ) -> Tuple[Optional[dict], "subprocess.CompletedProcess"]:
    """Spawn `argv` with env knobs, return (last JSON result line, proc).

    The result is None when the child produced no parseable JSON line
    (crash, OOM, import error) — the caller decides whether that is a
    recorded failure or fatal; `proc.stderr` carries the evidence either
    way."""
    proc = subprocess.run(list(argv), env=child_env(overrides, clear_prefixes),
                          capture_output=True, text=True, timeout=timeout)
    return last_json_line(proc.stdout, key=key), proc


def run_self_child(overrides: Dict[str, str], script: Optional[str] = None,
                   clear_prefixes: Sequence[str] = ("BENCH_",),
                   key: Optional[str] = None, timeout: Optional[float] = None):
    """The bench-lane flavor: re-run `script` (default: the calling
    process's entry script, `sys.argv[0]`) under the filtered env."""
    target = os.path.abspath(script if script is not None else sys.argv[0])
    return run_json_child([sys.executable, target], overrides,
                          clear_prefixes=clear_prefixes, key=key,
                          timeout=timeout)
