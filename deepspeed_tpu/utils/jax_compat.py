"""Cross-version JAX API shims.

The framework targets the current `jax.shard_map` spelling; older jaxlibs
(<0.7) ship it as `jax.experimental.shard_map.shard_map` with the
replication check named `check_rep` instead of `check_vma`. Import
`shard_map` from here everywhere so one shim owns the difference.
"""

try:                                      # jax >= 0.7
    from jax import shard_map as _native_shard_map
    shard_map = _native_shard_map
except ImportError:                       # jax < 0.7
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs.setdefault("check_rep", bool(check_vma))
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kwargs)
