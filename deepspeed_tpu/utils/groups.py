"""`deepspeed_tpu.utils.groups` — the reference's process-group bookkeeping
(`deepspeed/utils/groups.py`), mapped onto the global mesh.

The reference materializes torch process groups per parallelism flavor
(`_create_expert_and_data_parallel` etc.) and hands them to collectives. On
TPU a "group" is a tuple of mesh axis names: collectives inside the compiled
program reduce over axes, so this module only answers the bookkeeping
questions (sizes, ranks, axis handles) in the reference's vocabulary.

Reference names keep their leading underscore (MoE client code imports them
that way) with public aliases.
"""

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.utils.logging import logger

_EP_SIZE = None


def initialize(ep_size=1, mpu=None):
    """Reference `groups.initialize(ep_size=...)` (`utils/groups.py:51`):
    record the expert-parallel degree. The actual mesh factoring comes from
    the config's mesh block; this validates consistency when a mesh exists."""
    global _EP_SIZE
    _EP_SIZE = int(ep_size)
    if mesh_mod.has_mesh():
        actual = mesh_mod.axis_size(mesh_mod.EXPERT_AXIS)
        if actual not in (1, _EP_SIZE):
            logger.warning(f"groups.initialize(ep_size={ep_size}) but the mesh "
                           f"expert axis is {actual}; the mesh wins")


def _get_data_parallel_group():
    """Axes forming the data-parallel domain (a 'group handle' here is the
    axis-name tuple accepted by every comm collective)."""
    return mesh_mod.ZERO_AXES


def _get_data_parallel_world_size():
    return mesh_mod.axis_size(mesh_mod.ZERO_AXES)


def _get_data_parallel_rank():
    """Rank within the data-parallel domain: the mesh coordinates of this
    process's first addressable device along ZERO_AXES, flattened in axis
    order. Falls back to the process index when no mesh exists (then the
    process IS the data-parallel unit)."""
    import jax
    if not mesh_mod.has_mesh():
        return jax.process_index()
    mesh = mesh_mod.get_mesh()
    local = [d for d in mesh.devices.flat if d.process_index == jax.process_index()]
    if not local:
        return jax.process_index()
    device = min(local, key=lambda d: d.id)
    # coordinates of `device` in the mesh array
    import numpy as np
    idx = np.argwhere(mesh.devices == device)[0]
    coord = dict(zip(mesh.axis_names, idx))
    rank = 0
    for ax in mesh_mod.ZERO_AXES:
        if ax in coord:
            rank = rank * mesh.shape[ax] + int(coord[ax])
    return rank


def _get_model_parallel_group():
    return (mesh_mod.TENSOR_AXIS,)


def _get_model_parallel_world_size():
    return mesh_mod.axis_size(mesh_mod.TENSOR_AXIS)


def _get_expert_parallel_group(group_name=None):
    return (mesh_mod.EXPERT_AXIS,)


def _get_expert_parallel_world_size(group_name=None):
    return mesh_mod.axis_size(mesh_mod.EXPERT_AXIS)


def _get_expert_data_parallel_group(group_name=None):
    """Data-parallel replication domain of the expert weights (the axes NOT
    carrying experts within the ZeRO domain)."""
    return tuple(a for a in mesh_mod.ZERO_AXES if a != mesh_mod.EXPERT_AXIS)


def _get_expert_data_parallel_world_size(group_name=None):
    return mesh_mod.axis_size(_get_expert_data_parallel_group())


def _get_sequence_parallel_group():
    return (mesh_mod.SEQ_AXIS,)


def _get_sequence_parallel_world_size():
    return mesh_mod.axis_size(mesh_mod.SEQ_AXIS)


def _get_world_group():
    return mesh_mod.ALL_AXES


# public aliases
get_data_parallel_group = _get_data_parallel_group
get_data_parallel_world_size = _get_data_parallel_world_size
get_model_parallel_world_size = _get_model_parallel_world_size
get_expert_parallel_group = _get_expert_parallel_group
get_expert_parallel_world_size = _get_expert_parallel_world_size
get_sequence_parallel_world_size = _get_sequence_parallel_world_size
