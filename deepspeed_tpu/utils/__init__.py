from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer
from deepspeed_tpu.utils.memory import see_memory_usage
from deepspeed_tpu.utils.tree import (
    tree_size_bytes,
    tree_num_params,
    tree_cast,
    tree_zeros_like,
)
