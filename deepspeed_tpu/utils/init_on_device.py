"""Abstract ("meta") initialization and direct-to-sharded materialization.

Reference: `OnDevice` (`deepspeed/utils/init_on_device.py`) constructs modules
on the meta device (shapes only); `zero.Init` (`zero/partition_parameters.py:723`)
partitions parameters *at construction* so the full model never materializes on
one device.

TPU-native: both collapse into two primitives —
  * `abstract_init(init_fn, *args)` → pytree of jax.ShapeDtypeStruct via
    `jax.eval_shape` (zero memory, the "meta device");
  * `materialize_sharded(init_fn, shardings, *args)` → jit with out_shardings:
    XLA materializes each parameter shard directly on its owner device, so a
    model larger than one chip's HBM initializes without ever being gathered —
    exactly zero.Init's contract, minus the module-patching machinery.
"""

import jax


def abstract_init(init_fn, *args, **kwargs):
    """Shapes/dtypes of `init_fn(*args)` without allocating (the meta device)."""
    return jax.eval_shape(init_fn, *args, **kwargs)


def materialize_sharded(init_fn, shardings, *args, **kwargs):
    """Run `init_fn` with every output leaf placed per `shardings` at creation.

    `shardings`: pytree of NamedSharding matching init_fn's output (e.g. from
    ZeroShardingPolicy.param_shardings over abstract_init's result).
    """
    # dstpu: ignore[DT004]: one-shot sharded-init program — runs once per engine build, sharded placement at creation is the point
    return jax.jit(init_fn, out_shardings=shardings)(*args, **kwargs)


class OnDevice:
    """Reference-shaped context manager.

    with OnDevice(dtype=jnp.bfloat16, device="meta"):
        shapes = builder()          # builder returns abstract shapes

    On TPU the context itself needs no patching — it simply records the target
    and exposes `.abstract` / `.materialize` for the two phases.
    """

    def __init__(self, dtype=None, device="meta", enabled=True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _cast(self, fn):
        if self.dtype is None:
            return fn

        def casted(*a, **kw):
            from deepspeed_tpu.utils.tree import tree_cast
            return tree_cast(fn(*a, **kw), self.dtype)

        return casted

    def abstract(self, init_fn, *args, **kwargs):
        return abstract_init(self._cast(init_fn), *args, **kwargs)

    def materialize(self, init_fn, shardings, *args, **kwargs):
        return materialize_sharded(self._cast(init_fn), shardings, *args, **kwargs)
