"""Rank-filtered logging.

TPU-native analog of the reference's `deepspeed/utils/logging.py` (logger + `log_dist`
which prints only on selected ranks). Process identity comes from `jax.process_index()`
instead of torch.distributed ranks.
"""

import functools
import logging
import os
import sys

LOG_LEVEL = os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper()

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name="deepspeed_tpu", level=None):
    lg = logging.getLogger(name)
    lg.setLevel(level if level is not None else log_levels.get(LOG_LEVEL.lower(), logging.INFO))
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
                datefmt="%Y-%m-%d %H:%M:%S",
            ))
        lg.addHandler(handler)
    return lg


logger = _create_logger()


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log `message` only if this process's index is in `ranks` (or ranks is None/[-1])."""
    rank = _process_index()
    my_turn = ranks is None or -1 in ranks or rank in ranks
    if my_turn:
        logger.log(level, f"[Rank {rank}] {message}")


def print_rank_0(message):
    if _process_index() == 0:
        print(message, flush=True)


def warning_once(message, _seen=set()):
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
