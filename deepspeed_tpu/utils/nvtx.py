"""Profiler range annotations — analog of the reference's nvtx shim
(`deepspeed/utils/nvtx.py` `instrument_w_nvtx`, accelerator
`range_push/range_pop`). On TPU these map to `jax.profiler` trace
annotations, which show up in xprof/TensorBoard traces.

Import-guarded: when `jax.profiler.TraceAnnotation` is unavailable (minimal
environments, stripped jax builds) every entry point is a hard no-op, so the
telemetry span layer (`telemetry/spans.py`) stays safe to call anywhere."""

import contextlib
import functools

try:
    import jax
    _TraceAnnotation = jax.profiler.TraceAnnotation
except Exception:          # pragma: no cover - depends on the environment
    _TraceAnnotation = None

# LIFO of open ranges so range_pop() matches the reference accelerator API
# (`accelerator/abstract_accelerator.py` range_pop takes no arguments).
_RANGE_STACK = []


def range_push(msg):
    """Start a named range (reference accelerator.range_push)."""
    if _TraceAnnotation is None:
        return None
    t = _TraceAnnotation(msg)
    t.__enter__()
    _RANGE_STACK.append(t)
    return t


def range_pop(t=None):
    """End a range started with range_push. With no argument, pops the most
    recently pushed range (reference API); a handle may also be passed."""
    if t is None:
        if not _RANGE_STACK:
            return
        t = _RANGE_STACK.pop()
    else:
        # remove the handle wherever it sits so a later argless pop never
        # exits it a second time
        try:
            _RANGE_STACK.remove(t)
        except ValueError:
            pass
    t.__exit__(None, None, None)


def instrument_w_nvtx(func):
    """Decorator: wrap `func` in a named profiler range (reference
    `utils/nvtx.py:instrument_w_nvtx`); returns `func` unchanged when the
    profiler is unavailable."""
    if _TraceAnnotation is None:
        return func

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with _TraceAnnotation(func.__qualname__):
            return func(*args, **kwargs)

    return wrapped


def annotate(name):
    """Context manager for a named trace range (null when unavailable)."""
    if _TraceAnnotation is None:
        return contextlib.nullcontext()
    return _TraceAnnotation(name)
