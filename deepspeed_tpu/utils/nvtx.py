"""Profiler range annotations — analog of the reference's nvtx shim
(`deepspeed/utils/nvtx.py` `instrument_w_nvtx`, accelerator
`range_push/range_pop`). On TPU these map to `jax.profiler` trace
annotations, which show up in xprof/TensorBoard traces."""

import functools

import jax


def range_push(msg):
    """Start a named range (reference accelerator.range_push)."""
    t = jax.profiler.TraceAnnotation(msg)
    t.__enter__()
    return t


def range_pop(t):
    """End a range started with range_push."""
    t.__exit__(None, None, None)


def instrument_w_nvtx(func):
    """Decorator: wrap `func` in a named profiler range (reference
    `utils/nvtx.py:instrument_w_nvtx`)."""

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(func.__qualname__):
            return func(*args, **kwargs)

    return wrapped


def annotate(name):
    """Context manager for a named trace range."""
    return jax.profiler.TraceAnnotation(name)
