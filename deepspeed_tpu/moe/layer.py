"""Reference import path `deepspeed.moe.layer` (`deepspeed/moe/layer.py:16`)."""

from deepspeed_tpu.parallel.moe import MoE, MoELayer

__all__ = ["MoE", "MoELayer"]
