"""`deepspeed_tpu.moe` — the reference's `deepspeed.moe` import namespace
(`deepspeed/moe/`). The implementation lives in `parallel/moe.py` (expert
sharding over the `expert` mesh axis); this package keeps reference import
paths (`from deepspeed.moe.layer import MoE`) working."""

from deepspeed_tpu.moe import layer
from deepspeed_tpu.parallel.moe import MoE, MoELayer

__all__ = ["MoE", "MoELayer", "layer"]
