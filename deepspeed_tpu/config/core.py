"""Typed configuration system.

TPU-native analog of the reference's `runtime/config.py:686` (`DeepSpeedConfig`) and
`runtime/config_utils.py:16` (`DeepSpeedConfigModel`, the pydantic base with "auto"
fields). We use plain dataclass-style models (no pydantic dependency) with:

  * JSON file or dict input,
  * `"auto"` sentinel resolution,
  * unknown-key warnings (matching the reference's strict-ish behavior),
  * the micro/GAS/global batch-size triad arithmetic
    (reference `runtime/config.py` `_batch_assertion`/`_set_batch_related_parameters`).

Config keys intentionally mirror the reference's JSON schema (`train_batch_size`,
`zero_optimization.stage`, `fp16.enabled`, ...) so reference configs load unchanged;
TPU-specific extensions live under the `"mesh"` block.
"""

import copy
import dataclasses
import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Union

from deepspeed_tpu.utils.logging import logger

AUTO = "auto"


class OffloadDeviceEnum(str, Enum):
    """Reference: `runtime/zero/offload_config.py` OffloadDeviceEnum."""
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


def _is_auto(v):
    return isinstance(v, str) and v == AUTO


@dataclass
class ConfigModel:
    """Base for config blocks: dict construction with unknown-key warnings and
    recursive nesting, mirroring `DeepSpeedConfigModel`."""

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]], path=""):
        d = dict(d or {})
        kwargs = {}
        field_map = {f.name: f for f in dataclasses.fields(cls)}
        for key, value in d.items():
            if key not in field_map:
                logger.warning(f"Config: unknown key '{path}{key}' ignored")
                continue
            f = field_map[key]
            ftype = f.type
            if isinstance(value, dict) and isinstance(ftype, type) and issubclass_safe(ftype, ConfigModel):
                value = ftype.from_dict(value, path=f"{path}{key}.")
            kwargs[key] = value
        return cls(**kwargs)

    def to_dict(self):
        out = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, ConfigModel):
                v = v.to_dict()
            elif isinstance(v, Enum):
                v = v.value
            out[f.name] = v
        return out

    def resolve_auto(self, **defaults):
        for name, value in defaults.items():
            if _is_auto(getattr(self, name, None)):
                setattr(self, name, value)


def issubclass_safe(t, parent):
    try:
        return issubclass(t, parent)
    except TypeError:
        return False


def maybe_unwrap_tuned(d):
    """A dstpu_tune artifact (autotuning/session.py) handed where a config
    dict is expected unwraps to its winner's full merged config — so
    `initialize(config="tuned_config.json")` / `init_inference(config=...)`
    consume the tuner's output directly. Anything else passes through."""
    if isinstance(d, dict) and "dstpu_tune" in d:
        winner = d.get("winner") or {}
        cfg = winner.get("config")
        if not isinstance(cfg, dict):
            raise ValueError(
                "dstpu_tune artifact has no winner config to load (a "
                "--dry-run artifact holds only the prune ledger) — run the "
                "measured stage, or extract a config by hand")
        return copy.deepcopy(cfg)
    return d


# --------------------------------------------------------------------------------------
# Feature blocks
# --------------------------------------------------------------------------------------


@dataclass
class OffloadParamConfig(ConfigModel):
    """Reference: `DeepSpeedZeroOffloadParamConfig` (`runtime/zero/offload_config.py`)."""
    device: str = "none"          # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 10**8
    max_in_cpu: int = 10**9
    pin_memory: bool = False
    # async staging-pool depth (runtime/param_swap.LayerStreamer): layers
    # of weights kept in flight ahead of compute; 0 = blocking baseline,
    # 1 = classic double buffering (docs/offload.md "Staging depth")
    lookahead: int = 1


@dataclass
class OffloadOptimizerConfig(ConfigModel):
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0


@dataclass
class ZeroConfig(ConfigModel):
    """Reference: `DeepSpeedZeroConfig` (`runtime/zero/config.py:81`).

    On TPU, stages are realized as sharding policies over the mesh's combined
    data axes rather than hook-driven partitioning:
      stage 0: params+grads+opt replicated (DP allreduce)
      stage 1: optimizer state sharded
      stage 2: + gradients reduce-scattered into the shard
      stage 3: + parameters sharded (XLA gathers before use)
    """
    stage: int = 0
    contiguous_gradients: bool = True           # accepted; XLA manages layout
    reduce_scatter: bool = True
    reduce_bucket_size: int = 5 * 10**8         # accepted; XLA buckets internally
    allgather_partitions: bool = True
    allgather_bucket_size: int = 5 * 10**8
    overlap_comm: bool = True                   # XLA latency-hiding scheduler
    offload_param: Optional[OffloadParamConfig] = None
    offload_optimizer: Optional[OffloadOptimizerConfig] = None
    sub_group_size: int = 10**9
    stage3_max_live_parameters: int = 10**9
    stage3_max_reuse_distance: int = 10**9
    stage3_prefetch_bucket_size: int = 5 * 10**7
    stage3_param_persistence_threshold: int = 10**5
    stage3_gather_16bit_weights_on_model_save: bool = False
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = 1            # ZeRO++ hpZ: secondary shard group size
    zero_quantized_weights: bool = False        # ZeRO++ qwZ: int8 weight all-gather
    zero_quantized_gradients: bool = False      # ZeRO++ qgZ: int8 grad reduce
    # explicit grad-reduce through the comm facade: one hierarchical
    # reduce per step — plain psum over the fast (ICI) axes, then a
    # transform-compressed 2-hop reduce over the declared slow axis
    # (compressed_comm_axis, default: the outermost data-domain axis).
    # With zero_quantized_gradients the slow hop runs the int8 qgZ wire.
    explicit_grad_reduce: bool = False
    # 1-bit Adam wire: error-feedback sign+scale compression on the slow-axis
    # grad reduce (pairs with the OneBit* optimizers, whose in-optimizer
    # compression is simulated — this knob shrinks the actual wire). Implies
    # explicit_grad_reduce.
    onebit_gradients: bool = False
    compressed_comm_axis: Optional[str] = None  # slow-tier mesh axis for the wire
    mics_shard_size: int = -1                   # MiCS: shard group size (<=0 disabled)
    mics_hierarchical_params_gather: bool = False
    ignore_unused_parameters: bool = True
    param_persistence_threshold: Optional[int] = None

    def __post_init__(self):
        if isinstance(self.offload_param, dict):
            self.offload_param = OffloadParamConfig.from_dict(self.offload_param)
        if isinstance(self.offload_optimizer, dict):
            self.offload_optimizer = OffloadOptimizerConfig.from_dict(self.offload_optimizer)
        assert 0 <= self.stage <= 3, f"zero_optimization.stage must be 0-3, got {self.stage}"


@dataclass
class Fp16Config(ConfigModel):
    """Reference: fp16 block (`runtime/config.py`, loss scaler `runtime/fp16/loss_scaler.py`)."""
    enabled: Union[bool, str] = False
    auto_cast: bool = False
    loss_scale: float = 0.0          # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0

    @property
    def dynamic(self):
        return self.loss_scale == 0


@dataclass
class Bf16Config(ConfigModel):
    enabled: Union[bool, str] = False
    # Keep fp32 master weights + fp32 grad accumulation (reference BF16_Optimizer role).
    master_weights: bool = True


@dataclass
class OptimizerConfig(ConfigModel):
    """Reference: optimizer block — {"type": "AdamW", "params": {...}}."""
    type: str = "AdamW"
    params: Dict[str, Any] = field(default_factory=dict)
    legacy_fusion: bool = False


@dataclass
class SchedulerConfig(ConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass
class MeshConfig(ConfigModel):
    """TPU-native extension: logical mesh axis sizes.

    Replaces the reference's process-group plumbing (`deepspeed/utils/groups.py`,
    `runtime/pipe/topology.py`): DP/TP/PP/SP/EP group objects collapse into named mesh
    axes. Sizes of -1 mean "absorb remaining devices" (at most one axis may be -1;
    default: data).
    Axis order is outer→inner = DCN→ICI friendly: pipe, data, zero, expert,
    sequence, tensor.
    """
    data: int = -1
    zero: int = 1     # inner factor of the data domain (MiCS/hpZ sub-group size)
    tensor: int = 1
    pipe: int = 1
    sequence: int = 1
    expert: int = 1
    # devices: total device count override (defaults to jax.device_count())
    devices: Optional[int] = None


@dataclass
class ActivationCheckpointingConfig(ConfigModel):
    """Reference: `runtime/activation_checkpointing/checkpointing.py` config block.
    On TPU this maps to `jax.checkpoint` policies; partitioning/cpu offload map to
    remat policies + host offload of residuals."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU extension: which remat policy to use ("full", "dots", "dots_with_no_batch_dims", "none")
    policy: str = "full"


@dataclass
class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclass
class TelemetryConfig(ConfigModel):
    """Unified telemetry (`deepspeed_tpu/telemetry/`): metrics registry +
    exporters + spans. Opt-in: when disabled (default) the instrumented
    subsystems record nothing and NO files are written. Shared by the train
    config and `TpuInferenceConfig` — the serving scheduler reads the same
    block."""
    enabled: bool = False
    output_path: str = "telemetry"   # dir for <subsystem>.prom/.jsonl/.trace.json
    export_interval: int = 20        # steps between exports (scheduler
                                     # iterations for serving, optimizer steps
                                     # for training)
    prometheus: bool = True          # text-exposition file (atomic rewrite)
    jsonl: bool = True               # append-only log (bin/dstpu_metrics)
    monitor_bridge: bool = True      # flatten snapshots into MonitorMaster
                                     # scalars so TB/WandB/CSV keep working
    chrome_trace: bool = False       # host-side span timeline (Perfetto)
    peak_tflops: float = 0.0         # per-chip peak override for MFU (TFLOPs);
                                     # 0 = auto-detect from the device kind
    measure_program_flops: bool = True  # MFU numerator: cost-analyze the
                                     # compiled step once at first step (XLA's
                                     # exact program flops — an extra one-time
                                     # compile); False = analytic 6N model
                                     # flops (the PaLM MFU convention, free)
    tracing: bool = False            # request-scoped span trees:
                                     # <subsystem>.trace.jsonl (dstpu_trace)
                                     # + a flow-linked chrome trace (Perfetto)
    flight_recorder: bool = False    # bounded ring of scheduling events,
                                     # dumped to <subsystem>.flightrec.*.json
                                     # on replica failure / sentinel trip /
                                     # dump signal
    flight_recorder_events: int = 256  # ring capacity (last-N events kept)
    memscope: bool = False           # HBM memory ledger + OOM forensics
                                     # (telemetry/memscope.py): per-subsystem
                                     # mem/* byte-attribution gauges, a pre-
                                     # flight capacity check at engine build,
                                     # and a ledger+planner+flight dump on
                                     # RESOURCE_EXHAUSTED at the dispatch
                                     # boundaries
    memscope_programs: bool = True   # ledger includes per-program temp/arg
                                     # bytes from XLA memory_analysis() of
                                     # the persistent jitted programs — one
                                     # extra AOT compile per program, lazily
                                     # at first export (the jit CALL caches,
                                     # and so compile_stats(), are untouched)
    memscope_capacity_bytes: int = 0  # per-device HBM capacity override for
                                     # headroom/preflight math; 0 = read
                                     # device.memory_stats()["bytes_limit"]
                                     # (absent on the CPU harness)
    memscope_preflight: str = "warn"  # capacity-planner verdict at engine
                                     # build: "off" | "warn" | "refuse"
                                     # (refuse raises PredictedOOMError
                                     # before anything compiles)


@dataclass
class EigenvalueConfig(ConfigModel):
    """Reference: eigenvalue block (`runtime/config.py:545`) — curvature
    estimation driving the MoQ quantization schedule."""
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "blocks"
    layer_num: int = 0


@dataclass
class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


@dataclass
class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


@dataclass
class CsvConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


@dataclass
class CommsLoggerConfig(ConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = field(default_factory=list)


@dataclass
class PipelineConfig(ConfigModel):
    """Pipeline-parallel engine knobs (reference: `runtime/pipe/` + engine config)."""
    stages: Union[int, str] = AUTO
    partition_method: str = "parameters"   # parameters | uniform | type:<regex>
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_schedule: str = "1f1b"            # 1f1b | gpipe | interleaved


@dataclass
class GradientCompressionConfig(ConfigModel):
    """1-bit/compressed-optimizer analog (reference `runtime/fp16/onebit/`).
    TPU realization: error-feedback + int8/1-bit quantized collectives."""
    enabled: bool = False
    bits: int = 8
    error_feedback: bool = True
    warmup_steps: int = 100


@dataclass
class AutotuningConfig(ConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    max_train_micro_batch_size_per_gpu: int = 1024
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50


@dataclass
class ElasticityConfig(ConfigModel):
    """Reference: `elasticity/config.py` — admissible world sizes from batch divisibility."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    version: float = 0.1


@dataclass
class DataEfficiencyConfig(ConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = field(default_factory=dict)
    data_routing: Dict[str, Any] = field(default_factory=dict)


@dataclass
class DataTypesConfig(ConfigModel):
    """Reference: `runtime/config.py:876` data_types block — the gradient
    ACCUMULATOR dtype for gas > 1. Default fp32 (exact accumulation across
    micro-batches); "bf16" halves the accumulator's HBM footprint and RMW
    traffic at ~3-decimal-digit accumulation precision — the knob that makes
    gas viable when fp32 accumulators do not fit next to the model state."""
    grad_accum_dtype: Optional[str] = None   # None/"fp32" | "bf16" | "fp16"


@dataclass
class ProgressiveLayerDropConfig(ConfigModel):
    """Reference: `runtime/config.py` progressive_layer_drop block +
    `runtime/progressive_layer_drop.py` (theta schedule)."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


@dataclass
class CheckpointConfig(ConfigModel):
    """Reference: checkpoint block + `runtime/checkpoint_engine/`."""
    tag_validation: str = "Warn"     # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = field(default_factory=dict)
    # TPU extension: engine = "orbax" (async, default) or "numpy" (simple .npz files)
    engine: str = "orbax"
    async_save: bool = False
    # crash-safety knobs (docs/fault_tolerance.md):
    # keep_last_n: retention — committed tags beyond the newest N are GC'd
    # after each successful commit (0 = keep everything); uncommitted/legacy
    # dirs are never retention-deleted
    keep_last_n: int = 0
    # verify_checksums: load-time deep (crc32) verification of every file the
    # manifest records; False checks existence+size only (large checkpoints)
    verify_checksums: bool = True


@dataclass
class FaultToleranceConfig(ConfigModel):
    """Training-loop bad-state sentinels + in-process rollback
    (`runtime/sentinel.py`, docs/fault_tolerance.md). Opt-in: the sentinel
    reads the loss on the host every step, which costs a device sync."""
    enabled: bool = False
    nonfinite_budget: int = 3        # consecutive non-finite losses tolerated
    overflow_budget: int = 50        # consecutive fp16 overflow skip-steps
    loss_spike_window: int = 0       # rolling-median window (0 = disabled)
    loss_spike_factor: float = 10.0
    loss_spike_patience: int = 3
    # rollback to the last good checkpoint in-process instead of raising
    # BadStateError (requires a prior save_checkpoint/load_checkpoint so the
    # engine knows the checkpoint root)
    auto_rollback: bool = True
    max_rollbacks: int = 3           # per-process budget before raising anyway


@dataclass
class MoEConfig(ConfigModel):
    """Expert-parallel knobs; layer-level options live on the MoE layer itself
    (reference `deepspeed/moe/layer.py:16`)."""
    enabled: bool = False
    ep_size: int = 1
    moe_param_groups: bool = True
    use_residual: bool = False


@dataclass
class CompressionConfig(ConfigModel):
    """Reference: `deepspeed/compression/config.py` — accepted and dispatched to
    deepspeed_tpu.compression."""
    weight_quantization: Dict[str, Any] = field(default_factory=dict)
    activation_quantization: Dict[str, Any] = field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = field(default_factory=dict)
    row_pruning: Dict[str, Any] = field(default_factory=dict)
    head_pruning: Dict[str, Any] = field(default_factory=dict)
    channel_pruning: Dict[str, Any] = field(default_factory=dict)
    layer_reduction: Dict[str, Any] = field(default_factory=dict)


# --------------------------------------------------------------------------------------
# Root config
# --------------------------------------------------------------------------------------


@dataclass
class TpuTrainConfig(ConfigModel):
    """Root training config — analog of `DeepSpeedConfig` (`runtime/config.py:686`)."""

    train_batch_size: Union[int, str, None] = None
    train_micro_batch_size_per_gpu: Union[int, str, None] = None
    gradient_accumulation_steps: Union[int, str, None] = None

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None

    fp16: Fp16Config = field(default_factory=Fp16Config)
    bf16: Bf16Config = field(default_factory=Bf16Config)
    zero_optimization: ZeroConfig = field(default_factory=ZeroConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    activation_checkpointing: ActivationCheckpointingConfig = field(default_factory=ActivationCheckpointingConfig)
    flops_profiler: FlopsProfilerConfig = field(default_factory=FlopsProfilerConfig)
    eigenvalue: EigenvalueConfig = field(default_factory=EigenvalueConfig)
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = field(default_factory=WandbConfig)
    csv_monitor: CsvConfig = field(default_factory=CsvConfig)
    comms_logger: CommsLoggerConfig = field(default_factory=CommsLoggerConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    compression_training: CompressionConfig = field(default_factory=CompressionConfig)
    gradient_compression: GradientCompressionConfig = field(default_factory=GradientCompressionConfig)
    autotuning: AutotuningConfig = field(default_factory=AutotuningConfig)
    elasticity: ElasticityConfig = field(default_factory=ElasticityConfig)
    data_efficiency: DataEfficiencyConfig = field(default_factory=DataEfficiencyConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = field(
        default_factory=ProgressiveLayerDropConfig)
    data_types: DataTypesConfig = field(default_factory=DataTypesConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    fault_tolerance: FaultToleranceConfig = field(default_factory=FaultToleranceConfig)
    moe: MoEConfig = field(default_factory=MoEConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)

    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    communication_data_type: Optional[str] = None
    sparse_gradients: bool = False
    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False
    zero_allow_untested_optimizer: bool = True
    zero_force_ds_cpu_optimizer: bool = False
    disable_allgather: bool = False
    seed: int = 1234

    # TPU extensions
    param_dtype: str = AUTO          # resolved from fp16/bf16 blocks
    matmul_precision: str = "default"  # jax.default_matmul_precision
    remat: bool = False              # shorthand: activation_checkpointing.policy applied to blocks

    def __post_init__(self):
        for name, cls_ in (("optimizer", OptimizerConfig), ("scheduler", SchedulerConfig)):
            v = getattr(self, name)
            if isinstance(v, dict):
                setattr(self, name, cls_.from_dict(v, path=name + "."))
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, dict) and issubclass_safe(f.type, ConfigModel):
                setattr(self, f.name, f.type.from_dict(v, path=f.name + "."))

    # ---------------- batch triad ----------------

    def resolve_batch_sizes(self, dp_world_size: int):
        """Resolve the (global, micro, GAS) triad given the data-parallel world size.

        Mirrors the reference's `_set_batch_related_parameters` / `_batch_assertion`
        (`runtime/config.py`): any two determine the third; one given assumes the
        others are 1; none given defaults micro=1, gas=1.
        """
        tb = self.train_batch_size if not _is_auto(self.train_batch_size) else None
        mb = self.train_micro_batch_size_per_gpu if not _is_auto(self.train_micro_batch_size_per_gpu) else None
        gas = self.gradient_accumulation_steps if not _is_auto(self.gradient_accumulation_steps) else None

        if tb is not None and mb is not None and gas is not None:
            pass
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            mb = tb // (gas * dp_world_size)
        elif mb is not None and gas is not None:
            tb = mb * gas * dp_world_size
        elif tb is not None:
            gas = 1
            mb = tb // dp_world_size
        elif mb is not None:
            gas = 1
            tb = mb * dp_world_size
        else:
            mb, gas = 1, 1
            tb = dp_world_size

        assert tb == mb * gas * dp_world_size, (
            f"batch size triad inconsistent: train_batch_size={tb} != "
            f"micro({mb}) * gas({gas}) * dp_world({dp_world_size})")
        assert tb > 0 and mb > 0 and gas > 0, "batch sizes must be positive"

        self.train_batch_size = int(tb)
        self.train_micro_batch_size_per_gpu = int(mb)
        self.gradient_accumulation_steps = int(gas)
        return tb, mb, gas

    # ---------------- precision ----------------

    @property
    def fp16_enabled(self):
        return bool(self.fp16.enabled) and self.fp16.enabled != AUTO

    @property
    def bf16_enabled(self):
        return bool(self.bf16.enabled) and self.bf16.enabled != AUTO

    def compute_dtype(self):
        import jax.numpy as jnp
        if self.fp16_enabled:
            return jnp.float16
        if self.bf16_enabled:
            return jnp.bfloat16
        if self.param_dtype not in (AUTO, None):
            return jnp.dtype(self.param_dtype)
        return jnp.float32

    # ---------------- construction ----------------

    @classmethod
    def load(cls, config: Union[str, Dict[str, Any], "TpuTrainConfig", None]):
        if config is None:
            config = {}
        if isinstance(config, TpuTrainConfig):
            return config
        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        assert isinstance(config, dict), f"config must be dict/path/TpuTrainConfig, got {type(config)}"
        config = copy.deepcopy(maybe_unwrap_tuned(config))
        return cls.from_dict(config)

    def dump(self):
        return json.dumps(self.to_dict(), indent=2, default=str)
