from deepspeed_tpu.config.core import (
    TpuTrainConfig,
    ConfigModel,
    AUTO,
    ZeroConfig,
    Fp16Config,
    Bf16Config,
    MeshConfig,
    OffloadDeviceEnum,
)
