"""Measured trials — short runs scoring the planner's survivors.

The measured stage replays ONE deterministic ragged trace (seeded lengths
and tokens, `ragged_trace`) through a serving engine built from the
candidate's config, or times a few training steps, and returns a plain
JSON-able measurement record the objective scores.

Determinism is the contract the reproducible-artifact promise rests on:
serving trials drive an injectable `VirtualClock` that advances one tick
per scheduler sync, so every latency histogram — and therefore every SLO
score, and therefore the winner — is a pure function of (trace seed,
candidate config), byte-identical across runs and machines. `clock="wall"`
swaps in `time.monotonic` for real-hardware tuning, same code path.

Trials can run in-process (the CPU-harness default: one engine at a time,
torn down between trials) or in a child process via `run_trial_child` —
the bench-lane `BENCH_*_CHILD` recipe (`utils/subproc.py`), which a crash
or real OOM cannot take the tuner down with.
"""

import copy
import gc
import json
import sys
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.autotuning.space import apply_overrides
from deepspeed_tpu.utils.subproc import run_json_child

TRIAL_ENV = "DSTPU_TUNE_TRIAL"       # the child reads its spec from here


class VirtualClock:
    """Deterministic engine clock: one tick per scheduler sync. With the
    stamps in "seconds" and one sync ticking 1e-3, the serving latency
    histograms read in SYNCS when formatted as milliseconds — TTFT p99 of
    7.0 means the 99th-percentile request saw its first token 7 syncs
    after arrival."""

    TICK = 1e-3

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def tick(self):
        self.t += self.TICK


def ragged_trace(seed: int = 0, n_requests: int = 12, min_len: int = 2,
                 max_len: int = 48, max_new: int = 12,
                 vocab: int = 256) -> Dict[str, Any]:
    """A serving workload as a JSON-able spec: seeded ragged prompt
    lengths (and, derived from the same seed, the prompt tokens —
    `trace_requests` materializes them). A shared prefix rides the first
    third of the requests so prefix caching has something to win on."""
    rng = np.random.default_rng(int(seed))
    lens = [int(rng.integers(min_len, max_len + 1))
            for _ in range(int(n_requests))]
    return {"seed": int(seed), "n_requests": int(n_requests),
            "lens": lens, "max_new": int(max_new), "vocab": int(vocab),
            "shared_prefix": int(min_len)}


def trace_requests(trace: Dict[str, Any]) -> List[Any]:
    """Materialize the trace's `Request` list (deterministic from the
    spec). `stop_on_eos=False`: every request generates its full budget,
    so the token count — the throughput numerator — is config-invariant
    and objectives compare time, not luck."""
    from deepspeed_tpu.inference.scheduler import Request
    rng = np.random.default_rng(int(trace["seed"]))
    vocab = int(trace["vocab"])
    prefix = rng.integers(0, vocab, (int(trace.get("shared_prefix", 0)),))
    reqs = []
    for i, length in enumerate(trace["lens"]):
        body = rng.integers(0, vocab, (int(length),)).astype(np.int32)
        if trace.get("shared_prefix") and i < len(trace["lens"]) // 3:
            body[:len(prefix)] = prefix
        reqs.append(Request(uid=i, tokens=body,
                            max_new_tokens=int(trace["max_new"]),
                            stop_on_eos=False))
    return reqs


def _merged_config(base_config, overrides, telemetry):
    cfg = copy.deepcopy(dict(base_config or {}))
    apply_overrides(cfg, dict(overrides or {}))
    if telemetry and "telemetry" not in cfg:
        # registry-only: histograms exist, no files are written
        cfg["telemetry"] = {"enabled": True, "prometheus": False,
                            "jsonl": False, "monitor_bridge": False}
    return cfg


def measure_serving(spec_factory, base_config: Dict[str, Any],
                    overrides: Dict[str, Any], trace: Dict[str, Any],
                    clock: str = "virtual", draft_factory=None,
                    ) -> Dict[str, Any]:
    """One serving trial: build an engine from base_config+overrides,
    replay the trace, return the measurement record. Never raises for a
    config-shaped failure — the record carries ok=False and the error
    text (the tuner maps it to infeasible)."""
    from deepspeed_tpu.inference.engine import init_inference
    cfg = _merged_config(base_config, overrides, telemetry=True)
    vc = VirtualClock() if clock == "virtual" else None
    engine = serving = None
    try:
        engine = init_inference(model=spec_factory(), config=cfg)
        draft_spec = draft_factory() if (
            draft_factory is not None and
            str(cfg.get("serving", {}).get("spec_decode", {})
                .get("drafter", "off")) == "model") else None
        serving = engine.serving(draft_spec=draft_spec,
                                 clock=(vc if vc is not None else None))
        for r in trace_requests(trace):
            serving.submit(r)
        t0 = time.perf_counter()
        done: Dict[Any, Any] = {}
        while serving.queue or serving.num_active:
            before = (serving.prefill_chunks, serving.decode_steps,
                      len(serving.queue))
            if vc is not None:
                vc.tick()
            for c in serving.step():
                done[c.uid] = c
            after = (serving.prefill_chunks, serving.decode_steps,
                     len(serving.queue))
            if after == before:
                raise RuntimeError("serving trial made no progress")
        wall_s = time.perf_counter() - t0
        generated = int(sum(len(c.tokens) for c in done.values()))
        elapsed = float(vc.t) if vc is not None else wall_s
        rec = {"ok": True, "kind": "serving",
               "generated_tokens": generated,
               "syncs": int(serving.steps),
               "elapsed": elapsed, "wall_s": wall_s,
               "tokens_per_time": generated / max(elapsed, 1e-9),
               "latency": serving.latency_snapshot(),
               "compile_stats": serving.compile_stats()}
        stats = serving.stats()
        if "prefix_cache" in stats:
            rec["prefix_cache"] = {
                "hit_tokens": stats["prefix_cache"]["hit_tokens"]}
        if "spec_decode" in stats:
            rec["spec_decode"] = {
                "acceptance_rate": stats["spec_decode"]["acceptance_rate"]}
        return rec
    except Exception as e:
        return {"ok": False, "kind": "serving",
                "error": f"{type(e).__name__}: {str(e)[:300]}"}
    finally:
        del serving, engine
        gc.collect()


def measure_training(model_factory, batch_factory,
                     base_config: Dict[str, Any], overrides: Dict[str, Any],
                     steps: int = 3, warmup: int = 1) -> Dict[str, Any]:
    """One training trial: a few timed steps with an honest scalar-readback
    fence (the seed Autotuner's measurement, behind the same record
    contract as the serving trial)."""
    import deepspeed_tpu
    from deepspeed_tpu.comm import mesh as mesh_mod
    cfg = _merged_config(base_config, overrides, telemetry=False)
    mesh_mod._CURRENT_MESH = None
    mesh_mod._CURRENT_SPEC = None
    engine = None
    try:
        engine, _, _, _ = deepspeed_tpu.initialize(model=model_factory(),
                                                   config=cfg)
        batch = batch_factory(engine.train_batch_size())
        loss = None
        for _ in range(max(0, int(warmup))):
            loss = engine.train_batch(batch)
        if loss is not None:
            float(loss)
        t0 = time.perf_counter()
        for _ in range(max(1, int(steps))):
            loss = engine.train_batch(batch)
        float(loss)
        dt = (time.perf_counter() - t0) / max(1, int(steps))
        return {"ok": True, "kind": "train", "step_ms": dt * 1e3,
                "samples_per_sec": engine.train_batch_size() / dt}
    except Exception as e:
        return {"ok": False, "kind": "train",
                "error": f"{type(e).__name__}: {str(e)[:300]}"}
    finally:
        del engine
        gc.collect()


def run_trial_child(spec: Dict[str, Any],
                    timeout: Optional[float] = None) -> Dict[str, Any]:
    """Run one trial in a child process (`python -m
    deepspeed_tpu.autotuning.trial` reading `DSTPU_TUNE_TRIAL`): the
    bench-lane subprocess recipe, so a segfault or a real device OOM
    costs one trial, not the tuner. Only specs the trial module can
    reconstruct from JSON are supported (the built-in demo model zoo —
    see `trial.py`); in-process measurement has no such limit."""
    rec, proc = run_json_child(
        [sys.executable, "-m", "deepspeed_tpu.autotuning.trial"],
        {TRIAL_ENV: json.dumps(spec, sort_keys=True)},
        clear_prefixes=("BENCH_", "DSTPU_TUNE_"), key="ok",
        timeout=timeout)
    if rec is None:
        return {"ok": False, "kind": spec.get("kind", "?"),
                "error": f"trial child produced no result "
                         f"(rc={proc.returncode}): "
                         f"{(proc.stderr or '').strip()[-300:]}"}
    return rec
