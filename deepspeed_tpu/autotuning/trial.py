"""Trial child entry — `python -m deepspeed_tpu.autotuning.trial`.

The measured stage's subprocess half of the bench-lane recipe
(`utils/subproc.py`): the parent (`measure.run_trial_child`) puts a JSON
trial spec in `DSTPU_TUNE_TRIAL`, this module reconstructs the model,
runs ONE measurement, and prints the result record as the last stdout
line. A crash, a real device OOM, or an import error in here costs the
tuner one recorded failure, never the session.

Only models this module can rebuild from JSON are supported — the
built-in demo zoo (`"model": {"kind": "tiny_gpt", "cfg": {...}}`, a
`GPTConfig` built from plain fields). Arbitrary model factories tune
in-process instead (`TuneSession` with a bound `measure_fn`).
"""

import json
import os
import sys


def _build_spec(model: dict):
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt import GPTConfig, make_gpt_decode_model
    kind = model.get("kind", "tiny_gpt")
    if kind != "tiny_gpt":
        raise ValueError(f"trial child cannot rebuild model kind {kind!r} "
                         f"— tune in-process with a bound measure_fn")
    cfg_d = dict(model.get("cfg", {}))
    cfg_d["dtype"] = jnp.dtype(cfg_d.get("dtype", "float32"))
    cfg_d.setdefault("remat", False)
    cfg = GPTConfig(**cfg_d)
    return make_gpt_decode_model(cfg=cfg, name=model.get("name", "tuned"))


def main() -> int:
    from deepspeed_tpu.autotuning.measure import (TRIAL_ENV,
                                                  measure_serving)
    raw = os.environ.get(TRIAL_ENV)
    if not raw:
        print(json.dumps({"ok": False,
                          "error": f"no {TRIAL_ENV} in the environment"}))
        return 2
    spec = json.loads(raw)
    if spec.get("kind", "serving") != "serving":
        print(json.dumps({"ok": False,
                          "error": "trial child runs serving trials only"}))
        return 2
    from deepspeed_tpu.comm import mesh as mesh_mod
    from deepspeed_tpu.config.core import MeshConfig
    mesh_mod._CURRENT_MESH = None
    mesh_mod._CURRENT_SPEC = None
    mesh_mod.init_mesh(MeshConfig(data=1, tensor=1, sequence=1, expert=1,
                                  pipe=1))
    rec = measure_serving(lambda: _build_spec(spec.get("model", {})),
                          spec.get("base_config", {}),
                          spec.get("overrides", {}),
                          spec["trace"],
                          clock=spec.get("clock", "virtual"))
    print(json.dumps(rec, sort_keys=True, default=str))
    return 0 if rec.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
