"""TuneSession — the whole-stack tuning pipeline, and its artifact.

One session runs the three stages in order over a `SearchSpace`:
constraint refusals (the stack's loud ValueErrors, evaluated symbolically),
planner pruning (memscope's analytic memory plans — predicted OOM and
low-headroom candidates never construct anything), and the measured stage
(the seed GridSearch/Random/ModelBased tuners re-targeted: survivors are
their experiment list, a short trace replay is their `run_fn`). A baseline
measurement of the UNMODIFIED base config on the same trace anchors the
winner's claim — "beats the stack defaults" is in the artifact, not in a
README sentence.

The artifact is the deliverable: one sorted-keys JSON document holding the
search space, the full prune ledger, every trial's measurement, the
baseline, the winner (overrides + the full merged config `initialize()` /
`init_inference()` consume directly — `load_tuned_config` / the config
loaders unwrap it), and an environment fingerprint. No timestamps, no
floats from wall clocks (virtual-clock trials): two runs with the same
seed and trace serialize byte-identically.
"""

import copy
import hashlib
import json
import pathlib
import sys
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.autotuning.planner import ledger_counts, prune
from deepspeed_tpu.autotuning.space import (ModelProfile, SearchSpace,
                                            apply_overrides)
from deepspeed_tpu.autotuning.objectives import Objective, make_objective
from deepspeed_tpu.autotuning.tuner import make_tuner
from deepspeed_tpu.utils.logging import logger

ARTIFACT_MARKER = "dstpu_tune"       # top-level key marking a tuned artifact
ARTIFACT_VERSION = 1

# the tune/* counters the session emits through the registry; recorded via
# one f-string loop, so analysis/rules_catalog.py enumerates THIS tuple —
# growing it grows the docs/profiling.md catalog check automatically
TUNE_COUNTERS = ("candidates", "constraint_refused", "planner_refused",
                 "planner_kept", "trials", "trial_failures")

# measurement keys that vary run-to-run even under the virtual clock
# (host timing); stripped from artifact records so reproducibility is
# byte-exact, kept in the records handed back to callers
_VOLATILE_KEYS = ("wall_s",)


def environment_fingerprint() -> Dict[str, Any]:
    """Where the measurements came from — enough to refuse (or warn on)
    replaying a tuned artifact somewhere it wasn't tuned. Deliberately
    time-free: the fingerprint identifies the environment, not the run."""
    import jax
    import deepspeed_tpu
    fp = {"platform": jax.default_backend(),
          "device_count": jax.device_count(),
          "device_kind": (jax.devices()[0].device_kind
                          if jax.devices() else "?"),
          "jax": jax.__version__,
          "deepspeed_tpu": getattr(deepspeed_tpu, "__version__", "0"),
          "python": "%d.%d" % sys.version_info[:2]}
    blob = json.dumps(fp, sort_keys=True)
    fp["sha256"] = hashlib.sha256(blob.encode()).hexdigest()[:16]
    return fp


def artifact_json(artifact: Dict[str, Any]) -> str:
    """THE serialization: sorted keys, fixed indent, trailing newline.
    Byte-identical artifacts are an acceptance criterion, so there is
    exactly one way to write one."""
    return json.dumps(artifact, sort_keys=True, indent=2,
                      default=str) + "\n"


def load_tuned_config(artifact, check_env: bool = False) -> Dict[str, Any]:
    """The winner's full config dict out of an artifact (path, JSON text,
    or dict). `check_env=True` refuses an artifact fingerprinted on a
    different platform/device-count — measured knobs don't transfer."""
    if isinstance(artifact, (str, pathlib.Path)):
        p = pathlib.Path(artifact)
        text = p.read_text() if p.exists() else str(artifact)
        artifact = json.loads(text)
    if not isinstance(artifact, dict) or ARTIFACT_MARKER not in artifact:
        raise ValueError("not a dstpu_tune artifact (no "
                         f"'{ARTIFACT_MARKER}' marker)")
    if check_env:
        import jax
        env = artifact.get("environment", {})
        here = (jax.default_backend(), jax.device_count())
        there = (env.get("platform"), env.get("device_count"))
        if there != (None, None) and here != there:
            raise ValueError(
                f"tuned artifact was measured on platform="
                f"{there[0]} x{there[1]}, this is {here[0]} x{here[1]} — "
                f"re-tune (or load with check_env=False)")
    return copy.deepcopy(artifact["winner"]["config"])


class TuneSession:
    """One tuning run: space -> constraints -> planner -> measurements ->
    artifact. `measure_fn(overrides) -> record` is the only harness
    dependency (bind a trace + model factory with `functools.partial` or
    use the CLI's built-ins), so train and serving tune identically."""

    def __init__(self, space: SearchSpace, objective,
                 measure_fn: Callable[[Dict[str, Any]], Dict[str, Any]],
                 profile: ModelProfile,
                 base_config: Optional[Dict[str, Any]] = None,
                 capacity_bytes: int = 0, min_headroom_frac: float = 0.0,
                 n_devices: int = 1, tuner_type: str = "gridsearch",
                 seed: int = 0, max_trials: Optional[int] = None,
                 early_stopping: Optional[int] = None,
                 trace: Optional[Dict[str, Any]] = None,
                 telemetry=None):
        self.space = space
        self.objective: Objective = make_objective(objective)
        self.measure_fn = measure_fn
        self.profile = profile
        self.base_config = copy.deepcopy(dict(base_config or {}))
        self.capacity_bytes = int(capacity_bytes)
        self.min_headroom_frac = float(min_headroom_frac)
        self.n_devices = int(n_devices)
        self.tuner_type = tuner_type
        self.seed = int(seed)
        self.max_trials = max_trials
        self.early_stopping = early_stopping
        self.trace = trace
        self.telemetry = telemetry
        self.trials: List[Dict[str, Any]] = []
        self._baseline: Optional[Dict[str, Any]] = None

    # ---- stages ------------------------------------------------------

    def _score(self, record: Dict[str, Any]) -> Optional[float]:
        if not record or not record.get("ok"):
            return None
        return float(self.objective.score(record))

    def _run_trial(self, overrides: Dict[str, Any]) -> Optional[float]:
        record = self.measure_fn(dict(overrides))
        score = self._score(record)
        self.trials.append({"overrides": dict(overrides),
                            "measurement": record,
                            "objective": score})
        return score

    def run(self, dry_run: bool = False) -> Dict[str, Any]:
        """The pipeline. `dry_run=True` stops after the planner stage —
        the ledger (and its counts) is the artifact's payload, with no
        winner; nothing is allocated or compiled at all."""
        self.trials = []
        self._baseline = None
        survivors, ledger = prune(
            self.space, self.profile, self.base_config,
            capacity_bytes=self.capacity_bytes,
            min_headroom_frac=self.min_headroom_frac,
            n_devices=self.n_devices)
        counts = ledger_counts(ledger)
        logger.info(
            f"dstpu_tune: {counts['candidates']} candidates -> "
            f"{counts['kept']} survive "
            f"({counts['constraint_refused']} constraint-refused, "
            f"{counts['planner_refused']} planner-refused) with zero "
            f"allocations/compiles")

        best_exp = best_val = baseline = None
        if not dry_run and survivors:
            tuner_kw = {}
            if self.tuner_type in ("random", "model_based"):
                tuner_kw["seed"] = self.seed
            tuner = make_tuner(self.tuner_type, survivors, self._run_trial,
                               **tuner_kw)
            best_exp, best_val = tuner.tune(
                n_trials=self.max_trials,
                early_stopping=self.early_stopping)
            # the stack-defaults anchor, on the same trace: an artifact
            # that cannot show its winner beating {} is not a win
            baseline_rec = self.measure_fn({})
            baseline = {"overrides": {},
                        "measurement": self._strip(baseline_rec),
                        "objective": self._score(baseline_rec)}
            self._baseline = baseline
        return self._artifact(ledger, counts, best_exp, best_val, baseline,
                              dry_run)

    # ---- artifact ----------------------------------------------------

    @staticmethod
    def _strip(record):
        if not isinstance(record, dict):
            return record
        return {k: v for k, v in record.items()
                if k not in _VOLATILE_KEYS}

    def _artifact(self, ledger, counts, best_exp, best_val, baseline,
                  dry_run) -> Dict[str, Any]:
        winner = None
        if best_exp is not None:
            winner = {"overrides": dict(best_exp),
                      "objective": best_val,
                      "config": apply_overrides(
                          copy.deepcopy(self.base_config), best_exp)}
        art = {
            ARTIFACT_MARKER: ARTIFACT_VERSION,
            "kind": self.space.kind,
            "space": self.space.to_dict(),
            "objective": self.objective.describe(),
            "base_config": self.base_config,
            "profile": self.profile.to_dict(),
            "capacity_bytes": self.capacity_bytes,
            "min_headroom_frac": self.min_headroom_frac,
            "seed": self.seed,
            "tuner_type": self.tuner_type,
            "trace": self.trace,
            "prune_ledger": {"counts": counts,
                             "entries": [e.to_dict() for e in ledger]},
            "trials": [{**t, "measurement": self._strip(t["measurement"])}
                       for t in self.trials],
            "baseline": baseline,
            "winner": winner,
            "dry_run": bool(dry_run),
            "environment": environment_fingerprint(),
        }
        self._export_telemetry(counts)
        return art

    def _export_telemetry(self, counts):
        tele = self.telemetry
        if tele is None or not getattr(tele, "enabled", False):
            return
        measured = self.trials + ([self._baseline] if self._baseline else [])
        trial_failures = sum(1 for t in measured if t["objective"] is None)
        values = {"candidates": counts["candidates"],
                  "constraint_refused": counts["constraint_refused"],
                  "planner_refused": counts["planner_refused"],
                  "planner_kept": counts["kept"],
                  "trials": len(measured),
                  "trial_failures": trial_failures}
        for name in TUNE_COUNTERS:
            tele.inc(f"tune/{name}", values[name])
        best = max((t["objective"] for t in self.trials
                    if t["objective"] is not None), default=None)
        if best is not None:
            tele.set_gauge("tune/best_objective", best)


def write_artifact(artifact: Dict[str, Any], path) -> pathlib.Path:
    p = pathlib.Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(artifact_json(artifact))
    return p
