"""Experiment tuners for autotuning — grid / random / cost-model-guided.

Reference: `deepspeed/autotuning/tuner/` — `index_based_tuner.py`
(RandomTuner, GridSearchTuner over an experiment list), `model_based_tuner.py`
(ModelBasedTuner guided by a fitted cost model) and `cost_model.py`
(XGBoostCostModel). The TPU build keeps the same tuner protocol but fits a
dependency-free ridge regression on one-hot/numeric experiment features
instead of xgboost — the search spaces here (ZeRO stage × micro-batch ×
offload flags) are small enough that a linear surrogate ranks them well.

Protocol: `run_fn(exp: dict) -> float | None` returns the measured metric
(higher is better; e.g. samples/sec) or None when the config is infeasible
(OOM). `tuner.tune(...)` explores the experiment list and tracks the best.
"""

import random
from typing import Callable, Dict, List, Optional

import numpy as np


class CostModel:
    """Ridge regression over featurized experiment dicts (reference
    `cost_model.py` XGBoostCostModel role)."""

    def __init__(self, l2: float = 1e-3, space: List[Dict] = None):
        """`space`: the full candidate list; fixes the featurization vocabulary
        up front so categorical values unseen in the training observations
        still featurize (and predict) consistently."""
        self.l2 = l2
        self._keys = None
        self._vocab = {}
        self._w = None
        if space:
            self._featurize(space)

    def _featurize(self, exps: List[Dict]):
        if self._keys is None:
            self._keys = sorted({k for e in exps for k in e})
            for k in self._keys:
                vals = {e[k] for e in exps if k in e and not isinstance(e[k], (int, float, bool))}
                if vals:
                    self._vocab[k] = sorted(vals, key=str)
        feats = []
        for e in exps:
            row = []
            for k in self._keys:
                v = e.get(k, 0)
                if k in self._vocab:
                    row.extend(1.0 if v == c else 0.0 for c in self._vocab[k])
                else:
                    row.append(float(v))
            feats.append(row)
        x = np.asarray(feats, np.float64)
        return np.concatenate([x, np.ones((x.shape[0], 1))], axis=1)  # bias col

    def fit(self, exps: List[Dict], y):
        x = self._featurize(exps)
        y = np.asarray(y, np.float64)
        a = x.T @ x + self.l2 * np.eye(x.shape[1])
        self._w = np.linalg.solve(a, x.T @ y)
        return self

    def predict(self, exps: List[Dict]):
        assert self._w is not None, "fit() first"
        return self._featurize(exps) @ self._w


class BaseTuner:
    """Sequential explorer over an experiment list (reference `base_tuner.py`).

    Metric semantics live entirely in `run_fn`: it returns a higher-is-better
    value (negate latencies), or None for infeasible configs."""

    def __init__(self, exps: List[Dict], run_fn: Callable[[Dict], Optional[float]]):
        self.all_exps = list(exps)
        self.remaining = list(exps)
        self.run_fn = run_fn
        self.observed: List[Dict] = []
        self.observed_vals: List[float] = []
        self.best_exp: Optional[Dict] = None
        self.best_metric_val: Optional[float] = None

    def has_next(self):
        return bool(self.remaining)

    def next_batch(self, sample_size=1) -> List[Dict]:
        raise NotImplementedError

    def update(self):
        """Hook after each measured batch (model refit etc.)."""

    def tune(self, sample_size=1, n_trials=None, early_stopping=None):
        """Run up to `n_trials` experiments; stop after `early_stopping`
        consecutive non-improving trials. Returns (best_exp, best_val)."""
        budget = n_trials if n_trials is not None else len(self.all_exps)
        stale = 0
        while self.has_next() and budget > 0:
            batch = self.next_batch(min(sample_size, budget))
            for exp in batch:
                val = self.run_fn(exp)
                budget -= 1
                if val is None:
                    continue
                self.observed.append(exp)
                self.observed_vals.append(float(val))
                if self.best_metric_val is None or val > self.best_metric_val:
                    self.best_exp, self.best_metric_val = exp, float(val)
                    stale = 0
                else:
                    stale += 1
            self.update()
            if early_stopping is not None and stale >= early_stopping:
                break
        return self.best_exp, self.best_metric_val


class GridSearchTuner(BaseTuner):
    """In-order sweep (reference `index_based_tuner.py` GridSearchTuner)."""

    def next_batch(self, sample_size=1):
        batch, self.remaining = (self.remaining[:sample_size],
                                 self.remaining[sample_size:])
        return batch


class RandomTuner(BaseTuner):
    """Uniform random order (reference RandomTuner)."""

    def __init__(self, exps, run_fn, seed=0):
        super().__init__(exps, run_fn)
        self._rng = random.Random(seed)

    def next_batch(self, sample_size=1):
        n = min(sample_size, len(self.remaining))
        picks = self._rng.sample(range(len(self.remaining)), n)
        batch = [self.remaining[i] for i in picks]
        for i in sorted(picks, reverse=True):
            del self.remaining[i]
        return batch


class ModelBasedTuner(BaseTuner):
    """Cost-model-guided search (reference `model_based_tuner.py`): explore
    randomly for `warmup_trials`, then repeatedly fit the cost model on the
    observations and run the highest-predicted remaining candidates."""

    def __init__(self, exps, run_fn, warmup_trials=3, seed=0):
        super().__init__(exps, run_fn)
        self.warmup_trials = warmup_trials
        self._rng = random.Random(seed)
        self._model = None

    def next_batch(self, sample_size=1):
        n = min(sample_size, len(self.remaining))
        if len(self.observed) < self.warmup_trials or self._model is None:
            picks = self._rng.sample(range(len(self.remaining)), n)
        else:
            pred = self._model.predict(self.remaining)
            picks = list(np.argsort(pred)[::-1][:n])
        batch = [self.remaining[i] for i in picks]
        for i in sorted(picks, reverse=True):
            del self.remaining[int(i)]
        return batch

    def update(self):
        if len(self.observed) >= max(2, self.warmup_trials):
            self._model = CostModel(space=self.all_exps).fit(self.observed,
                                                             self.observed_vals)


TUNERS = {
    "gridsearch": GridSearchTuner,
    "random": RandomTuner,
    "model_based": ModelBasedTuner,
}


def make_tuner(tuner_type, exps, run_fn, **kw):
    if tuner_type not in TUNERS:
        raise ValueError(f"unknown tuner '{tuner_type}' (have {sorted(TUNERS)})")
    return TUNERS[tuner_type](exps, run_fn, **kw)
