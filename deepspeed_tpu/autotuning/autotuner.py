"""Autotuning — ZeRO-stage / micro-batch search.

Reference: `deepspeed/autotuning/` (2.7k LoC): model-info profile run, max-mbs
binary search, per-stage experiment grid over a resource pool, xgboost cost
model.

TPU-native: experiments run in-process (no multi-node scheduler needed — one
process drives the chips): for each candidate (zero_stage, micro_batch), build
an engine, time a few steps (honest scalar-readback fence), tear down. Memory
feasibility is probed by compile+run inside try/except (XLA OOMs deterministically
at allocation). Search: binary-search max mbs per stage, then pick by
throughput (metric="throughput") or latency.
"""

import copy
import gc
import json
import pathlib
import time

import numpy as np

from deepspeed_tpu.utils.logging import logger

DEFAULT_STAGES = (0, 1, 2, 3)


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def admissible_mesh_shapes(n_devices, max_tensor=None, max_pipe=None,
                           max_sequence=None):
    """All (data, tensor, sequence, pipe) factorings of `n_devices`.

    On TPU the mesh factoring IS the parallelism config — the knob the
    reference's autotuner never sweeps (its space is ZeRO configs only,
    `autotuning/autotuner.py:404`). Axis caps bound the space: tensor beyond
    one ICI domain or pipe deeper than the layer count are never useful.
    """
    max_tensor = max_tensor or n_devices
    max_pipe = max_pipe or n_devices
    max_sequence = max_sequence or n_devices
    shapes = []
    for t in _divisors(n_devices):
        if t > max_tensor:
            continue
        for s in _divisors(n_devices // t):
            if s > max_sequence:
                continue
            for p in _divisors(n_devices // (t * s)):
                if p > max_pipe:
                    continue
                d = n_devices // (t * s * p)
                shapes.append({"data": d, "tensor": t, "sequence": s, "pipe": p})
    return shapes


class Autotuner:
    """Reference class name; `tune()` returns (best_config_dict, results)."""

    def __init__(self, model_factory, base_config, batch_factory,
                 stages=DEFAULT_STAGES, max_micro_batch=1024, steps=4, warmup=2,
                 results_dir=None, metric="throughput", capacity_bytes=None,
                 n_params=None, temp_bytes_per_sample=0,
                 min_headroom_frac=0.0):
        """model_factory() -> ModelSpec (fresh params per experiment);
        batch_factory(global_batch_size) -> batch pytree.

        Feasibility is probed ANALYTICALLY first: every candidate goes
        through `memscope.plan_training` against `capacity_bytes` (None =
        auto-detect from the device's memory_stats; 0 = unknown) with
        `n_params` counted once from a single profile factory call —
        predicted-OOM candidates are refused without constructing
        anything. The measured compile+run probe remains the fallback for
        planner-unknown configs (no known capacity — the CPU harness — or
        no countable params). `temp_bytes_per_sample` margins the
        activation workspace per micro-batch sample on top of the model
        states; `min_headroom_frac` additionally refuses tight fits."""
        self.model_factory = model_factory
        self.base_config = copy.deepcopy(base_config)
        self.batch_factory = batch_factory
        self.stages = stages
        self.max_micro_batch = max_micro_batch
        self.steps = steps
        self.warmup = warmup
        self.metric = metric
        self.results_dir = results_dir
        self.results = []
        self.capacity_bytes = capacity_bytes
        self.n_params = n_params
        self.temp_bytes_per_sample = int(temp_bytes_per_sample)
        self.min_headroom_frac = float(min_headroom_frac)
        self.planner_refusals = 0
        # persisted experiment journal (reference autotuner persists every
        # experiment and the cost model fits on them, `tuner/cost_model.py`;
        # r3 verdict: results were throwaway): records are keyed by a
        # fingerprint of (experiment, base config, device context) so a later
        # invocation — or the cost-model warmup — reuses measurements instead
        # of re-running them. Journal survives across processes in
        # results_dir/experiments.jsonl.
        self._journal = {}
        self._journal_path = None
        if results_dir:
            out = pathlib.Path(results_dir)
            out.mkdir(parents=True, exist_ok=True)
            self._journal_path = out / "experiments.jsonl"
            if self._journal_path.exists():
                with open(self._journal_path) as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if "fingerprint" in rec:
                            self._journal[rec["fingerprint"]] = rec["record"]
                if self._journal:
                    logger.info(f"autotune journal: {len(self._journal)} "
                                f"cached experiments from {self._journal_path}")

    def _fingerprint(self, stage, micro_batch, extra):
        import hashlib
        import jax
        ctx = {
            "exp": {"stage": stage, "micro_batch": micro_batch,
                    "extra": extra or {}},
            "base_config": self.base_config,
            # model identity: the factory's qualname (pass distinct
            # results_dirs for same-named factories of different models)
            "model": getattr(self.model_factory, "__qualname__",
                             repr(self.model_factory)),
            "steps": self.steps, "warmup": self.warmup,
            "n_devices": jax.device_count(),
            "platform": jax.default_backend(),
        }
        blob = json.dumps(ctx, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:24]

    def _journal_put(self, fp, rec):
        self._journal[fp] = rec
        if self._journal_path is not None:
            with open(self._journal_path, "a") as f:
                f.write(json.dumps({"fingerprint": fp, "record": rec}) + "\n")

    # ---- analytic preflight (memscope.plan_training) ----

    def _detect_capacity(self):
        """Per-device HBM budget: the explicit ctor value, else the
        backend's memory_stats (TPU/GPU report bytes_limit; the CPU
        harness reports nothing -> 0 = planner-unknown)."""
        if self.capacity_bytes is None:
            import jax
            cap = 0
            try:
                stats = jax.local_devices()[0].memory_stats() or {}
                cap = int(stats.get("bytes_limit", 0))
            except Exception:
                cap = 0
            self.capacity_bytes = cap
        return int(self.capacity_bytes)

    def _count_params(self):
        """The model-info profile run, reduced to its useful output: ONE
        factory call, counted and discarded (experiments still get fresh
        params from their own calls)."""
        if self.n_params is None:
            import jax
            import numpy as np
            model = self.model_factory()
            params = getattr(model, "params", None)
            self.n_params = sum(
                int(np.prod(leaf.shape))
                for leaf in jax.tree_util.tree_leaves(params)
                if hasattr(leaf, "shape")) if params is not None else 0
            del model
        return int(self.n_params)

    def _planner_verdict(self, stage, micro_batch, extra):
        """Refusal reason from `memscope.plan_training`, or None when the
        candidate is admissible — or planner-unknown (no capacity /
        no countable params), which falls through to the measured probe."""
        cap = self._detect_capacity()
        if not cap:
            return None
        n = self._count_params()
        if not n:
            return None
        import jax
        from deepspeed_tpu.telemetry import memscope
        cfg = self._apply_exp(copy.deepcopy(self.base_config),
                              dict(extra or {}, zero_stage=stage,
                                   micro_batch=micro_batch))
        mesh = cfg.get("mesh", {}) or {}
        tp = max(1, int(mesh.get("tensor", 1) or 1))
        sp = max(1, int(mesh.get("sequence", 1) or 1))
        pp = max(1, int(mesh.get("pipe", 1) or 1))
        dp = int(mesh.get("data", 0) or 0)
        if dp <= 0:
            dp = max(1, jax.device_count() // (tp * sp * pp))
        zero = cfg.get("zero_optimization", {}) or {}
        off_opt = str((zero.get("offload_optimizer") or {})
                      .get("device", "none")) not in ("none", "")
        off_param = str((zero.get("offload_param") or {})
                        .get("device", "none")) not in ("none", "")
        dtype = "bfloat16" if (cfg.get("bf16", {}) or {}).get("enabled") \
            else ("float16" if (cfg.get("fp16", {}) or {}).get("enabled")
                  else "float32")
        plan = memscope.plan_training(
            n, zero_stage=int(zero.get("stage", stage)), dp=dp, tp=tp,
            dtype=dtype,
            grad_accum_dtype=(cfg.get("data_types", {}) or {})
            .get("grad_accum_dtype"),
            offload_optimizer=off_opt, offload_param=off_param,
            temp_bytes=self.temp_bytes_per_sample * int(micro_batch),
            capacity_bytes=cap)
        if plan.fits is False:
            return (f"planner predicted OOM: peak "
                    f"{plan.predicted_peak_bytes} > capacity {cap}")
        hf = plan.headroom_frac
        if hf is not None and hf < self.min_headroom_frac:
            return (f"planner headroom {hf:.1%} under the "
                    f"{self.min_headroom_frac:.1%} floor")
        return None

    # ---- single experiment ----

    def _run_experiment(self, stage, micro_batch, extra=None):
        import jax
        import deepspeed_tpu
        from deepspeed_tpu.comm import mesh as mesh_mod
        fp = self._fingerprint(stage, micro_batch, extra)
        if fp in self._journal:
            rec = dict(self._journal[fp], cached=True)
            self.results.append(rec)
            logger.info(f"autotune experiment (journal): {rec}")
            return rec
        refusal = self._planner_verdict(stage, micro_batch, extra)
        if refusal is not None:
            # predicted-OOM candidates never construct anything: no model,
            # no engine, no compile — the refusal is the record
            rec = {"stage": stage, "micro_batch": micro_batch,
                   "status": "planner_refused", "error": refusal}
            self.planner_refusals += 1
            self.results.append(rec)
            logger.info(f"autotune experiment: {rec}")
            return rec
        mesh_mod._CURRENT_MESH = None
        mesh_mod._CURRENT_SPEC = None
        cfg = copy.deepcopy(self.base_config)
        cfg["gradient_accumulation_steps"] = 1
        self._apply_exp(cfg, dict(extra or {}, zero_stage=stage,
                                  micro_batch=micro_batch))
        engine = None
        try:
            model = self.model_factory()
            engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
            batch = self.batch_factory(engine.train_batch_size())
            for _ in range(self.warmup):
                loss = engine.train_batch(batch)
            float(loss)
            t0 = time.perf_counter()
            for _ in range(self.steps):
                loss = engine.train_batch(batch)
            float(loss)
            dt = (time.perf_counter() - t0) / self.steps
            tput = engine.train_batch_size() / dt
            rec = {"stage": stage, "micro_batch": micro_batch, "step_ms": dt * 1e3,
                   "samples_per_sec": tput, "status": "ok"}
        except Exception as e:
            rec = {"stage": stage, "micro_batch": micro_batch, "status": "fail",
                   "error": str(e)[:200]}
        finally:
            del engine
            gc.collect()
        self.results.append(rec)
        if rec["status"] == "ok":
            # only successes persist: a journaled transient failure (flaky
            # backend abort, interrupt) would be replayed as permanently
            # infeasible in every later invocation
            self._journal_put(fp, rec)
        logger.info(f"autotune experiment: {rec}")
        return rec

    # ---- search ----

    def _max_feasible_mbs(self, stage):
        """Binary search the largest runnable micro-batch (reference mbs search)."""
        lo, hi = 1, self.max_micro_batch
        best = None
        # fast doubling first
        mb = 1
        while mb <= hi:
            rec = self._run_experiment(stage, mb)
            if rec["status"] == "ok":
                best = rec
                mb *= 2
            else:
                hi = mb - 1
                break
        if best is None:
            return None
        lo = best["micro_batch"]
        # binary refine between lo and hi
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if mid == best["micro_batch"]:
                break
            rec = self._run_experiment(stage, mid)
            if rec["status"] == "ok":
                best = rec
                lo = mid
            else:
                hi = mid - 1
        return best

    def _base_stage(self):
        return self.base_config.get("zero_optimization", {}).get("stage", 0)

    def _base_mbs(self):
        return self.base_config.get("train_micro_batch_size_per_gpu", 1)

    def _apply_exp(self, tuned, exp):
        """Write an experiment's overrides into a config dict. Keys other than
        zero_stage/micro_batch are dotted config paths
        (e.g. "zero_optimization.offload_optimizer.device")."""
        if "micro_batch" in exp:
            tuned["train_micro_batch_size_per_gpu"] = exp["micro_batch"]
        if "zero_stage" in exp:
            tuned.setdefault("zero_optimization", {})["stage"] = exp["zero_stage"]
        for k, v in exp.items():
            if k in ("zero_stage", "micro_batch"):
                continue
            node = tuned
            *parents, leaf = k.split(".")
            for p in parents:
                node = node.setdefault(p, {})
            node[leaf] = v
        return tuned

    def _run_config(self, exp):
        """Tuner protocol adapter: run one experiment dict of config overrides
        and return the metric value (higher is better) or None if infeasible.
        Keys absent from the experiment inherit the base config."""
        rec = self._run_experiment(exp.get("zero_stage", self._base_stage()),
                                   exp.get("micro_batch", self._base_mbs()),
                                   extra={k: v for k, v in exp.items()
                                          if k not in ("zero_stage", "micro_batch")})
        if rec["status"] != "ok":
            return None
        return (rec["samples_per_sec"] if self.metric == "throughput"
                else -rec["step_ms"])

    def tune_space(self, exps, tuner_type="model_based", sample_size=1,
                   n_trials=None, early_stopping=None, **tuner_kw):
        """Explore an explicit experiment list with a tuner (reference
        `autotuning/tuner/`: gridsearch | random | model_based). Each exp is a
        dict of overrides — `zero_stage`, `micro_batch`, or dotted config paths
        like "zero_optimization.offload_optimizer.device"; omitted keys inherit
        the base config. Returns (tuned_config, best_record)."""
        from deepspeed_tpu.autotuning.tuner import make_tuner
        tuner = make_tuner(tuner_type, exps, self._run_config, **tuner_kw)
        best_exp, best_val = tuner.tune(sample_size=sample_size, n_trials=n_trials,
                                        early_stopping=early_stopping)
        if best_exp is None:
            raise RuntimeError("autotuning: no feasible configuration found")
        tuned = self._apply_exp(copy.deepcopy(self.base_config), best_exp)
        logger.info(f"autotune({tuner_type}) best: {best_exp} -> {best_val:.2f}")
        return tuned, {"exp": best_exp, "metric_val": best_val,
                       "trials": len(tuner.observed)}

    def tune_mesh(self, n_devices=None, shapes=None, tuner_type="gridsearch",
                  max_tensor=None, max_pipe=None, max_sequence=None,
                  extra_overrides=None, **tuner_kw):
        """Sweep mesh factorings (dp × tp × sp × pp) of the device count and
        return (tuned_config_with_best_mesh, best_record).

        `shapes` overrides the enumerated space with an explicit list of
        {"data","tensor","sequence","pipe"} dicts. Other config overrides
        (e.g. a fixed zero stage) ride along via `extra_overrides`.
        """
        if shapes is None:
            if n_devices is None:
                import jax
                n_devices = len(jax.devices())
            shapes = admissible_mesh_shapes(n_devices, max_tensor=max_tensor,
                                            max_pipe=max_pipe,
                                            max_sequence=max_sequence)
        exps = []
        for sh in shapes:
            exp = {f"mesh.{k}": v for k, v in sh.items()}
            exp.update(extra_overrides or {})
            exps.append(exp)
        tuned, best = self.tune_space(exps, tuner_type=tuner_type, **tuner_kw)
        best["mesh"] = {k.split(".", 1)[1]: v for k, v in best["exp"].items()
                       if k.startswith("mesh.")}
        logger.info(f"autotune mesh recommendation: {best['mesh']}")
        return tuned, best

    def tune(self):
        """Reference `Autotuner.tune` (`autotuner.py:404`)."""
        best = None
        for stage in self.stages:
            rec = self._max_feasible_mbs(stage)
            if rec is None:
                continue
            if best is None:
                best = rec
            elif self.metric == "throughput" and rec["samples_per_sec"] > best["samples_per_sec"]:
                best = rec
            elif self.metric == "latency" and rec["step_ms"] < best["step_ms"]:
                best = rec
        if self.results_dir:
            out = pathlib.Path(self.results_dir)
            out.mkdir(parents=True, exist_ok=True)
            with open(out / "autotuning_results.json", "w") as f:
                json.dump(self.results, f, indent=2)
        if best is None:
            raise RuntimeError("autotuning: no feasible configuration found")
        tuned = copy.deepcopy(self.base_config)
        tuned["train_micro_batch_size_per_gpu"] = best["micro_batch"]
        tuned.setdefault("zero_optimization", {})["stage"] = best["stage"]
        logger.info(f"autotune best: stage={best['stage']} mbs={best['micro_batch']} "
                    f"({best['samples_per_sec']:.1f} samples/s)")
        return tuned, best
