"""Declarative search spaces over the stack's config knobs.

A `SearchSpace` is a list of `Knob`s — each a dotted config path plus its
candidate values — whose deterministic cartesian product yields override
dicts (`zero_stage` / `micro_batch` keep the seed Autotuner's special
spelling; everything else is a dotted `TpuTrainConfig` /
`TpuInferenceConfig` path like ``serving.quantization.kv_cache_dtype``).

Constraint rules come FROM the stack, not next to it: every rule here
mirrors a loud refusal some subsystem already raises (the ValueErrors
pinned by `tests/test_tune.py::TestRefusalContracts`) so a candidate the
stack would reject at build time is refused symbolically — same verdict,
zero construction. Rules return a human-readable reason string (kept in
the prune ledger) or None for "admissible".
"""

import dataclasses
import itertools
from typing import Any, Callable, Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Knob:
    """One axis of a search space: a dotted config path and its values."""
    name: str
    values: tuple

    def __init__(self, name: str, values: Sequence[Any]):
        object.__setattr__(self, "name", str(name))
        object.__setattr__(self, "values", tuple(values))
        if not self.values:
            raise ValueError(f"knob '{name}' has no values")


@dataclasses.dataclass
class ModelProfile:
    """The model facts the analytic planner needs — gathered once (the
    reference autotuner's "model info profile run", here a pure read of
    the model config: no forward pass, no allocation)."""
    n_params: int
    n_layer: int
    n_head: int
    n_kv_head: int
    head_dim: int
    d_model: int
    vocab_size: int = 0
    max_seq_len: int = 0
    n_expert_params: int = 0
    num_experts: int = 0
    draft: Optional[Dict[str, Any]] = None   # drafter-model facts for
                                             # spec_decode drafter="model"

    @classmethod
    def from_gpt_config(cls, cfg, n_params=None, draft=None):
        """Profile a `models.gpt.GPTConfig` (or anything shaped like one).
        `n_params` overrides the analytic dense-GPT estimate."""
        n_kv = getattr(cfg, "n_kv_head", None) or cfg.n_head
        hd = cfg.d_model // cfg.n_head
        if n_params is None:
            d_ff = getattr(cfg, "d_ff", None) or 4 * cfg.d_model
            per_layer = (4 * cfg.d_model * cfg.d_model          # qkv+proj (MHA)
                         + 2 * cfg.d_model * d_ff)              # mlp in/out
            n_params = (cfg.vocab_size * cfg.d_model            # embedding
                        + cfg.n_layer * per_layer)
        return cls(n_params=int(n_params), n_layer=cfg.n_layer,
                   n_head=cfg.n_head, n_kv_head=int(n_kv), head_dim=hd,
                   d_model=cfg.d_model,
                   vocab_size=getattr(cfg, "vocab_size", 0),
                   max_seq_len=getattr(cfg, "max_seq_len", 0),
                   draft=draft)

    def to_dict(self):
        return dataclasses.asdict(self)


def apply_overrides(config: Dict[str, Any], overrides: Dict[str, Any]) -> Dict[str, Any]:
    """Write an override dict into a config dict (in place, returned).

    Same grammar as the seed `Autotuner._apply_exp`: `zero_stage` /
    `micro_batch` are the special spellings, every other key is a dotted
    path whose intermediate nodes are created as dicts."""
    for k, v in overrides.items():
        if k == "micro_batch":
            config["train_micro_batch_size_per_gpu"] = v
            continue
        if k == "zero_stage":
            config.setdefault("zero_optimization", {})["stage"] = v
            continue
        node = config
        *parents, leaf = k.split(".")
        for p in parents:
            nxt = node.get(p)
            if not isinstance(nxt, dict):
                nxt = {}
                node[p] = nxt
            node = nxt
        node[leaf] = v
    return config


class SearchSpace:
    """A named cartesian product of knobs. `kind` is "train" or
    "serving" — it selects the planner and the measurement harness."""

    def __init__(self, kind: str, knobs: Sequence[Knob]):
        if kind not in ("train", "serving"):
            raise ValueError(f"search-space kind must be 'train' or "
                             f"'serving', got {kind!r}")
        names = [k.name for k in knobs]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(f"duplicate knobs in search space: {sorted(dupes)}")
        self.kind = kind
        self.knobs = list(knobs)

    def __len__(self):
        n = 1
        for k in self.knobs:
            n *= len(k.values)
        return n

    def candidates(self) -> List[Dict[str, Any]]:
        """The full candidate list, in a deterministic order (knob order ×
        value order — `itertools.product` with the declared sequences), so
        grid search and the reproducibility contract are stable across
        runs."""
        names = [k.name for k in self.knobs]
        return [dict(zip(names, combo))
                for combo in itertools.product(*(k.values for k in self.knobs))]

    def to_dict(self):
        return {"kind": self.kind,
                "knobs": [{"name": k.name, "values": list(k.values)}
                          for k in self.knobs],
                "size": len(self)}

    @classmethod
    def from_dict(cls, d):
        return cls(d["kind"], [Knob(k["name"], k["values"])
                               for k in d.get("knobs", [])])


# ----------------------------------------------------------------------
# Constraint rules — one per loud refusal in the stack
# ----------------------------------------------------------------------

def _get(overrides: Dict[str, Any], base: Dict[str, Any], path: str,
         default=None):
    """Resolve a dotted path: overrides win, then the base config dict."""
    if path in overrides:
        return overrides[path]
    node = base or {}
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return default
        node = node[part]
    return node


def _is_streamed(overrides, base):
    # ZeRO-Inference offloaded weights => the streamed serving mode
    dev = _get(overrides, base, "zero.offload_param.device")
    return bool(dev)


def rule_streamed_spec_decode(kind, overrides, profile, base):
    """scheduler.py: streamed serving has no verify contract."""
    if kind != "serving" or not _is_streamed(overrides, base):
        return None
    drafter = str(_get(overrides, base, "serving.spec_decode.drafter",
                       "off") or "off")
    if drafter != "off":
        return ("speculative decoding is a resident-engine feature — the "
                "streamed (offloaded-weights) mode has no verify contract")
    return None


def rule_streamed_decode_window(kind, overrides, profile, base):
    """scheduler.py: the K-step jitted window needs a resident stack."""
    if kind != "serving" or not _is_streamed(overrides, base):
        return None
    window = int(_get(overrides, base, "serving.decode_steps_per_sync", 1)
                 or 1)
    if window != 1:
        return (f"decode_steps_per_sync={window} needs the whole stack "
                f"resident inside one jitted scan; the streamed mode "
                f"streams layers per token")
    return None


def rule_onebit_dispatch_wire(kind, overrides, profile, base):
    """collectives.py transform_all_to_all: the 1-bit wire is an
    error-feedback gradient codec, not an activation codec."""
    wire = _get(overrides, base, "moe.dispatch_wire")
    if wire is None:
        wire = _get(overrides, base, "moe.expert_parallel.dispatch_wire")
    if str(wire or "none") == "onebit":
        return ("moe dispatch_wire='onebit' — the 1-bit wire is an "
                "error-feedback gradient codec, not an activation codec")
    return None


def rule_heads_divisible(kind, overrides, profile, base):
    """ulysses.py: the head all-to-all scatters whole heads per rank —
    heads must divide by tp*sp."""
    if profile is None:
        return None
    tp = int(_get(overrides, base, "mesh.tensor", 1) or 1)
    sp = int(_get(overrides, base, "mesh.sequence", 1) or 1)
    if kind == "serving" and "mesh.tensor" not in overrides:
        tp = int(_get(overrides, base, "tensor_parallel.tp_size", tp) or tp)
    if tp * sp > 1 and profile.n_head % (tp * sp) != 0:
        return (f"{profile.n_head} heads do not divide by tp*sp="
                f"{tp * sp} — the sequence all-to-all scatters whole "
                f"heads per rank")
    kv = profile.n_kv_head or profile.n_head
    if tp > 1 and kv % tp != 0:
        return f"{kv} kv heads do not divide by tp={tp}"
    return None


def rule_int8_kv_needs_paged(kind, overrides, profile, base):
    """engine.py _get_cache: the contiguous generate() cache has no scale
    storage — int8 KV is a paged-pool serving feature. In a serving space
    the quantization block is the right spelling; the engine-level
    kv_cache_dtype knob set to int8 would refuse at the first
    generate()."""
    eng_dt = str(_get(overrides, base, "kv_cache_dtype", "") or "")
    if kind == "train" and eng_dt == "int8":
        return "kv_cache_dtype='int8' has no training meaning"
    if eng_dt == "int8" and "kv_cache_dtype" in overrides:
        return ("kv_cache_dtype='int8' on the engine quantizes the "
                "contiguous generate() cache, which has no scale storage "
                "— use serving.quantization.kv_cache_dtype")
    return None


def rule_kv_group_divides_head_dim(kind, overrides, profile, base):
    """quantization.py: K/V scale groups tile head_dim exactly."""
    if profile is None:
        return None
    g = int(_get(overrides, base, "serving.quantization.kv_group_size", 0)
            or 0)
    if g and profile.head_dim % g != 0:
        return (f"kv_group_size={g} does not divide head_dim="
                f"{profile.head_dim}")
    return None


def rule_model_drafter_needs_profile(kind, overrides, profile, base):
    """spec_decode drafter='model' serves a second DecodeModelSpec — the
    planner cannot price (and the harness cannot build) the draft mirror
    without its profile."""
    drafter = str(_get(overrides, base, "serving.spec_decode.drafter",
                       "off") or "off")
    if drafter == "model" and (profile is None or profile.draft is None):
        return ("spec_decode drafter='model' needs a draft model profile "
                "(none was provided)")
    return None


def rule_draft_k_without_drafter(kind, overrides, profile, base):
    """Degenerate-duplicate pruning: draft_k has no effect with the
    drafter off — keeping the variants would measure the same config
    len(draft_k values) times."""
    drafter = str(_get(overrides, base, "serving.spec_decode.drafter",
                       "off") or "off")
    if drafter != "off" or "serving.spec_decode.draft_k" not in overrides:
        return None
    k = int(overrides["serving.spec_decode.draft_k"])
    default_k = 4
    if k != default_k:
        return (f"draft_k={k} is inert with the drafter off — duplicate "
                f"of the default candidate")
    return None


def rule_mesh_matches_devices(kind, overrides, profile, base,
                              n_devices=None):
    """mesh.py init_mesh: the axis product must equal the device count
    (one absorbing -1 axis excepted)."""
    axes = {a: _get(overrides, base, f"mesh.{a}")
            for a in ("data", "tensor", "sequence", "pipe", "expert")}
    if all(v is None for v in axes.values()) or n_devices is None:
        return None
    vals = [int(v) for v in axes.values() if v is not None]
    if any(v == -1 for v in vals):
        fixed = 1
        for v in vals:
            if v != -1:
                fixed *= v
        if fixed == 0 or n_devices % fixed != 0:
            return (f"mesh axes {axes} do not factor the "
                    f"{n_devices}-device slice")
        return None
    prod = 1
    for v in vals:
        prod *= v
    if prod != n_devices:
        return (f"mesh axes product {prod} != device count {n_devices}")
    return None


DEFAULT_RULES = (
    rule_streamed_spec_decode,
    rule_streamed_decode_window,
    rule_onebit_dispatch_wire,
    rule_heads_divisible,
    rule_int8_kv_needs_paged,
    rule_kv_group_divides_head_dim,
    rule_model_drafter_needs_profile,
    rule_draft_k_without_drafter,
)


def check_constraints(kind, overrides, profile=None, base=None,
                      rules=DEFAULT_RULES, n_devices=None) -> Optional[str]:
    """First refusal reason among the rules, or None when admissible."""
    base = base or {}
    for rule in rules:
        reason = rule(kind, overrides, profile, base)
        if reason:
            return f"{rule.__name__}: {reason}"
    reason = rule_mesh_matches_devices(kind, overrides, profile, base,
                                       n_devices=n_devices)
    if reason:
        return f"rule_mesh_matches_devices: {reason}"
    return None


# ----------------------------------------------------------------------
# Default space builders
# ----------------------------------------------------------------------

def default_serving_space(num_kv_blocks=(0, 64, 128, 256),
                          kv_block_size=(16, 32),
                          kv_dtypes=("", "int8"),
                          drafters=("off", "ngram"),
                          prefix_caching=(False, True),
                          windows=(1, 4)) -> SearchSpace:
    """The serving knobs every PR since 4 added, as one space. The
    defaults deliberately include candidates the planner/constraints must
    refuse (oversized pools, inert draft_k variants) — the prune ledger
    is the point, not an embarrassment."""
    return SearchSpace("serving", [
        Knob("serving.num_kv_blocks", num_kv_blocks),
        Knob("kv_block_size", kv_block_size),
        Knob("serving.quantization.kv_cache_dtype", kv_dtypes),
        Knob("serving.spec_decode.drafter", drafters),
        Knob("serving.enable_prefix_caching", prefix_caching),
        Knob("serving.decode_steps_per_sync", windows),
    ])


def default_training_space(stages=(0, 1, 2, 3),
                           micro_batches=(1, 2, 4, 8),
                           grad_accum=(1, 2),
                           offload_optimizer=(False, True)) -> SearchSpace:
    return SearchSpace("train", [
        Knob("zero_stage", stages),
        Knob("micro_batch", micro_batches),
        Knob("gradient_accumulation_steps", grad_accum),
        Knob("zero_optimization.offload_optimizer.device",
             tuple("cpu" if o else "none" for o in offload_optimizer)),
    ])
