from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.measure import (VirtualClock, measure_serving,
                                              measure_training, ragged_trace,
                                              run_trial_child)
from deepspeed_tpu.autotuning.objectives import (Objective,
                                                 ServingSLOObjective,
                                                 ServingThroughputObjective,
                                                 TrainMFUObjective,
                                                 TrainThroughputObjective,
                                                 make_objective)
from deepspeed_tpu.autotuning.planner import (PruneEntry, ledger_counts,
                                              plan_candidate, prune)
from deepspeed_tpu.autotuning.session import (TUNE_COUNTERS, TuneSession,
                                              artifact_json,
                                              environment_fingerprint,
                                              load_tuned_config,
                                              write_artifact)
from deepspeed_tpu.autotuning.space import (Knob, ModelProfile, SearchSpace,
                                            apply_overrides,
                                            check_constraints,
                                            default_serving_space,
                                            default_training_space)
from deepspeed_tpu.autotuning.tuner import (BaseTuner, CostModel,
                                            GridSearchTuner, ModelBasedTuner,
                                            RandomTuner, make_tuner)
