from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.tuner import (BaseTuner, CostModel,
                                            GridSearchTuner, ModelBasedTuner,
                                            RandomTuner, make_tuner)
