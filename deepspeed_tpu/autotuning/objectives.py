"""Tuning objectives — how a measured trial becomes one number.

Every objective scores a measurement record (the JSON dict a trial run
returns, `autotuning/measure.py`) into a single higher-is-better float —
the tuner protocol's currency (`tuner.py` `run_fn`). Throughput objectives
are the plain rates; the SLO objective is the serving one that matters in
deployments: meet the declared TTFT/TPOT p99 targets (read from the PR 5
latency histograms over a replayed trace), THEN maximize throughput. An
SLO violation scores strictly below every SLO-meeting config — a fast
config that blows its tail latency can never win.
"""

from typing import Any, Dict, Optional


class Objective:
    """Base: `score(measurement) -> float` (higher is better; the caller
    maps a failed/absent measurement to infeasible before scoring)."""

    name = "objective"

    def score(self, measurement: Dict[str, Any]) -> float:
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name}


class ServingThroughputObjective(Objective):
    """Generated tokens per unit of engine time on the replayed trace."""

    name = "serving_throughput"

    def score(self, measurement):
        return float(measurement.get("tokens_per_time", 0.0))


class ServingSLOObjective(Objective):
    """SLO-gated throughput: TTFT/TPOT p99 targets first, tokens/s second.

    Both targets are in the measurement clock's milliseconds (the virtual
    clock counts scheduler syncs, so a target of N means "p99 within N
    syncs"; the wall clock makes them real milliseconds). A config meeting
    every declared target scores its throughput; a violating config scores
    the NEGATED worst violation ratio — ordering violators by how badly
    they miss, strictly below all compliant configs.
    """

    name = "serving_slo"

    def __init__(self, ttft_p99_ms: Optional[float] = None,
                 tpot_p99_ms: Optional[float] = None):
        self.ttft_p99_ms = ttft_p99_ms
        self.tpot_p99_ms = tpot_p99_ms

    def _violation(self, measurement) -> float:
        lat = measurement.get("latency", {}) or {}
        worst = 0.0
        for target, key in ((self.ttft_p99_ms, "ttft_ms"),
                            (self.tpot_p99_ms, "tpot_ms")):
            if not target:
                continue
            hist = lat.get(key) or {}
            p99 = hist.get("p99")
            if p99 is None:
                # no histogram = no evidence the SLO is met; a compliant
                # config must prove it
                worst = max(worst, 1.0)
                continue
            worst = max(worst, max(0.0, float(p99) / float(target) - 1.0))
        return worst

    def score(self, measurement):
        v = self._violation(measurement)
        if v > 0.0:
            return -v
        return float(measurement.get("tokens_per_time", 0.0))

    def describe(self):
        return {"name": self.name, "ttft_p99_ms": self.ttft_p99_ms,
                "tpot_p99_ms": self.tpot_p99_ms}


class TrainThroughputObjective(Objective):
    """Training samples (or tokens) per second, as the trial measured it."""

    name = "train_throughput"

    def score(self, measurement):
        return float(measurement.get("samples_per_sec", 0.0))


class TrainMFUObjective(Objective):
    """Model-flops utilization when the trial exports it, falling back to
    throughput (an MFU comparison needs the telemetry MFU gauge; trials
    without it still rank consistently by rate)."""

    name = "train_mfu"

    def score(self, measurement):
        mfu = measurement.get("mfu")
        if mfu is not None:
            return float(mfu)
        return float(measurement.get("samples_per_sec", 0.0))


OBJECTIVES = {
    "throughput": ServingThroughputObjective,
    "slo": ServingSLOObjective,
    "train_throughput": TrainThroughputObjective,
    "mfu": TrainMFUObjective,
    # canonical `Objective.name` spellings, so an artifact's `objective`
    # block (written by describe()) round-trips through make_objective
    "serving_throughput": ServingThroughputObjective,
    "serving_slo": ServingSLOObjective,
    "train_mfu": TrainMFUObjective,
}


def make_objective(spec) -> Objective:
    """Build from a name or a {"name": ..., **kwargs} dict (the artifact's
    `objective` block round-trips through this)."""
    if isinstance(spec, Objective):
        return spec
    if isinstance(spec, str):
        spec = {"name": spec}
    spec = dict(spec or {})
    name = spec.pop("name", "throughput")
    if name not in OBJECTIVES:
        raise ValueError(f"unknown objective '{name}' "
                         f"(have {sorted(OBJECTIVES)})")
    return OBJECTIVES[name](**spec)
