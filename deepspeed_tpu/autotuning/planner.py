"""Planner pruning — refuse candidates analytically, before anything exists.

The reference autotuner discovers infeasible configs by RUNNING them (build
the engine, catch the OOM). memscope's pre-flight planners (PR 10,
`telemetry/memscope.py`) make that backwards for this stack: `plan_training`
/ `plan_serving` price every candidate's resident bytes — including the
int8-scale and expert-placement terms — with pure arithmetic, so predicted-
OOM and low-headroom configs are refused before any allocation or compile.
What survives goes to the measured stage; what doesn't is a ledger row with
the reason, which is part of the tuned-config artifact, not a log line.
"""

import copy
import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.autotuning.space import (ModelProfile, SearchSpace,
                                            apply_overrides,
                                            check_constraints)
from deepspeed_tpu.telemetry.memscope import (MemoryPlan, dtype_bytes,
                                              plan_serving, plan_training)


@dataclasses.dataclass
class PruneEntry:
    """One ledger row: a candidate and what the planner decided about it."""
    overrides: Dict[str, Any]
    verdict: str                       # "kept" | "refused"
    reason: str = ""                   # refusal reason ("" when kept)
    stage: str = ""                    # "constraint" | "planner" | ""
    predicted_peak_bytes: Optional[int] = None
    headroom_frac: Optional[float] = None

    def to_dict(self):
        return dataclasses.asdict(self)


def _merged(base_config: Dict[str, Any], overrides: Dict[str, Any]):
    return apply_overrides(copy.deepcopy(dict(base_config or {})), overrides)


def _dig(d: Dict[str, Any], path: str, default=None):
    node = d
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return default
        node = node[part]
    return node


def _weight_bytes(n_params: int, weights: str, group: int,
                  param_dtype: str) -> int:
    """Resident weight bytes under serving weight-only quantization —
    mirrors `inference/quantization.py` pricing: int8 stores 1 byte per
    element, int4 packs two per byte, both plus one f32 scale per
    `group` elements (4/g bytes each)."""
    w = str(weights or "off")
    if w == "off":
        return int(n_params) * dtype_bytes(param_dtype)
    g = max(1, int(group) or 64)
    per_elem = (1.0 if w == "int8" else 0.5) + 4.0 / g
    return int(n_params * per_elem)


def train_temp_margin(profile: ModelProfile, micro_batch: int,
                      seq_len: int, dtype: str = "bfloat16") -> int:
    """Activation-workspace margin for the training plan: one boundary
    activation per layer (plus the embedding output) at the step dtype —
    the remat floor. Deliberately a FLOOR, not a peak model: the planner
    refuses on resident states + this margin; anything tighter goes to
    the measured stage."""
    seq = int(seq_len) or 1024
    return int((profile.n_layer + 1) * max(1, int(micro_batch)) * seq
               * profile.d_model * dtype_bytes(dtype))


def plan_candidate(kind: str, profile: ModelProfile,
                   base_config: Dict[str, Any], overrides: Dict[str, Any],
                   capacity_bytes: int = 0, n_devices: int = 1,
                   temp_bytes: Optional[int] = None) -> MemoryPlan:
    """Price one candidate with the memscope planner. Pure arithmetic —
    no jax import, no allocation, no compile."""
    cfg = _merged(base_config, overrides)
    if kind == "serving":
        return _plan_serving_candidate(profile, cfg, capacity_bytes,
                                       temp_bytes)
    return _plan_training_candidate(profile, cfg, capacity_bytes,
                                    n_devices, temp_bytes)


def _plan_serving_candidate(profile, cfg, capacity_bytes, temp_bytes):
    block = int(_dig(cfg, "kv_block_size", 512) or 512)
    serving = _dig(cfg, "serving", {}) or {}
    max_slots = int(serving.get("max_slots", 8) or 8)
    max_context = int(serving.get("max_context", 0) or
                      _dig(cfg, "max_out_tokens", 1024) or 1024)
    nb = max(1, math.ceil(max_context / block))
    num_blocks = int(serving.get("num_kv_blocks", 0) or
                     (max_slots * nb + 1))
    quant = serving.get("quantization", {}) or {}
    kv_dtype = str(quant.get("kv_cache_dtype", "") or
                   _dig(cfg, "kv_cache_dtype", "bfloat16") or "bfloat16")
    kv_group = int(quant.get("kv_group_size", 0) or 0)
    param_dtype = str(_dig(cfg, "dtype", "bfloat16") or "bfloat16")
    params_bytes = _weight_bytes(profile.n_params,
                                 quant.get("weights", "off"),
                                 quant.get("weight_group_size", 64),
                                 param_dtype)
    tp = int(_dig(cfg, "tensor_parallel.tp_size", 1) or 1)
    sp = int(_dig(cfg, "mesh.sequence", 1) or 1)
    draft = None
    drafter = str(_dig(cfg, "serving.spec_decode.drafter", "off") or "off")
    if drafter == "model" and profile.draft:
        draft = dict(profile.draft)
    return plan_serving(
        n_layer=profile.n_layer, n_kv_head=profile.n_kv_head,
        head_dim=profile.head_dim, kv_block_size=block,
        num_kv_blocks=num_blocks, kv_cache_dtype=kv_dtype,
        kv_group_size=kv_group, params_bytes=params_bytes, tp=tp,
        sequence_parallel=sp, draft=draft,
        temp_bytes=int(temp_bytes or 0), capacity_bytes=int(capacity_bytes))


def _plan_training_candidate(profile, cfg, capacity_bytes, n_devices,
                             temp_bytes):
    zero = _dig(cfg, "zero_optimization", {}) or {}
    stage = int(zero.get("stage", 0) or 0)
    tp = int(_dig(cfg, "mesh.tensor", 1) or 1)
    sp = int(_dig(cfg, "mesh.sequence", 1) or 1)
    pp = int(_dig(cfg, "mesh.pipe", 1) or 1)
    ep = int(_dig(cfg, "mesh.expert", 1) or 1)
    dp = int(_dig(cfg, "mesh.data", 0) or 0)
    if dp <= 0:
        dp = max(1, int(n_devices) // max(1, tp * sp * pp))
    dtype = "bfloat16" if _dig(cfg, "bf16.enabled") else (
        "float16" if _dig(cfg, "fp16.enabled") else
        str(_dig(cfg, "data_types.param_dtype", "") or "float32"))
    off_opt = str(_dig(cfg, "zero_optimization.offload_optimizer.device",
                       "none") or "none") not in ("none", "")
    off_param = str(_dig(cfg, "zero_optimization.offload_param.device",
                         "none") or "none") not in ("none", "")
    mbs = int(_dig(cfg, "train_micro_batch_size_per_gpu", 1) or 1)
    if temp_bytes is None:
        temp_bytes = train_temp_margin(profile, mbs, profile.max_seq_len,
                                       dtype)
    return plan_training(
        profile.n_params, zero_stage=stage, dp=dp, tp=tp, dtype=dtype,
        grad_accum_dtype=_dig(cfg, "data_types.grad_accum_dtype"),
        offload_optimizer=off_opt, offload_param=off_param,
        num_experts=profile.num_experts, ep_size=ep,
        n_expert_params=profile.n_expert_params,
        temp_bytes=int(temp_bytes), capacity_bytes=int(capacity_bytes))


def prune(space: SearchSpace, profile: ModelProfile,
          base_config: Optional[Dict[str, Any]] = None,
          capacity_bytes: int = 0, min_headroom_frac: float = 0.0,
          n_devices: int = 1, temp_bytes: Optional[int] = None,
          ) -> Tuple[List[Dict[str, Any]], List[PruneEntry]]:
    """Score every candidate; return (survivor overrides, full ledger).

    Two refusal stages, both symbolic: constraint rules (the stack's loud
    refusals, `space.py`) first, then the memory plan — predicted OOM, or
    headroom under `min_headroom_frac` of capacity. With no known
    capacity (the CPU harness) the planner stage keeps everything and the
    ledger still records each candidate's predicted peak."""
    base = dict(base_config or {})
    survivors: List[Dict[str, Any]] = []
    ledger: List[PruneEntry] = []
    for cand in space.candidates():
        reason = check_constraints(space.kind, cand, profile=profile,
                                   base=base, n_devices=n_devices)
        if reason:
            ledger.append(PruneEntry(cand, "refused", reason,
                                     stage="constraint"))
            continue
        plan = plan_candidate(space.kind, profile, base, cand,
                              capacity_bytes=capacity_bytes,
                              n_devices=n_devices, temp_bytes=temp_bytes)
        hf = plan.headroom_frac
        if plan.fits is False:
            ledger.append(PruneEntry(
                cand, "refused",
                f"predicted OOM: peak {plan.predicted_peak_bytes} > "
                f"capacity {plan.capacity_bytes}", stage="planner",
                predicted_peak_bytes=plan.predicted_peak_bytes,
                headroom_frac=hf))
            continue
        if hf is not None and hf < float(min_headroom_frac):
            ledger.append(PruneEntry(
                cand, "refused",
                f"headroom {hf:.1%} under the {min_headroom_frac:.1%} "
                f"floor", stage="planner",
                predicted_peak_bytes=plan.predicted_peak_bytes,
                headroom_frac=hf))
            continue
        ledger.append(PruneEntry(cand, "kept",
                                 predicted_peak_bytes=plan.predicted_peak_bytes,
                                 headroom_frac=hf))
        survivors.append(cand)
    return survivors, ledger


def ledger_counts(ledger: List[PruneEntry]) -> Dict[str, int]:
    out = {"candidates": len(ledger), "kept": 0,
           "constraint_refused": 0, "planner_refused": 0}
    for e in ledger:
        if e.verdict == "kept":
            out["kept"] += 1
        elif e.stage == "constraint":
            out["constraint_refused"] += 1
        else:
            out["planner_refused"] += 1
    return out
