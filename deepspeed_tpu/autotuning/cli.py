"""`bin/dstpu_tune` — the whole-stack tuner as a command.

Runs a `TuneSession` over the default serving or training search space —
against the built-in tiny-GPT demo model (the CPU-harness walkthrough in
docs/autotuning.md; 8 virtual devices, virtual clock, fully
deterministic) — and writes the tuned-config artifact. Programs tuning a
real model build a `TuneSession` directly with their own profile and
`measure_fn`; this CLI is the end-to-end recipe and the smoke lane.

    dstpu_tune serving --objective slo --ttft-p99 8 --tpot-p99 4 \
        --capacity 16M --out tuned.json
    dstpu_tune serving --dry-run            # planner ledger only
    dstpu_tune train --trials 6
"""

import argparse
import functools
import json
import sys


def _demo_gpt_cfg():
    return dict(n_layer=2, n_head=4, d_model=64, max_seq_len=256,
                vocab_size=256, dtype="float32", remat=False)


def _serving_measure_fn(args, base_config, trace, model_cfg):
    from deepspeed_tpu.autotuning.measure import (measure_serving,
                                                  run_trial_child)
    if args.isolation == "process":
        def measure(overrides):
            return run_trial_child({
                "kind": "serving",
                "model": {"kind": "tiny_gpt", "cfg": model_cfg},
                "base_config": base_config, "overrides": overrides,
                "trace": trace, "clock": args.clock,
            }, timeout=args.trial_timeout)
        return measure

    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt import GPTConfig, make_gpt_decode_model

    def spec_factory():
        cfg = dict(model_cfg, dtype=jnp.dtype(model_cfg["dtype"]))
        return make_gpt_decode_model(cfg=GPTConfig(**cfg), name="tuned")

    return functools.partial(measure_serving, spec_factory, base_config,
                             trace=trace, clock=args.clock)


def _train_measure_fn(args, base_config, model_cfg):
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu.autotuning.measure import measure_training
    from deepspeed_tpu.models.gpt import GPTConfig, make_gpt_model
    seq = 32

    def model_factory():
        cfg = dict(model_cfg, max_seq_len=seq,
                   dtype=jnp.dtype(model_cfg["dtype"]))
        return make_gpt_model(cfg=GPTConfig(**cfg))

    def batch_factory(n):
        toks = np.random.default_rng(args.seed).integers(
            0, model_cfg["vocab_size"], (n, seq))
        return {"tokens": toks.astype(np.int32)}

    def measure(overrides):
        return measure_training(model_factory, batch_factory, base_config,
                                overrides, steps=2, warmup=1)
    return measure


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu_tune",
        description="planner-pruned whole-stack autotuner: search space -> "
                    "constraint+planner prune (zero allocations) -> "
                    "measured trials -> reproducible tuned-config artifact")
    ap.add_argument("mode", choices=("serving", "train"))
    ap.add_argument("--capacity", default="0",
                    help="per-device memory budget the planner judges "
                         "against (e.g. 16G, 512M; 0 = unknown: planner "
                         "records peaks but refuses nothing)")
    ap.add_argument("--min-headroom", type=float, default=0.0,
                    help="refuse candidates with predicted headroom under "
                         "this fraction of capacity")
    ap.add_argument("--objective", default=None,
                    help="slo | throughput (serving); train_throughput | "
                         "mfu (train)")
    ap.add_argument("--ttft-p99", type=float, default=None,
                    help="SLO target: TTFT p99 in clock ms (virtual clock: "
                         "scheduler syncs)")
    ap.add_argument("--tpot-p99", type=float, default=None,
                    help="SLO target: TPOT p99 in clock ms")
    ap.add_argument("--tuner", default="gridsearch",
                    choices=("gridsearch", "random", "model_based"))
    ap.add_argument("--trials", type=int, default=None,
                    help="measurement budget (default: every survivor)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace-seed", type=int, default=None,
                    help="ragged-trace seed (default: --seed)")
    ap.add_argument("--requests", type=int, default=12,
                    help="requests in the replayed trace")
    ap.add_argument("--max-new", type=int, default=12,
                    help="tokens generated per request")
    ap.add_argument("--clock", default="virtual",
                    choices=("virtual", "wall"),
                    help="virtual = deterministic sync-count latencies "
                         "(the reproducibility contract); wall = real "
                         "time on hardware")
    ap.add_argument("--isolation", default="inprocess",
                    choices=("inprocess", "process"),
                    help="process = each trial in a child (the bench-lane "
                         "recipe; a trial crash costs one trial)")
    ap.add_argument("--trial-timeout", type=float, default=None)
    ap.add_argument("--dry-run", action="store_true",
                    help="stop after the planner stage: artifact holds "
                         "the prune ledger, no measurements")
    ap.add_argument("--out", default="tuned_config.json")
    args = ap.parse_args(argv)

    from deepspeed_tpu.autotuning.measure import ragged_trace
    from deepspeed_tpu.autotuning.session import (TuneSession,
                                                  write_artifact)
    from deepspeed_tpu.autotuning.space import (ModelProfile,
                                                default_serving_space,
                                                default_training_space)
    from deepspeed_tpu.telemetry.memscope import _parse_size, fmt_bytes

    capacity = _parse_size(args.capacity)
    model_cfg = _demo_gpt_cfg()

    class _Cfg:                           # profile view of the demo dict
        pass
    view = _Cfg()
    for k, v in model_cfg.items():
        setattr(view, k, v)
    view.d_ff = None
    view.n_kv_head = None
    profile = ModelProfile.from_gpt_config(view)

    if args.mode == "serving":
        import jax
        base_config = {"dtype": "float32", "kv_cache_dtype": "float32",
                       "greedy": True, "kv_block_size": 16,
                       "max_out_tokens": 64, "serving": {"max_slots": 4}}
        trace = ragged_trace(
            seed=args.trace_seed if args.trace_seed is not None
            else args.seed,
            n_requests=args.requests, max_new=args.max_new,
            vocab=model_cfg["vocab_size"])
        objective = args.objective or (
            "slo" if (args.ttft_p99 or args.tpot_p99) else "throughput")
        if objective == "slo":
            objective = {"name": "slo", "ttft_p99_ms": args.ttft_p99,
                         "tpot_p99_ms": args.tpot_p99}
        session = TuneSession(
            default_serving_space(), objective,
            _serving_measure_fn(args, base_config, trace, model_cfg),
            profile, base_config=base_config, capacity_bytes=capacity,
            min_headroom_frac=args.min_headroom,
            n_devices=jax.device_count(), tuner_type=args.tuner,
            seed=args.seed, max_trials=args.trials, trace=trace)
    else:
        import jax
        base_config = {"optimizer": {"type": "Adam",
                                     "params": {"lr": 1e-3}},
                       "train_micro_batch_size_per_gpu": 1,
                       "mesh": {"data": -1}, "steps_per_print": 10**9}
        session = TuneSession(
            default_training_space(),
            args.objective or "train_throughput",
            _train_measure_fn(args, base_config, model_cfg),
            profile, base_config=base_config, capacity_bytes=capacity,
            min_headroom_frac=args.min_headroom,
            n_devices=jax.device_count(), tuner_type=args.tuner,
            seed=args.seed, max_trials=args.trials)

    artifact = session.run(dry_run=args.dry_run)
    path = write_artifact(artifact, args.out)
    counts = artifact["prune_ledger"]["counts"]
    print(f"dstpu_tune: {counts['candidates']} candidates, "
          f"{counts['constraint_refused']} constraint-refused, "
          f"{counts['planner_refused']} planner-refused "
          f"(capacity {fmt_bytes(capacity) if capacity else 'unknown'}), "
          f"{counts['kept']} measured-stage survivors")
    if artifact["winner"] is not None:
        base = artifact["baseline"]["objective"] \
            if artifact["baseline"] else None
        print(f"winner objective {artifact['winner']['objective']:.4g}"
              + (f" vs baseline {base:.4g}" if base is not None else "")
              + f" — overrides {json.dumps(artifact['winner']['overrides'], sort_keys=True)}")
    elif not args.dry_run:
        print("no feasible candidate survived to the measured stage")
    print(f"artifact written to {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
