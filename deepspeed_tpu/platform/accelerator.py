"""Platform (accelerator) abstraction.

TPU-native analog of the reference's `accelerator/abstract_accelerator.py:10`
(`DeepSpeedAccelerator` ABC, ~80 methods) + `accelerator/real_accelerator.py:45`
(env/auto probe). In JAX most of that surface collapses: streams/events are XLA's
async dispatch, memory mgmt is the runtime's; what remains useful is device query,
HBM stats, dtype support, platform naming, and the communication-backend name.

Selection: `DSTPU_ACCELERATOR` env ("tpu" | "cpu" | "gpu") or auto-probe of
`jax.default_backend()`.
"""

import os
import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class BaseAccelerator:
    """Shared implementation over jax.devices()."""

    _name = "base"
    _communication_backend = "xla"

    # ---- identity ----
    def device_name(self, device_index=None):
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def is_available(self):
        try:
            return len(self.devices()) > 0
        except RuntimeError:
            return False

    def device_count(self):
        return len(self.devices())

    def devices(self):
        return [d for d in jax.devices() if self._matches(d)]

    def _matches(self, d):
        return True

    def current_device(self):
        return self.devices()[0]

    def current_device_name(self):
        return self.device_name(0)

    def communication_backend_name(self):
        # Reference: accelerator.communication_backend_name() picks nccl/ccl/hccl
        # (`accelerator/cuda_accelerator.py`); on TPU there is a single answer: XLA
        # collectives over ICI/DCN.
        return self._communication_backend

    # ---- memory ----
    def memory_stats(self, device=None):
        d = device or self.current_device()
        try:
            return d.memory_stats() or {}
        except Exception:
            return {}

    def memory_allocated(self, device=None):
        return self.memory_stats(device).get("bytes_in_use", 0)

    def max_memory_allocated(self, device=None):
        return self.memory_stats(device).get("peak_bytes_in_use", 0)

    def total_memory(self, device=None):
        return self.memory_stats(device).get("bytes_limit", 0)

    def available_memory(self, device=None):
        s = self.memory_stats(device)
        return max(s.get("bytes_limit", 0) - s.get("bytes_in_use", 0), 0)

    def empty_cache(self):
        # XLA owns allocation; provide GC-style hook for API parity.
        import gc
        gc.collect()

    def reset_peak_memory_stats(self, device=None):
        pass  # not exposed by the TPU runtime; kept for API parity

    # ---- synchronization (streams/events collapse to dispatch barriers) ----
    def synchronize(self, device=None):
        jax.effects_barrier()

    # ---- dtype support ----
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    def preferred_dtype(self):
        return jnp.bfloat16

    # ---- profiling ranges (nvtx analog) ----
    def range_push(self, msg):
        self._trace = jax.profiler.TraceAnnotation(msg)
        self._trace.__enter__()

    def range_pop(self):
        if getattr(self, "_trace", None) is not None:
            self._trace.__exit__(None, None, None)
            self._trace = None

    # ---- misc parity ----
    def lazy_call(self, callback):
        callback()

    def op_builder_dir(self):
        return "deepspeed_tpu.ops"

    def on_accelerator(self, tensor):
        return hasattr(tensor, "devices") or hasattr(tensor, "device")


class TpuAccelerator(BaseAccelerator):
    _name = "tpu"
    _communication_backend = "xla-ici"

    def _matches(self, d):
        return d.platform in ("tpu", "axon")

    def preferred_dtype(self):
        return jnp.bfloat16


class CpuAccelerator(BaseAccelerator):
    _name = "cpu"
    _communication_backend = "xla-host"

    def _matches(self, d):
        return d.platform == "cpu"


class GpuAccelerator(BaseAccelerator):
    _name = "gpu"
    _communication_backend = "xla-nccl"

    def _matches(self, d):
        return d.platform in ("gpu", "cuda", "rocm")


_ACCELERATOR = None


def set_accelerator(accel):
    global _ACCELERATOR
    _ACCELERATOR = accel


@functools.lru_cache(None)
def _probe():
    env = os.environ.get("DSTPU_ACCELERATOR")
    backend = env or jax.default_backend()
    if backend in ("tpu", "axon"):
        return TpuAccelerator()
    if backend in ("gpu", "cuda", "rocm"):
        return GpuAccelerator()
    return CpuAccelerator()


def get_accelerator():
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = _probe()
    return _ACCELERATOR
