from deepspeed_tpu.platform.accelerator import (
    TpuAccelerator,
    CpuAccelerator,
    get_accelerator,
    set_accelerator,
)
