"""Tensor parallelism: sharding rules + automatic planning.

Reference surfaces covered:
  * Megatron-style layer helpers — the reference delegates training TP to the
    client via `mpu` (`deepspeed/__init__.py:94`); here we make it first-class
    with PartitionSpec helpers.
  * AutoTP (`module_inject/auto_tp.py:175` + `tp_shard.py`, `fusedqkv_utils.py`):
    policy-free sharding of an arbitrary transformer param tree. The reference
    walks the module graph looking for all-reduce points; we classify 2-D weight
    leaves by name/shape heuristics into column-parallel (shard output dim),
    row-parallel (shard input dim) or replicated — under SPMD the all-reduce
    points then fall out of XLA's partitioner instead of being patched in.
  * TiledLinear (`runtime/zero/tiling.py:32`): activation-memory capping by
    splitting a big matmul — on TPU a lax.map over column tiles.
"""

import re
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.mesh import TENSOR_AXIS
from deepspeed_tpu.utils.logging import logger


def column_parallel_spec(ndim=2):
    """Shard the output (last) dim: Y = X @ W, W: [in, out@tp]."""
    return P(*([None] * (ndim - 1) + [TENSOR_AXIS]))


def row_parallel_spec(ndim=2):
    """Shard the input (second-to-last) dim: W: [in@tp, out] — XLA inserts the
    all-reduce after the partial matmul."""
    if ndim == 1:
        return P(None)
    return P(*([None] * (ndim - 2) + [TENSOR_AXIS, None]))


# name patterns → parallel style (covers HF gpt2/llama/opt/bloom/neox naming and
# our zoo; mirrors the module lists AutoTP builds per policy)
_COLUMN_PATTERNS = [
    r"qkv", r"q_proj", r"k_proj", r"v_proj", r"query", r"key", r"value",
    r"wi\b", r"up_proj", r"gate_proj", r"fc_in", r"c_fc", r"mlp_up", r"mlp_gate",
    r"intermediate", r"dense_h_to_4h",
]
_ROW_PATTERNS = [
    r"o_proj", r"out_proj", r"attn_out", r"c_proj", r"wo\b", r"down_proj",
    r"fc_out", r"mlp_down", r"dense_4h_to_h", r"attention\.dense",
]
_EMBED_PATTERNS = [r"wte", r"embed_tokens", r"word_embeddings", r"lm_head", r"embed_out"]


def _classify(path: str):
    low = path.lower()
    for pat in _ROW_PATTERNS:
        if re.search(pat, low):
            return "row"
    for pat in _COLUMN_PATTERNS:
        if re.search(pat, low):
            return "column"
    for pat in _EMBED_PATTERNS:
        if re.search(pat, low):
            return "embed"
    return "replicate"


def plan_tp_specs(params, tp_size: Optional[int] = None, overrides: Dict[str, P] = None,
                  stacked_layers: bool = False, verbose=False):
    """AutoTP analog: produce a PartitionSpec pytree for an arbitrary param tree.

    `stacked_layers`: leaves carry a leading layer dim (scan-over-layers zoo
    models) — specs get a leading None. `overrides`: regex → PartitionSpec.
    """
    overrides = overrides or {}

    def leaf_spec(path_parts, leaf):
        path = "/".join(str(p) for p in path_parts)
        for pat, spec in overrides.items():
            if re.search(pat, path):
                return spec
        ndim = getattr(leaf, "ndim", 0)
        eff_ndim = ndim - (1 if stacked_layers else 0)
        kind = _classify(path)
        if eff_ndim < 1 or kind == "replicate":
            spec = P(*([None] * ndim))
        elif kind == "embed":
            # vocab-parallel embedding: shard vocab (first effective) dim
            spec = P(*([None] * (1 if stacked_layers else 0) + [TENSOR_AXIS]
                       + [None] * (eff_ndim - 1)))
        elif kind == "column":
            base = [None] * (eff_ndim - 1) + [TENSOR_AXIS]
            spec = P(*(([None] if stacked_layers else []) + base))
        else:  # row
            if eff_ndim == 1:
                spec = P(*([None] * ndim))
            else:
                base = [None] * (eff_ndim - 2) + [TENSOR_AXIS, None]
                spec = P(*(([None] if stacked_layers else []) + base))
        if verbose:
            logger.info(f"AutoTP: {path} [{getattr(leaf, 'shape', ())}] -> {kind} {spec}")
        return spec

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: leaf_spec([getattr(k, 'key', getattr(k, 'name', getattr(k, 'idx', k)))
                                      for k in path], leaf),
        params)


def tiled_linear(x, w, b=None, splits=4):
    """Compat alias for the canonical implementation in runtime/tiling.py
    (`runtime/zero/tiling.py:32`)."""
    from deepspeed_tpu.runtime.tiling import tiled_matmul
    return tiled_matmul(x, w, b, out_splits=splits)


# Canonical class lives in runtime/tiling.py; re-exported here for parity.
from deepspeed_tpu.runtime.tiling import TiledLinear  # noqa: E402
