"""Mixture-of-Experts with expert parallelism, TPU-native.

Reference: `deepspeed/moe/` — `MoE` layer (`moe/layer.py:16`), `MOELayer` +
`top1gating`/`top2gating` with capacity/jitter/load-balance loss
(`moe/sharded_moe.py:184,282,425`), `_AllToAll` dispatch (:95), expert groups
(`utils/groups.py:113,207`).

TPU-native formulation (GShard-style, fully static shapes): gating produces
dispatch/combine tensors; capacity overflow drops tokens by masking (no
dynamic shapes under jit — the "hard part" called out in SURVEY §7). Token
routing runs one of three ways:

  * **facade-routed** (`expert_parallel_moe`) — the first-class path when a
    mesh with `expert` axis size > 1 is active: gating + dispatch/combine run
    inside `shard_map`, and the expert exchange is two explicit
    `comm/collectives.py` all_to_alls (the reference `_AllToAll` pair). The
    facade records trace-time byte/call stats (`comm/all_to_all_bytes`) and
    the wire is `WireTransform`-compressible (``dispatch_wire="int8"``).
    Tokens shard over (data, zero, expert) jointly; experts shard over
    `expert`. Each shard gates its own tokens against a *local* capacity
    ``ceil(n_local/E · cf)`` — the reference's per-rank gating.
  * **einsum fallback** — no mesh / ep==1 / a composition the shard_map path
    does not cover (tensor- or sequence-sharded activations): dispatch is an
    einsum plus a sharding constraint that puts the expert dim on the
    `expert` mesh axis, and XLA emits the all-to-all pair itself (invisible
    to the facade's byte accounting).
  * **dropless** (`dropless_moe`) — no capacity, no drops: the Pallas token
    sort kernel (`ops/pallas/token_sort.py`) ranks each token within its
    expert's queue and tokens scatter into an [E, N] buffer (capacity = N is
    the only static dropless bound; memory E·N·D — for moderate N).
"""

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import collectives as coll
from deepspeed_tpu.comm.mesh import (BATCH_AXES, EXPERT_AXIS, SEQ_AXIS,
                                     TENSOR_AXIS, shard_constraint)
from deepspeed_tpu.utils.jax_compat import shard_map


def _capacity(num_tokens, num_experts, capacity_factor, min_capacity):
    cap = int(np.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def top1_gating(logits, capacity_factor=1.0, min_capacity=4, noisy_gate_policy=None,
                rng=None, used_token_mask=None):
    """Top-1 gating (reference `top1gating`, `moe/sharded_moe.py:184`).

    logits: [N, E] (N = flattened tokens). Returns (l_aux, dispatch [N,E,C] bool,
    combine [N,E,C] float, exp_counts [E]).
    """
    N, E = logits.shape
    C = _capacity(N, E, capacity_factor, min_capacity)

    if noisy_gate_policy == "RSample" and rng is not None:
        logits = logits + jax.random.gumbel(rng, logits.shape) * 1e-2
    gates = jax.nn.softmax(logits, axis=-1)                       # [N, E]
    expert_idx = jnp.argmax(gates, axis=-1)                       # [N]
    mask1 = _one_hot(expert_idx, E)                               # [N, E]
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None]

    # load-balancing aux loss (me·ce formulation of the reference)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position of each token within its expert queue
    pos_in_expert = jnp.cumsum(mask1, axis=0) * mask1             # [N, E], 1-based
    keep = (pos_in_expert <= C) & (mask1 > 0)
    pos = (pos_in_expert - 1.0) * mask1                           # 0-based
    exp_counts = jnp.sum(mask1, axis=0)

    gate_val = jnp.sum(gates * mask1, axis=-1, keepdims=True)     # [N, 1]
    slot = jnp.sum(pos, axis=-1).astype(jnp.int32)                # [N] 0-based slot
    dispatch = keep[..., None] * _one_hot(slot, C)[:, None, :]    # [N, E, C]
    combine = dispatch * gate_val[..., None]
    return l_aux, dispatch.astype(jnp.bool_), combine, exp_counts


def top2_gating(logits, capacity_factor=1.0, min_capacity=4, rng=None):
    """Top-2 gating (reference `top2gating`, `moe/sharded_moe.py:282`).

    The second-expert tie-breaking jitter takes an **explicit** `jax.random`
    key — no hidden seed state, so replay under the chaos/parity harnesses is
    deterministic; ``rng=None`` means no jitter. Top-2 weights are
    renormalized **after** the capacity drop: a token whose second expert
    overflowed gives its full combine weight to the surviving expert (the
    pre-drop renorm leaked the dropped expert's share to nobody).
    """
    N, E = logits.shape
    C = _capacity(N, E, 2 * capacity_factor, min_capacity)

    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    gates_wo1 = gates * (1 - mask1)
    if rng is not None:
        # jitter only the *selection* of the second expert (reference RSample);
        # combine weights below still come from the clean gate probabilities.
        noisy = gates_wo1 + jax.random.gumbel(rng, gates_wo1.shape) * 1e-2
        idx2 = jnp.argmax(jnp.where(mask1 > 0, -jnp.inf, noisy), axis=-1)
    else:
        idx2 = jnp.argmax(gates_wo1, axis=-1)
    mask2 = _one_hot(idx2, E)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    pos1 = jnp.cumsum(mask1, axis=0) * mask1
    pos2 = (jnp.cumsum(mask2, axis=0) + jnp.sum(mask1, axis=0, keepdims=True)) * mask2
    keep1 = (pos1 <= C) & (mask1 > 0)
    keep2 = (pos2 <= C) & (mask2 > 0)

    # renormalize over the experts that *survived* the capacity drop
    g1 = jnp.sum(gates * mask1, axis=-1) * jnp.any(keep1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1) * jnp.any(keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def build(keep, mask, pos, g):
        slot = jnp.sum((pos - 1.0) * mask, axis=-1).astype(jnp.int32)
        d = keep[..., None] * _one_hot(slot, C)[:, None, :]
        return d, d * g[:, None, None]

    d1, c1 = build(keep1, mask1, pos1, g1)
    d2, c2 = build(keep2, mask2, pos2, g2)
    dispatch = (d1 + d2) > 0
    combine = c1 + c2
    exp_counts = jnp.sum(mask1 + mask2, axis=0)
    return l_aux, dispatch, combine, exp_counts


def gating_drop_stats(dispatch, exp_counts):
    """Capacity-overflow accounting from a gating result.

    Returns f32 scalars {routed, kept, overflow_tokens, dropped_frac}:
    `routed` = token→expert assignments the router made, `kept` = assignments
    that fit under capacity, the rest overflowed (token masked to zero output
    for top-1; weight renormalized away for top-2). These feed the `moe/*`
    telemetry gauges.
    """
    routed = jnp.sum(exp_counts).astype(jnp.float32)
    kept = jnp.sum(dispatch.astype(jnp.float32))
    overflow = routed - kept
    return {
        "routed": routed,
        "kept": kept,
        "overflow_tokens": overflow,
        "dropped_frac": overflow / jnp.maximum(routed, 1.0),
    }


# ----------------------------------------------------------------------
# facade-routed expert dispatch (shard_map over the expert mesh axis)
# ----------------------------------------------------------------------


def _expert_token_axes(mesh):
    """Mesh axes the flattened token dim shards over in the facade path."""
    names = tuple(BATCH_AXES) + (EXPERT_AXIS,)
    return tuple(a for a in names if a in mesh.shape)


def can_use_expert_shard_map(mesh, num_experts, num_tokens):
    """True iff `expert_parallel_moe` covers this (mesh, problem) combo:
    expert axis > 1, experts and tokens divide evenly, and no tensor/
    sequence/pipe sharding (those compositions stay on the einsum path)."""
    if mesh is None:
        return False
    shape = dict(mesh.shape)
    if shape.get(EXPERT_AXIS, 1) <= 1:
        return False
    if num_experts % shape[EXPERT_AXIS] != 0:
        return False
    token_axes = _expert_token_axes(mesh)
    for name, size in shape.items():
        if name not in token_axes and size != 1:
            return False
    n_shards = int(np.prod([shape[a] for a in token_axes]))
    return num_tokens % n_shards == 0


def expert_parallel_moe(flat, gate_w, expert_params, ffn_fn, mesh, *,
                        num_experts, capacity_factor, min_capacity=4, k=1,
                        noisy_gate_policy=None, rng=None,
                        dispatch_wire="none",
                        wire_group_size=coll.DEFAULT_GROUP_SIZE):
    """Expert dispatch through the comm facade's instrumented all_to_all.

    flat: [N, D] tokens (N sharded over data×zero×expert jointly); gate_w:
    [D, E] (replicated); expert_params: pytree whose every leaf has leading
    dim E (sharded over the `expert` axis inside the body); ffn_fn(xe,
    local_params) maps [E_local, T, D] → [E_local, T, D] and must not issue
    sharding constraints (it runs under manual sharding).

    Per shard: local gating (capacity from the *local* token count) → dispatch
    einsum [E, C, D] → facade all_to_all (split experts, concat capacity) →
    local expert FFN → reverse all_to_all → combine. ``dispatch_wire="int8"``
    quantizes both exchanges groupwise (ZeRO++ qgZ on activations).

    Returns (out [N, D], l_aux, exp_counts [E], stats dict) — l_aux is the
    shard-mean aux loss, counts/stats are summed over shards, all replicated.
    """
    N, D = flat.shape
    E = num_experts
    shape = dict(mesh.shape)
    ep = shape.get(EXPERT_AXIS, 1)
    if E % ep != 0:
        raise ValueError(
            f"expert_parallel_moe: num_experts={E} not divisible by expert "
            f"axis size {ep}")
    token_axes = _expert_token_axes(mesh)
    n_shards = int(np.prod([shape[a] for a in token_axes]))
    if N % n_shards != 0:
        raise ValueError(
            f"expert_parallel_moe: {N} tokens not divisible by the "
            f"{n_shards}-way token sharding over mesh axes {token_axes}")
    for name, size in shape.items():
        if name not in token_axes and size != 1:
            raise ValueError(
                f"expert_parallel_moe: mesh axis {name!r} has size {size}; "
                "tensor/sequence/pipe sharding composes via the einsum "
                "fallback path, not the shard_map dispatch")

    def local(flat_l, gate_w_l, eparams_l):
        r = rng
        if r is not None:
            for a in token_axes:
                r = jax.random.fold_in(r, jax.lax.axis_index(a))
        logits = flat_l.astype(jnp.float32) @ gate_w_l.astype(jnp.float32)
        if k == 1:
            l_aux, dispatch, combine, counts = top1_gating(
                logits, capacity_factor, min_capacity, noisy_gate_policy, r)
        else:
            l_aux, dispatch, combine, counts = top2_gating(
                logits, capacity_factor, min_capacity, r)
        drop = gating_drop_stats(dispatch, counts)

        # [n_loc, E, C] x [n_loc, D] → [E, C, D] expert slots, then the wire:
        # split the expert dim across the axis, concat peers' slots — each
        # expert shard now holds its E/ep experts' tokens from every peer.
        xe = jnp.einsum("nec,nd->ecd", dispatch.astype(flat_l.dtype), flat_l)
        xe = coll.transform_all_to_all(
            xe, EXPERT_AXIS, split_axis=0, concat_axis=1,
            transform=dispatch_wire, group_size=wire_group_size,
            out_dtype=flat_l.dtype)                    # [E/ep, ep*C, D]
        ye = ffn_fn(xe, eparams_l)
        ye = coll.transform_all_to_all(
            ye, EXPERT_AXIS, split_axis=1, concat_axis=0,
            transform=dispatch_wire, group_size=wire_group_size,
            out_dtype=flat_l.dtype)                    # [E, C, D]
        out = jnp.einsum("nec,ecd->nd", combine.astype(flat_l.dtype), ye)

        l_aux = coll.pmean(l_aux, token_axes)
        counts = coll.psum(counts, token_axes)
        routed = coll.psum(drop["routed"], token_axes)
        kept = coll.psum(drop["kept"], token_axes)
        return out, l_aux, counts, routed, kept

    ep_specs = jax.tree_util.tree_map(
        lambda a: P(EXPERT_AXIS, *([None] * (a.ndim - 1))), expert_params)
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(token_axes, None), P(None, None), ep_specs),
        out_specs=(P(token_axes, None), P(), P(), P(), P()),
        check_vma=False)
    out, l_aux, exp_counts, routed, kept = fn(flat, gate_w, expert_params)
    stats = {
        "routed": routed,
        "kept": kept,
        "overflow_tokens": routed - kept,
        "dropped_frac": (routed - kept) / jnp.maximum(routed, 1.0),
    }
    return out, l_aux, exp_counts, stats


# ----------------------------------------------------------------------
# dropless variant (Pallas token sort)
# ----------------------------------------------------------------------


def dropless_moe(flat, gate_w, ffn_fn, num_experts, *, interpret=None):
    """Capacity-free top-1 MoE: no token is ever dropped.

    The Pallas token sort kernel ranks each token within its expert's queue
    (stable counting sort); tokens scatter into an [E, N, D] buffer — N is
    the only static capacity bound that can never overflow — and gather back
    after the expert FFN. Memory is E·N·D, so this is for moderate N (the
    capacity path is the at-scale default).

    Returns (out [N, D], l_aux, exp_counts [E]).
    """
    from deepspeed_tpu.ops.pallas.token_sort import token_sort

    N, D = flat.shape
    E = num_experts
    logits = flat.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)
    gate_val = jnp.max(gates, axis=-1).astype(flat.dtype)

    mask1 = _one_hot(expert_idx, E)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E
    exp_counts = jnp.sum(mask1, axis=0)

    pos, _counts = token_sort(expert_idx, E, interpret=interpret)
    xe = jnp.zeros((E, N, D), flat.dtype).at[expert_idx, pos].set(flat)
    xe = shard_constraint(xe, EXPERT_AXIS, None, None)
    ye = ffn_fn(xe)
    out = ye[expert_idx, pos] * gate_val[:, None]
    return out, l_aux, exp_counts


@dataclasses.dataclass
class MoELayer:
    """Functional expert-parallel FFN layer.

    Params layout (stacked over experts, expert dim sharded on the `expert` axis):
      {"gate_w": [D, E], "wi": [E, D, F], "wo": [E, F, D]}  (+ optional biases)

    Call: (params, x[B,S,D], rng) -> (y[B,S,D], l_aux, exp_counts). Pass
    ``mesh=`` to route dispatch through the comm facade's all_to_all inside
    shard_map (when `can_use_expert_shard_map` holds); otherwise the einsum
    fallback runs. ``dropless=True`` switches to the token-sort path.
    """
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    activation: Callable = jax.nn.gelu
    use_residual: bool = False     # residual MoE (DS-MoE paper)
    dropless: bool = False         # token-sort scatter, no capacity drops
    dispatch_wire: str = "none"    # WireTransform for the facade a2a pair

    def init_params(self, d_model, d_ff, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        E, D, F = self.num_experts, d_model, d_ff
        p = {
            "gate_w": jnp.asarray(rng.normal(0, 0.02, (D, E)), jnp.float32),
            "wi": jnp.asarray(rng.normal(0, 0.02, (E, D, F)), dtype),
            "wi_b": jnp.zeros((E, F), dtype),
            "wo": jnp.asarray(rng.normal(0, 0.02, (E, F, D)), dtype),
            "wo_b": jnp.zeros((E, D), dtype),
        }
        if self.use_residual:
            p["res_wi"] = jnp.asarray(rng.normal(0, 0.02, (D, F)), dtype)
            p["res_wo"] = jnp.asarray(rng.normal(0, 0.02, (F, D)), dtype)
            p["res_coef"] = jnp.asarray(rng.normal(0, 0.02, (D, 2)), jnp.float32)
        return p

    def param_specs(self):
        e, t = EXPERT_AXIS, TENSOR_AXIS
        specs = {
            "gate_w": P(None, None),
            "wi": P(e, None, t),
            "wi_b": P(e, t),
            "wo": P(e, t, None),
            "wo_b": P(e, None),
        }
        if self.use_residual:
            specs["res_wi"] = P(None, t)
            specs["res_wo"] = P(t, None)
            specs["res_coef"] = P(None, None)
        return specs

    def _ffn(self, xe, p, constrain=True):
        h = jnp.einsum("ecd,edf->ecf", xe, p["wi"]) + p["wi_b"][:, None, :]
        h = self.activation(h)
        if constrain:
            h = shard_constraint(h, EXPERT_AXIS, None, TENSOR_AXIS)
        return jnp.einsum("ecf,efd->ecd", h, p["wo"]) + p["wo_b"][:, None, :]

    def __call__(self, params, x, rng=None, training=True, mesh=None):
        B, S, D = x.shape
        E = self.num_experts
        N = B * S
        flat = x.reshape(N, D)
        cf = self.capacity_factor if training else self.eval_capacity_factor
        eparams = {k: params[k] for k in ("wi", "wi_b", "wo", "wo_b")}

        if self.dropless:
            y, l_aux, exp_counts = dropless_moe(
                flat, params["gate_w"], lambda xe: self._ffn(xe, eparams), E)
        elif can_use_expert_shard_map(mesh, E, N):
            y, l_aux, exp_counts, _stats = expert_parallel_moe(
                flat, params["gate_w"], eparams,
                lambda xe, p: self._ffn(xe, p, constrain=False), mesh,
                num_experts=E, capacity_factor=cf,
                min_capacity=self.min_capacity, k=self.k,
                noisy_gate_policy=self.noisy_gate_policy if training else None,
                rng=rng if training else None,
                dispatch_wire=self.dispatch_wire)
        else:
            logits = flat.astype(jnp.float32) @ params["gate_w"]
            if self.k == 1:
                l_aux, dispatch, combine, exp_counts = top1_gating(
                    logits, cf, self.min_capacity, self.noisy_gate_policy, rng)
            else:
                l_aux, dispatch, combine, exp_counts = top2_gating(
                    logits, cf, self.min_capacity, rng)

            # dispatch: [N,E,C] → expert inputs [E,C,D]; constraint puts E on the
            # expert mesh axis (XLA all-to-all = reference _AllToAll, sharded_moe.py:95)
            exp_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), flat)
            exp_in = shard_constraint(exp_in, EXPERT_AXIS, None, None)
            out = self._ffn(exp_in, eparams)
            out = shard_constraint(out, EXPERT_AXIS, None, None)
            y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), out)

        y = y.reshape(B, S, D)
        if self.use_residual:
            mlp = self.activation(x @ params["res_wi"]) @ params["res_wo"]
            coef = jax.nn.softmax(x.astype(jnp.float32) @ params["res_coef"], axis=-1)
            y = y * coef[..., 0:1].astype(x.dtype) + mlp * coef[..., 1:2].astype(x.dtype)
        return y, l_aux, exp_counts


class MoE:
    """API-parity wrapper (reference `moe/layer.py:16` signature)."""

    def __init__(self, hidden_size, expert=None, num_experts=1, ep_size=1, k=1,
                 capacity_factor=1.0, eval_capacity_factor=1.0, min_capacity=4,
                 use_residual=False, noisy_gate_policy=None, drop_tokens=True,
                 use_rts=True, use_tutel=False, enable_expert_tensor_parallelism=False):
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.layer = MoELayer(num_experts=num_experts, k=k,
                              capacity_factor=capacity_factor,
                              eval_capacity_factor=eval_capacity_factor,
                              min_capacity=min_capacity,
                              noisy_gate_policy=noisy_gate_policy,
                              use_residual=use_residual,
                              dropless=not drop_tokens)

    def init_params(self, d_ff, seed=0, dtype=jnp.float32):
        return self.layer.init_params(self.hidden_size, d_ff, seed=seed, dtype=dtype)

    def param_specs(self):
        return self.layer.param_specs()

    def __call__(self, params, hidden_states, rng=None, used_token=None, mesh=None):
        y, l_aux, exp_counts = self.layer(params, hidden_states, rng, mesh=mesh)
        return y, l_aux, exp_counts
