"""Mixture-of-Experts with expert parallelism, TPU-native.

Reference: `deepspeed/moe/` — `MoE` layer (`moe/layer.py:16`), `MOELayer` +
`top1gating`/`top2gating` with capacity/jitter/load-balance loss
(`moe/sharded_moe.py:184,282,425`), `_AllToAll` dispatch (:95), expert groups
(`utils/groups.py:113,207`).

TPU-native formulation (GShard-style, fully static shapes): gating produces
dispatch/combine tensors; token routing is einsum + a sharding constraint that
puts the expert dimension on the `expert` mesh axis — XLA emits the all-to-all
pair the reference issues by hand. Capacity overflow drops tokens by masking
(no dynamic shapes under jit — the "hard part" called out in SURVEY §7).
"""

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.mesh import EXPERT_AXIS, SEQ_AXIS, TENSOR_AXIS, shard_constraint


def _capacity(num_tokens, num_experts, capacity_factor, min_capacity):
    cap = int(np.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def top1_gating(logits, capacity_factor=1.0, min_capacity=4, noisy_gate_policy=None,
                rng=None, used_token_mask=None):
    """Top-1 gating (reference `top1gating`, `moe/sharded_moe.py:184`).

    logits: [N, E] (N = flattened tokens). Returns (l_aux, dispatch [N,E,C] bool,
    combine [N,E,C] float, exp_counts [E]).
    """
    N, E = logits.shape
    C = _capacity(N, E, capacity_factor, min_capacity)

    if noisy_gate_policy == "RSample" and rng is not None:
        logits = logits + jax.random.gumbel(rng, logits.shape) * 1e-2
    gates = jax.nn.softmax(logits, axis=-1)                       # [N, E]
    expert_idx = jnp.argmax(gates, axis=-1)                       # [N]
    mask1 = _one_hot(expert_idx, E)                               # [N, E]
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None]

    # load-balancing aux loss (me·ce formulation of the reference)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position of each token within its expert queue
    pos_in_expert = jnp.cumsum(mask1, axis=0) * mask1             # [N, E], 1-based
    keep = (pos_in_expert <= C) & (mask1 > 0)
    pos = (pos_in_expert - 1.0) * mask1                           # 0-based
    exp_counts = jnp.sum(mask1, axis=0)

    gate_val = jnp.sum(gates * mask1, axis=-1, keepdims=True)     # [N, 1]
    slot = jnp.sum(pos, axis=-1).astype(jnp.int32)                # [N] 0-based slot
    dispatch = keep[..., None] * _one_hot(slot, C)[:, None, :]    # [N, E, C]
    combine = dispatch * gate_val[..., None]
    return l_aux, dispatch.astype(jnp.bool_), combine, exp_counts


def top2_gating(logits, capacity_factor=1.0, min_capacity=4, rng=None):
    """Top-2 gating (reference `top2gating`, `moe/sharded_moe.py:282`) with
    renormalized top-2 weights and second-expert random tie-breaking jitter."""
    N, E = logits.shape
    C = _capacity(N, E, 2 * capacity_factor, min_capacity)

    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(idx1, E)
    gates_wo1 = gates * (1 - mask1)
    idx2 = jnp.argmax(gates_wo1, axis=-1)
    mask2 = _one_hot(idx2, E)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    pos1 = jnp.cumsum(mask1, axis=0) * mask1
    pos2 = (jnp.cumsum(mask2, axis=0) + jnp.sum(mask1, axis=0, keepdims=True)) * mask2
    keep1 = (pos1 <= C) & (mask1 > 0)
    keep2 = (pos2 <= C) & (mask2 > 0)

    g1 = jnp.sum(gates * mask1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    def build(keep, mask, pos, g):
        slot = jnp.sum((pos - 1.0) * mask, axis=-1).astype(jnp.int32)
        d = keep[..., None] * _one_hot(slot, C)[:, None, :]
        return d, d * g[:, None, None]

    d1, c1 = build(keep1, mask1, pos1, g1)
    d2, c2 = build(keep2, mask2, pos2, g2)
    dispatch = (d1 + d2) > 0
    combine = c1 + c2
    exp_counts = jnp.sum(mask1 + mask2, axis=0)
    return l_aux, dispatch, combine, exp_counts


@dataclasses.dataclass
class MoELayer:
    """Functional expert-parallel FFN layer.

    Params layout (stacked over experts, expert dim sharded on the `expert` axis):
      {"gate_w": [D, E], "wi": [E, D, F], "wo": [E, F, D]}  (+ optional biases)

    Call: (params, x[B,S,D], rng) -> (y[B,S,D], l_aux, exp_counts)
    """
    num_experts: int
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    activation: Callable = jax.nn.gelu
    use_residual: bool = False     # residual MoE (DS-MoE paper)

    def init_params(self, d_model, d_ff, seed=0, dtype=jnp.float32):
        rng = np.random.default_rng(seed)
        E, D, F = self.num_experts, d_model, d_ff
        p = {
            "gate_w": jnp.asarray(rng.normal(0, 0.02, (D, E)), jnp.float32),
            "wi": jnp.asarray(rng.normal(0, 0.02, (E, D, F)), dtype),
            "wi_b": jnp.zeros((E, F), dtype),
            "wo": jnp.asarray(rng.normal(0, 0.02, (E, F, D)), dtype),
            "wo_b": jnp.zeros((E, D), dtype),
        }
        if self.use_residual:
            p["res_wi"] = jnp.asarray(rng.normal(0, 0.02, (D, F)), dtype)
            p["res_wo"] = jnp.asarray(rng.normal(0, 0.02, (F, D)), dtype)
            p["res_coef"] = jnp.asarray(rng.normal(0, 0.02, (D, 2)), jnp.float32)
        return p

    def param_specs(self):
        from jax.sharding import PartitionSpec as P
        e, t = EXPERT_AXIS, TENSOR_AXIS
        specs = {
            "gate_w": P(None, None),
            "wi": P(e, None, t),
            "wi_b": P(e, t),
            "wo": P(e, t, None),
            "wo_b": P(e, None),
        }
        if self.use_residual:
            specs["res_wi"] = P(None, t)
            specs["res_wo"] = P(t, None)
            specs["res_coef"] = P(None, None)
        return specs

    def __call__(self, params, x, rng=None, training=True):
        B, S, D = x.shape
        E = self.num_experts
        N = B * S
        flat = x.reshape(N, D)

        logits = flat.astype(jnp.float32) @ params["gate_w"]
        cf = self.capacity_factor if training else self.eval_capacity_factor
        if self.k == 1:
            l_aux, dispatch, combine, exp_counts = top1_gating(
                logits, cf, self.min_capacity, self.noisy_gate_policy, rng)
        else:
            l_aux, dispatch, combine, exp_counts = top2_gating(
                logits, cf, self.min_capacity, rng)

        # dispatch: [N,E,C] → expert inputs [E,C,D]; constraint puts E on the
        # expert mesh axis (XLA all-to-all = reference _AllToAll, sharded_moe.py:95)
        exp_in = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), flat)
        exp_in = shard_constraint(exp_in, EXPERT_AXIS, None, None)

        h = jnp.einsum("ecd,edf->ecf", exp_in, params["wi"]) + params["wi_b"][:, None, :]
        h = self.activation(h)
        h = shard_constraint(h, EXPERT_AXIS, None, TENSOR_AXIS)
        out = jnp.einsum("ecf,efd->ecd", h, params["wo"]) + params["wo_b"][:, None, :]
        out = shard_constraint(out, EXPERT_AXIS, None, None)

        y = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), out)
        y = y.reshape(B, S, D)

        if self.use_residual:
            mlp = self.activation(x @ params["res_wi"]) @ params["res_wo"]
            coef = jax.nn.softmax(x.astype(jnp.float32) @ params["res_coef"], axis=-1)
            y = y * coef[..., 0:1].astype(x.dtype) + mlp * coef[..., 1:2].astype(x.dtype)
        return y, l_aux, exp_counts


class MoE:
    """API-parity wrapper (reference `moe/layer.py:16` signature)."""

    def __init__(self, hidden_size, expert=None, num_experts=1, ep_size=1, k=1,
                 capacity_factor=1.0, eval_capacity_factor=1.0, min_capacity=4,
                 use_residual=False, noisy_gate_policy=None, drop_tokens=True,
                 use_rts=True, use_tutel=False, enable_expert_tensor_parallelism=False):
        assert drop_tokens, "dropless MoE arrives with the pallas sort kernels"
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.layer = MoELayer(num_experts=num_experts, k=k,
                              capacity_factor=capacity_factor,
                              eval_capacity_factor=eval_capacity_factor,
                              min_capacity=min_capacity,
                              noisy_gate_policy=noisy_gate_policy,
                              use_residual=use_residual)

    def init_params(self, d_ff, seed=0, dtype=jnp.float32):
        return self.layer.init_params(self.hidden_size, d_ff, seed=seed, dtype=dtype)

    def param_specs(self):
        return self.layer.param_specs()

    def __call__(self, params, hidden_states, rng=None, used_token=None):
        y, l_aux, exp_counts = self.layer(params, hidden_states, rng)
        return y, l_aux, exp_counts
