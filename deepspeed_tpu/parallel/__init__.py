from deepspeed_tpu.parallel.ulysses import DistributedAttention, ulysses_attention
from deepspeed_tpu.parallel.moe import MoE, MoELayer, top1_gating, top2_gating
from deepspeed_tpu.parallel.tp import (
    column_parallel_spec,
    row_parallel_spec,
    plan_tp_specs,
    TiledLinear,
)
