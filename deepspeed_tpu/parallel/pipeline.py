"""Pipeline parallelism — looped SPMD pipelining over the `pipe` mesh axis.

Reference: `runtime/pipe/` (3.1k LoC) — `PipelineModule` (`pipe/module.py:130`,
LayerSpec list partitioned by parameters/uniform), `PipelineEngine`
(`pipe/engine.py:55`) interpreting instruction schedules (`pipe/schedule.py:189`
TrainSchedule/1F1B) with explicit P2P (`pipe/p2p.py`).

TPU-native formulation: ONE compiled SPMD program. Stage parameters are stacked
[PP, layers_per_stage, ...] and sharded on `pipe`; a schedule is a `lax.scan`
of ticks inside `shard_map`; stage handoff is a `ppermute` shift — the
instruction stream, P2P meta exchange and schedule interpreter of the
reference collapse into this loop. Two schedules:

* `pipeline_loss_fn` — fill-drain (GPipe) forward; backward by autodiff
  through the scan (O(M) live activations, used for eval / as a fallback).
* `pipeline_grad_fn` — 1F1B training schedule (reference `TrainSchedule`,
  `pipe/schedule.py:189`): forward and delayed backward micro-steps
  interleaved in one scan, stage inputs stashed in a 2*PP ring buffer,
  backward recomputed via `jax.vjp` — O(PP) live activations.

Embedding lives on stage 0, LM head + loss on the last stage; their params are
replicated over `pipe` but their compute runs under `lax.cond` on the owning
stage only. Bubble overhead is the standard (PP-1)/M fill-drain cost.
"""

import dataclasses
from functools import partial
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import collectives as coll
from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.comm.mesh import (BATCH_AXES, DATA_AXIS, PIPE_AXIS, SEQ_AXIS,
                                     TENSOR_AXIS, ZERO_INNER_AXIS)
from deepspeed_tpu.utils.logging import logger


# ----------------------------------------------------------------------
# LayerSpec-style container (API parity with deepspeed.pipe)
# ----------------------------------------------------------------------


@dataclasses.dataclass
class LayerSpec:
    """Deferred layer (reference `deepspeed/pipe` LayerSpec): builds params lazily
    so each stage only materializes its own layers."""
    init_fn: Callable[..., Any]       # () -> params
    apply_fn: Callable[..., Any]      # (params, x) -> x
    name: str = "layer"


class TiedLayerSpec(LayerSpec):
    """Weight tying across stages (reference TiedLayerSpec) — realized here by
    replicating the tied params over `pipe` and psum-ing their grads, which is
    what the reference's tied-weight allreduce does (`pipe/engine.py:266`)."""

    def __init__(self, key, init_fn, apply_fn, name="tied"):
        super().__init__(init_fn, apply_fn, name)
        self.key = key


def partition_layers(n_layers, n_stages, method="uniform", costs=None, names=None):
    """Layer → stage assignment (reference `PipelineModule` partition methods
    `module.py:370-386`): 'uniform' (equal counts), 'parameters' (balance by
    per-layer cost), or 'type:regex' (balance the count of layers whose name
    matches the regex; non-matching layers ride along with their stage —
    reference `module.py:385`)."""
    if method.startswith("type:"):
        import re
        if names is None:
            raise ValueError(
                "type: regex partitioning needs layer names — pass names=[...] "
                "(the reference matches layer class names, pipe/module.py:385)")
        pattern = re.compile(method[len("type:"):])
        weights = [1.0 if pattern.search(str(n)) else 0.0 for n in names]
        if sum(weights) == 0:
            raise ValueError(f"no layer name matches {method!r}: {names}")
        return partition_layers(n_layers, n_stages, "parameters", costs=weights)
    if method == "parameters" and costs is not None:
        costs = np.asarray(costs, dtype=np.float64)
        target = costs.sum() / n_stages
        bounds = [0]
        acc = 0.0
        for i, c in enumerate(costs):
            acc += c
            if acc >= target * len(bounds) and len(bounds) < n_stages:
                bounds.append(i + 1)
        while len(bounds) < n_stages:
            bounds.append(n_layers)
        bounds.append(n_layers)
        return [(bounds[i], bounds[i + 1]) for i in range(n_stages)]
    per = n_layers // n_stages
    rem = n_layers % n_stages
    out, start = [], 0
    for s in range(n_stages):
        n = per + (1 if s < rem else 0)
        out.append((start, start + n))
        start += n
    return out


def bubble_fraction(num_stages, num_microbatches, schedule="1f1b"):
    """Idle-tick fraction of the pipeline schedule.

    Both loops here run `n_ticks` scan iterations while only M of them do
    useful work per stage, so the bubble is (n_ticks - M) / n_ticks:

      gpipe (fill-drain forward): n_ticks = M + PP - 1  → (PP-1)/(M+PP-1)
      1f1b  (TrainSchedule):      n_ticks = M + 2PP - 1 → (2PP-1)/(M+2PP-1)

    (The 1F1B loop interleaves one forward AND one backward micro-step per
    tick, so its tick count — and bubble — spans the combined fwd+bwd
    schedule; the classic (PP-1)/M figure is this same quantity for the
    fwd-only fill-drain loop at large M.)
    """
    PP, M = int(num_stages), int(num_microbatches)
    if PP < 1 or M < 1:
        raise ValueError(f"num_stages={PP} and num_microbatches={M} must be >= 1")
    schedule = schedule.lower()
    if schedule == "1f1b":
        n_ticks = M + 2 * PP - 1
    elif schedule == "gpipe":
        n_ticks = M + PP - 1
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         "expected '1f1b' or 'gpipe'")
    return float(n_ticks - M) / float(n_ticks)


# ----------------------------------------------------------------------
# the looped pipeline program
# ----------------------------------------------------------------------


def _block_specs(params, block_tp_specs=None):
    """blocks-leaf PartitionSpecs: leading dim on `pipe`, optional TP tails
    (one composition point for the outer param specs AND shard_map in_specs —
    they must never disagree or every step pays a reshard)."""
    if block_tp_specs is None:
        return jax.tree_util.tree_map(
            lambda l: P(*([PIPE_AXIS] + [None] * (l.ndim - 1))), params["blocks"])
    return jax.tree_util.tree_map(
        lambda l, s: P(*([PIPE_AXIS] + list(tuple(s)))),
        params["blocks"], block_tp_specs)


def _pipe_inner_specs(params, block_tp_specs=None):
    """shard_map in_specs for the pipeline param layout (embed/head replicated,
    blocks leading-dim sharded on pipe) — one source of truth for both the
    training (1F1B) and inference schedules.

    `block_tp_specs`: optional tree matching params["blocks"] whose leaves are
    PartitionSpecs WITHOUT the leading layer dim (Megatron TP tails, e.g.
    P(None, "tensor") for a column-parallel [D, F] weight) — composed as
    P(pipe, *tail) for 3D pp x tp (x dp/zero outside)."""
    return {
        "embed": jax.tree_util.tree_map(lambda _: P(), params["embed"]),
        "blocks": _block_specs(params, block_tp_specs),
        "head": jax.tree_util.tree_map(lambda _: P(), params["head"]),
    }


# ----------------------------------------------------------------------
# Megatron-style tensor parallelism INSIDE the pipeline stage
# ----------------------------------------------------------------------


@jax.custom_vjp
def _tp_copy(x):
    """Megatron's `f` operator at a TP branch input: identity forward,
    all-reduce (psum over `tensor`) backward — the branch's column-parallel
    consumers each see the full activation, and its cotangent re-assembles
    the full gradient before flowing into the replicated region (reference
    equivalent: megatron's copy_to_tensor_model_parallel_region; the row
    outputs' forward psum plays `g`, whose transpose is identity)."""
    return x


def _tp_copy_fwd(x):
    return x, None


def _tp_copy_bwd(_, g):
    return (jax.lax.psum(g, TENSOR_AXIS),)


_tp_copy.defvjp(_tp_copy_fwd, _tp_copy_bwd)


@jax.custom_vjp
def _tp_reduce(x):
    """Megatron's `g` operator at a TP row-parallel output: psum forward,
    IDENTITY backward. Must be a custom_vjp: under shard_map(check_vma=False)
    a raw `lax.psum` transposes to psum again (the unchecked-replication
    transpose rule), which double-counts every TP cotangent by a factor of
    tp — measured as exactly-2x weight grads at tp=2 before this wrapper."""
    return jax.lax.psum(x, TENSOR_AXIS)


def _tp_reduce_fwd(x):
    return jax.lax.psum(x, TENSOR_AXIS), None


def _tp_reduce_bwd(_, g):
    return (g,)


_tp_reduce.defvjp(_tp_reduce_fwd, _tp_reduce_bwd)


def make_tp_block_fn(cfg, tp):
    """Transformer block over TENSOR-SHARDED leaves inside a fully-manual
    shard_map (pipeline stages): column-parallel q/k/v/up (separate leaves —
    a fused qkv dim cannot be evenly chunked into per-rank q|k|v runs), heads
    computed locally, row-parallel out/down followed by an explicit psum over
    `tensor`. LayerNorms run replicated; `_tp_copy` at each branch input
    makes their backward exact. Activation layout between blocks: replicated
    over `tensor` (classic Megatron; sequence-parallel LN sharding composes
    via the `sequence` axis outside).

    Supported config subset under TP is asserted in `split_block_params`."""
    from deepspeed_tpu.models.gpt import _attention, _norm, _rope, _act

    Hl = cfg.n_head // tp
    Hkvl = cfg.n_kv_head // tp
    hd = cfg.head_dim
    lcfg = dataclasses.replace(cfg, use_flash_attention=False)

    def block_fn(p, x, rng):
        B, T, D = x.shape
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

        h = _norm(x, p["ln1_scale"], p.get("ln1_bias"), cfg.use_rmsnorm,
                  cfg.norm_eps)
        h = _tp_copy(h)
        q = (h @ p["attn_q_w"] + p["attn_q_b"]).reshape(B, T, Hl, hd)
        k = (h @ p["attn_k_w"] + p["attn_k_b"]).reshape(B, T, Hkvl, hd)
        v = (h @ p["attn_v_w"] + p["attn_v_b"]).reshape(B, T, Hkvl, hd)
        if cfg.use_rotary:
            rd = int(cfg.rotary_pct * hd) // 2 * 2
            q = _rope(q, positions, rd, cfg.rope_theta)
            k = _rope(k, positions, rd, cfg.rope_theta)
        causal = jnp.tril(jnp.ones((T, T), bool))[None, None]
        attn = _attention(q, k, v, causal, lcfg)           # local heads
        attn_o = attn.reshape(B, T, Hl * hd) @ p["attn_out_w"]  # row parallel
        attn_o = _tp_reduce(attn_o) + p["attn_out_b"]

        use_rms = cfg.use_rmsnorm
        if cfg.parallel_residual:
            h2 = _norm(x, p["ln2_scale"], p.get("ln2_bias"), use_rms, cfg.norm_eps)
        else:
            x = x + attn_o
            h2 = _norm(x, p["ln2_scale"], p.get("ln2_bias"), use_rms, cfg.norm_eps)
        h2 = _tp_copy(h2)
        if cfg.use_swiglu:
            up = jax.nn.silu(h2 @ p["mlp_gate_w"]) * (h2 @ p["mlp_up_w"])
        else:
            up = _act(h2 @ p["mlp_up_w"] + p["mlp_up_b"], cfg)
        down = _tp_reduce(up @ p["mlp_down_w"]) + p["mlp_out_b"]
        if cfg.parallel_residual:
            return x + attn_o + down
        return x + down

    return block_fn


def split_block_params(cfg, blocks):
    """Fused-qkv stacked block params → the TP layout (separate q/k/v leaves).

    The fused [L, D, (H+2Hkv)*hd] weight cannot shard its output dim over
    `tensor`: equal chunks straddle the q|k|v boundaries. Splitting restores
    clean per-leaf column sharding; `checkpoint/universal.py` already
    converts fused↔split qkv orderings for resharding."""
    assert not cfg.use_alibi, "alibi slopes need global head indices under TP"
    assert cfg.attn_layer_types is None and not cfg.sliding_window, \
        "per-layer local attention is not wired for the TP pipeline block yet"
    H, Hkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    out = dict(blocks)
    qkv_w = out.pop("attn_qkv_w")
    qkv_b = out.pop("attn_qkv_b")
    q_end, k_end = H * hd, (H + Hkv) * hd
    out["attn_q_w"], out["attn_k_w"], out["attn_v_w"] = (
        qkv_w[..., :q_end], qkv_w[..., q_end:k_end], qkv_w[..., k_end:])
    out["attn_q_b"], out["attn_k_b"], out["attn_v_b"] = (
        qkv_b[..., :q_end], qkv_b[..., q_end:k_end], qkv_b[..., k_end:])
    return out


def tp_block_specs(cfg, blocks_split):
    """PartitionSpec tails (no layer dim) for the split TP block layout."""
    t = TENSOR_AXIS
    col_w, col_b = P(None, t), P(t)
    row_w, rep_v, rep_b = P(t, None), P(None), P(None)
    specs = {
        "ln1_scale": rep_v, "ln2_scale": rep_v,
        "attn_q_w": col_w, "attn_k_w": col_w, "attn_v_w": col_w,
        "attn_q_b": col_b, "attn_k_b": col_b, "attn_v_b": col_b,
        "attn_out_w": row_w, "attn_out_b": rep_b, "mlp_out_b": rep_b,
    }
    if not cfg.use_rmsnorm:
        specs["ln1_bias"] = rep_v
        specs["ln2_bias"] = rep_v
    if cfg.use_swiglu:
        specs["mlp_gate_w"] = col_w
        specs["mlp_up_w"] = col_w
        specs["mlp_down_w"] = row_w
    else:
        specs["mlp_up_w"] = col_w
        specs["mlp_up_b"] = col_b
        specs["mlp_down_w"] = row_w
    assert set(specs) == set(blocks_split), (
        sorted(set(blocks_split) ^ set(specs)))
    return specs


def make_ulysses_block_fn(cfg, sp):
    """Transformer block with DeepSpeed-Ulysses sequence parallelism INSIDE the
    pipeline stage: activations arrive sequence-sharded [B, T/sp, D]; q/k/v are
    computed locally, the Ulysses all-to-all sandwich (reference
    `sequence/layer.py:15` `_SeqAllToAll`) trades the sequence shard for a head
    shard, attention runs over the FULL sequence with H/sp local heads, and the
    output trades back. RoPE is applied BEFORE the all-to-all using global
    positions (axis_index(sequence) * T_local offset), so rotary phases match
    the unsharded model exactly.

    Composes pipe × data × sequence: the `pipe` axis is handled by the outer
    schedule, `sequence` by this block. Mutually exclusive with in-stage TP
    (asserted by the caller): both re-shard heads and would fight over them."""
    from deepspeed_tpu.models.gpt import _attention, _norm, _rope, _act
    from deepspeed_tpu.parallel.ulysses import seq_all_to_all

    assert not cfg.use_alibi, "alibi slopes need global head indices under Ulysses"
    assert cfg.attn_layer_types is None and not cfg.sliding_window, \
        "per-layer local attention is not wired for the Ulysses pipeline block yet"
    H, Hkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    lcfg = dataclasses.replace(cfg, use_flash_attention=False)

    def block_fn(p, x, rng):
        B, Tl, D = x.shape
        t0 = jax.lax.axis_index(SEQ_AXIS) * Tl
        positions = jnp.broadcast_to(
            t0 + jnp.arange(Tl, dtype=jnp.int32)[None], (B, Tl))

        h = _norm(x, p["ln1_scale"], p.get("ln1_bias"), cfg.use_rmsnorm,
                  cfg.norm_eps)
        qkv = h @ p["attn_qkv_w"] + p["attn_qkv_b"]
        q, k, v = jnp.split(qkv, [H * hd, (H + Hkv) * hd], axis=-1)
        q = q.reshape(B, Tl, H, hd)
        k = k.reshape(B, Tl, Hkv, hd)
        v = v.reshape(B, Tl, Hkv, hd)
        if cfg.use_rotary:
            rd = int(cfg.rotary_pct * hd) // 2 * 2
            q = _rope(q, positions, rd, cfg.rope_theta)
            k = _rope(k, positions, rd, cfg.rope_theta)
        # sequence→head re-shard: [B, T/sp, H, hd] → [B, T, H/sp, hd]
        q = seq_all_to_all(q, scatter_axis=2, gather_axis=1)
        k = seq_all_to_all(k, scatter_axis=2, gather_axis=1)
        v = seq_all_to_all(v, scatter_axis=2, gather_axis=1)
        T = Tl * sp
        causal = jnp.tril(jnp.ones((T, T), bool))[None, None]
        attn = _attention(q, k, v, causal, lcfg)      # full seq, local heads
        # head→sequence re-shard back: [B, T, H/sp, hd] → [B, T/sp, H, hd]
        attn = seq_all_to_all(attn, scatter_axis=1, gather_axis=2)
        attn_o = attn.reshape(B, Tl, H * hd) @ p["attn_out_w"] + p["attn_out_b"]

        use_rms = cfg.use_rmsnorm
        if cfg.parallel_residual:
            h2 = _norm(x, p["ln2_scale"], p.get("ln2_bias"), use_rms, cfg.norm_eps)
        else:
            x = x + attn_o
            h2 = _norm(x, p["ln2_scale"], p.get("ln2_bias"), use_rms, cfg.norm_eps)
        if cfg.use_swiglu:
            up = jax.nn.silu(h2 @ p["mlp_gate_w"]) * (h2 @ p["mlp_up_w"])
        else:
            up = _act(h2 @ p["mlp_up_w"] + p["mlp_up_b"], cfg)
        down = up @ p["mlp_down_w"] + p["mlp_out_b"]
        if cfg.parallel_residual:
            return x + attn_o + down
        return x + down

    return block_fn


def _batch_specs(batch, seq_sharded=False):
    """shard_map in_specs for the batch: leading dim over the data domain,
    and — for sequence-parallel pipelines — dim 1 (time) over `sequence`."""
    def leaf(a):
        if seq_sharded and a.ndim >= 2:
            return P(BATCH_AXES, SEQ_AXIS)
        return P(BATCH_AXES)
    return jax.tree_util.tree_map(leaf, batch)


def _mb_view(batch, i, M):
    """Microbatch i of a microbatch-major local batch."""
    def slice_leaf(a):
        if a.shape[0] % M != 0:
            raise ValueError(
                f"pipeline batch leading dim {a.shape[0]} is not divisible by "
                f"num_microbatches={M}; trailing samples would be silently "
                f"dropped from the loss")
        return jax.lax.dynamic_slice_in_dim(a, i * (a.shape[0] // M),
                                            a.shape[0] // M, axis=0)
    return jax.tree_util.tree_map(slice_leaf, batch)


def _make_stage_apply(block_fn, blocks):
    """Apply this stage's stacked layers (scan over the local block slice)."""
    def stage_apply(x, rng):
        def layer_body(h, lp):
            return block_fn(lp, h, rng), None
        out, _ = jax.lax.scan(layer_body, x, blocks)
        return out
    return stage_apply


def pipeline_loss_fn(embed_fn, block_fn, head_loss_fn, num_stages,
                     num_microbatches, remat_blocks=True, block_tp_specs=None,
                     remat_prevent_cse=False, seq_sharded=False):
    """Builds loss_fn(params, batch, rng) running the pipelined schedule.

    params = {"embed": <replicated>, "blocks": <stacked [PP*Lp, ...] leaves,
    sharded on pipe via leading dim>, "head": <replicated>}

    * embed_fn(embed_params, micro_batch, rng) -> activation [mb, ...]
    * block_fn(layer_params, activation, rng) -> activation  (applied per layer)
    * head_loss_fn(full_params, activation, micro_batch, rng) -> scalar loss
      (gets the FULL params dict so tied embeddings read the single "embed" leaf —
      reference TiedLayerSpec semantics with one parameter instead of a
      replicate+allreduce pair)
    batch: pytree with leading dim M*mb (microbatch-major).
    """
    PP = num_stages
    M = num_microbatches
    if remat_blocks:
        # default False: block_fn runs inside the schedule scan, the
        # safe+faster placement (see GPTConfig.remat_prevent_cse)
        block_fn = jax.checkpoint(block_fn, prevent_cse=remat_prevent_cse)

    def local(params, batch, rng):
        # inside shard_map over ('pipe',): blocks leaf leading dim = layers/stage
        p_idx = jax.lax.axis_index(PIPE_AXIS)
        stage_apply = _make_stage_apply(block_fn, params["blocks"])

        def mb_view(i):
            return _mb_view(batch, i, M)

        mb0 = mb_view(0)
        act_shape = jax.eval_shape(embed_fn, params["embed"], mb0, rng)
        zeros_act = jnp.zeros(act_shape.shape, act_shape.dtype)

        n_ticks = M + PP - 1
        perm_fwd = [(j, j + 1) for j in range(PP - 1)]

        def tick(carry, t):
            buf, loss_sum, n_done = carry
            mb_idx = t - p_idx
            active = (mb_idx >= 0) & (mb_idx < M)
            # Stage 0 reads its microbatch; others read the handed-off
            # activation. Embed and head run under lax.cond so only the owning
            # stage pays their flops — safe because any collective inside a
            # branch (the sequence-parallel loss psum) runs over an axis whose
            # ranks all share the branch predicate; pipe ppermute/psum stay at
            # tick top level.
            mb_i = jnp.clip(t, 0, M - 1)
            x_in = jax.lax.cond(
                p_idx == 0,
                lambda: embed_fn(params["embed"], mb_view(mb_i), rng),
                lambda: buf)
            y = stage_apply(x_in, rng)
            y = jnp.where(active, y, zeros_act)
            # last stage: loss of its active microbatch (owner-only compute —
            # the [mb,T,d]x[d,V] head matmul is a large fraction of stage flops)
            out_idx = jnp.clip(t - (PP - 1), 0, M - 1)
            take = active & (p_idx == PP - 1)
            mb_loss = jax.lax.cond(
                take,
                lambda: head_loss_fn(params, y, mb_view(out_idx), rng).astype(
                    jnp.float32),
                lambda: jnp.asarray(0.0, jnp.float32))
            loss_sum = loss_sum + mb_loss
            n_done = n_done + jnp.where(take, 1, 0)
            buf = coll.ppermute(y, PIPE_AXIS, perm_fwd, repeats=n_ticks)
            return (buf, loss_sum, n_done), None

        (buf, loss_sum, n_done), _ = jax.lax.scan(
            tick, (zeros_act, jnp.asarray(0.0, jnp.float32), jnp.asarray(0, jnp.int32)),
            jnp.arange(n_ticks))
        # broadcast the mean loss to every pipe rank (reference _aggregate_total_loss)
        total = coll.psum(loss_sum, PIPE_AXIS)
        count = coll.psum(n_done, PIPE_AXIS)
        loss = total / jnp.maximum(count, 1)
        # mean over the data domain so grads of pipe-replicated leaves come out as
        # global-batch means
        return coll.pmean(loss, (DATA_AXIS, ZERO_INNER_AXIS, SEQ_AXIS))

    def loss_fn(params, batch, rng):
        mesh = mesh_mod.get_mesh()
        # batch stays data-sharded on its leading dim (composes PP × DP);
        # sequence-parallel models also shard the time dim over `sequence`
        batch_spec = _batch_specs(batch, seq_sharded)
        with mesh_mod.constraints_disabled():
            fn = shard_map(local, mesh=mesh,
                           in_specs=(_pipe_inner_specs(params, block_tp_specs),
                                     batch_spec, P()),
                           out_specs=P(), check_vma=False)
            return fn(params, batch, rng)

    return loss_fn


def pipeline_grad_fn(embed_fn, block_fn, head_loss_fn, num_stages,
                     num_microbatches, remat_blocks=True, block_tp_specs=None,
                     remat_prevent_cse=False, seq_sharded=False,
                     grad_reduce_transform="none"):
    """1F1B-structured pipelined (loss, grads) — reference `TrainSchedule`
    (`runtime/pipe/schedule.py:189`).

    One `lax.scan` interleaves a forward micro-step and a delayed backward
    micro-step per tick. Stage INPUTS are stashed in a ring buffer of 2*PP
    slots; the backward recomputes the stage forward inside `jax.vjp`, so live
    activation memory is O(PP) — independent of the microbatch count M.
    (GPipe/fill-drain autodiff through the scan keeps O(M) activations; this
    is the 1F1B memory bound the reference schedule exists for.)

    Schedule (stage s, microbatch i, PP stages):
      forward  of (i, s) at tick t = i + s
      backward of (i, s) at tick t = i + 2*PP - 1 - s
    Loss + head vjp run fused in the last stage's backward; cotangents hop
    stage s -> s-1 via reverse ppermute. Total ticks: M + 2*PP - 1; per tick
    each rank does one stage forward + one stage backward — the steady-state
    1F1B pattern. Embed/head/loss run under `lax.cond` so only the owning
    stage pays their flops (branches are collective-free).

    Returns grad_fn(params, batch, rng) -> (mean_loss, grads), grads in the
    pipeline layout (blocks pipe-sharded, embed/head replicated with tied
    contributions psummed over pipe — the reference's tied-weight allreduce),
    averaged over the data domain.
    """
    PP = num_stages
    M = num_microbatches
    R = 2 * PP  # ring slots; a stash entry lives 2*(PP-s)-1 < R ticks
    if grad_reduce_transform not in ("none", "int8"):
        raise ValueError(
            f"pipeline grad_reduce_transform must be one of ('none', 'int8'); "
            f"got {grad_reduce_transform!r} ('onebit' needs the persistent "
            f"error-feedback state the engine's onebit_gradients path carries)")
    if remat_blocks:
        # default False: block_fn runs inside the schedule scan, the
        # safe+faster placement (see GPTConfig.remat_prevent_cse)
        block_fn = jax.checkpoint(block_fn, prevent_cse=remat_prevent_cse)

    def local(params, batch, rng):
        p_idx = jax.lax.axis_index(PIPE_AXIS)
        blocks = params["blocks"]
        he = {"embed": params["embed"], "head": params["head"]}

        def stage_apply_with(blk, x):
            def layer_body(h, lp):
                return block_fn(lp, h, rng), None
            out, _ = jax.lax.scan(layer_body, x, blk)
            return out

        def mb_view(i):
            return _mb_view(batch, i, M)

        mb0 = mb_view(0)
        act_shape = jax.eval_shape(embed_fn, params["embed"], mb0, rng)
        zeros_act = jnp.zeros(act_shape.shape, act_shape.dtype)

        def zeros32(tree):
            return jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, jnp.float32), tree)

        carry0 = (
            zeros_act,                                   # fwd handoff buffer
            zeros_act,                                   # bwd cotangent buffer
            jnp.zeros((R,) + act_shape.shape, act_shape.dtype),  # input stash
            zeros32(blocks),                             # grad accum (blocks)
            zeros32(he),                                 # grad accum (embed/head)
            jnp.asarray(0.0, jnp.float32),               # loss sum
        )

        n_ticks = M + 2 * PP - 1
        perm_fwd = [(j, j + 1) for j in range(PP - 1)]
        perm_bwd = [(j, j - 1) for j in range(1, PP)]

        def tick(carry, t):
            fwd_buf, bwd_buf, xstash, gblocks, ghe, loss_sum = carry

            # ---- forward micro-step ------------------------------------
            f_idx = t - p_idx
            f_active = (f_idx >= 0) & (f_idx < M)
            mb_f = jnp.clip(f_idx, 0, M - 1)
            x_in = jax.lax.cond(
                p_idx == 0,
                lambda: embed_fn(params["embed"], mb_view(mb_f), rng),
                lambda: fwd_buf)
            y = stage_apply_with(blocks, x_in)
            y = jnp.where(f_active, y, zeros_act)
            f_slot = jnp.mod(f_idx, R)
            cur = jax.lax.dynamic_index_in_dim(xstash, f_slot, keepdims=False)
            xstash = jax.lax.dynamic_update_index_in_dim(
                xstash, jnp.where(f_active, x_in, cur), f_slot, 0)

            # ---- backward micro-step -----------------------------------
            b_idx = t - (2 * PP - 1 - p_idx)
            b_active = (b_idx >= 0) & (b_idx < M)
            mb_b = jnp.clip(b_idx, 0, M - 1)
            mbb = mb_view(mb_b)
            x_b = jax.lax.dynamic_index_in_dim(
                xstash, jnp.mod(b_idx, R), keepdims=False)

            def last_bwd():
                # loss + head vjp fused into the last stage's backward
                def f(blk, he_, x):
                    full = {"embed": he_["embed"], "blocks": blk,
                            "head": he_["head"]}
                    yy = stage_apply_with(blk, x)
                    return head_loss_fn(full, yy, mbb, rng).astype(jnp.float32)
                loss_i, vjp = jax.vjp(f, blocks, he, x_b)
                dblk, dhe, dx = vjp(jnp.asarray(1.0, jnp.float32))
                return loss_i, dblk, dhe, dx

            def mid_bwd():
                # cotangent for an invalid microbatch is always zero (zeros
                # propagate down from the last stage), so grads stay clean
                def f(blk, x):
                    return stage_apply_with(blk, x)
                _, vjp = jax.vjp(f, blocks, x_b)
                dblk, dx = vjp(bwd_buf)
                return (jnp.asarray(0.0, jnp.float32), dblk,
                        jax.tree_util.tree_map(jnp.zeros_like, he), dx)

            loss_i, dblk, dhe, dx = jax.lax.cond(
                b_active & (p_idx == PP - 1), last_bwd, mid_bwd)

            def emb_bwd():
                _, vjp = jax.vjp(lambda ep: embed_fn(ep, mbb, rng),
                                 params["embed"])
                (dep,) = vjp(dx)
                return dep

            dembed = jax.lax.cond(
                b_active & (p_idx == 0), emb_bwd,
                lambda: jax.tree_util.tree_map(jnp.zeros_like,
                                               params["embed"]))

            def add32(a, g):
                return a + g.astype(jnp.float32)

            gblocks = jax.tree_util.tree_map(add32, gblocks, dblk)
            ghe = jax.tree_util.tree_map(add32, ghe, dhe)
            ghe = {"embed": jax.tree_util.tree_map(add32, ghe["embed"], dembed),
                   "head": ghe["head"]}
            loss_sum = loss_sum + loss_i

            fwd_buf = coll.ppermute(y, PIPE_AXIS, perm_fwd, repeats=n_ticks)
            bwd_buf = coll.ppermute(dx, PIPE_AXIS, perm_bwd, repeats=n_ticks)
            return (fwd_buf, bwd_buf, xstash, gblocks, ghe, loss_sum), None

        (carry_out, _) = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
        _, _, _, gblocks, ghe, loss_sum = carry_out

        data_axes = (DATA_AXIS, ZERO_INNER_AXIS, SEQ_AXIS)
        inv_m = 1.0 / M

        def data_mean(g):
            # mean over the data domain. With a wire transform, the reduce is
            # hierarchical: plain psum rides the fast inner axes, the
            # compressed 2-hop wire rides the outermost (slow / DCN-tier)
            # data axis — the engine's explicit grad-reduce split
            # (zero.ZeroShardingPolicy.reduce_domain) applied to the
            # pipeline's post-schedule grad finish.
            if grad_reduce_transform == "none":
                return coll.pmean(g, data_axes)
            n_total, active = 1, []
            for a in data_axes:
                s = int(jax.lax.psum(1, a))
                n_total *= s
                if s > 1:
                    active.append(a)
            if not active:
                return g
            slow, fast = active[0], tuple(active[1:])
            if fast:
                g = coll.psum(g, fast)
            g = coll.compressed_all_reduce(g, slow, grad_reduce_transform)
            return g / n_total

        def finish_rep(g, p):  # replicated leaves: tied psum over pipe
            g = coll.psum(g * inv_m, PIPE_AXIS)
            return data_mean(g).astype(p.dtype)

        def finish_shard(g, p):  # pipe-sharded leaves stay per-stage
            return data_mean(g * inv_m).astype(p.dtype)

        grads = {
            "embed": jax.tree_util.tree_map(finish_rep, ghe["embed"],
                                            params["embed"]),
            "blocks": jax.tree_util.tree_map(finish_shard, gblocks, blocks),
            "head": jax.tree_util.tree_map(finish_rep, ghe["head"],
                                           params["head"]),
        }
        loss = coll.psum(loss_sum, PIPE_AXIS) * inv_m
        loss = coll.pmean(loss, data_axes)
        return loss, grads

    def grad_fn(params, batch, rng):
        mesh = mesh_mod.get_mesh()
        batch_spec = _batch_specs(batch, seq_sharded)
        specs = _pipe_inner_specs(params, block_tp_specs)
        with mesh_mod.constraints_disabled():
            fn = shard_map(local, mesh=mesh,
                           in_specs=(specs, batch_spec, P()),
                           out_specs=(P(), specs),
                           check_vma=False)
            return fn(params, batch, rng)

    return grad_fn


def pipeline_forward_fn(embed_fn, block_fn, head_fn, num_stages,
                        num_microbatches, block_tp_specs=None,
                        seq_sharded=False):
    """Pipelined forward-only schedule (reference `InferenceSchedule`,
    `runtime/pipe/schedule.py:135`): microbatches stream through the stages,
    the last stage applies `head_fn(params, act, micro_batch, rng) -> out
    [mb, ...]`, and the concatenated outputs are broadcast to every pipe rank
    (psum from the single contributing stage — the reference's result bcast).

    Returns forward(params, batch, rng) -> outputs with leading dim M*mb.
    """
    PP = num_stages
    M = num_microbatches

    def local(params, batch, rng):
        p_idx = jax.lax.axis_index(PIPE_AXIS)
        stage_apply = _make_stage_apply(block_fn, params["blocks"])

        def mb_view(i):
            return _mb_view(batch, i, M)

        mb0 = mb_view(0)
        act_shape = jax.eval_shape(embed_fn, params["embed"], mb0, rng)
        zeros_act = jnp.zeros(act_shape.shape, act_shape.dtype)
        out_shape = jax.eval_shape(head_fn, params, zeros_act, mb0, rng)
        out_buf0 = jnp.zeros((M * out_shape.shape[0],) + out_shape.shape[1:],
                             out_shape.dtype)

        n_ticks = M + PP - 1
        perm_fwd = [(j, j + 1) for j in range(PP - 1)]

        def tick(carry, t):
            buf, out_buf = carry
            mb_idx = t - p_idx
            active = (mb_idx >= 0) & (mb_idx < M)
            mb_i = jnp.clip(t, 0, M - 1)
            x_in = jax.lax.cond(
                p_idx == 0,
                lambda: embed_fn(params["embed"], mb_view(mb_i), rng),
                lambda: buf)
            y = stage_apply(x_in, rng)
            y = jnp.where(active, y, zeros_act)
            out_idx = jnp.clip(t - (PP - 1), 0, M - 1)
            take = active & (p_idx == PP - 1)
            out = jax.lax.cond(
                take,
                lambda: head_fn(params, y, mb_view(out_idx), rng),
                lambda: jnp.zeros(out_shape.shape, out_shape.dtype))
            start = out_idx * out.shape[0]
            cur = jax.lax.dynamic_slice_in_dim(out_buf, start, out.shape[0], axis=0)
            out_buf = jax.lax.dynamic_update_slice_in_dim(out_buf, cur + out,
                                                          start, axis=0)
            buf = coll.ppermute(y, PIPE_AXIS, perm_fwd, repeats=n_ticks)
            return (buf, out_buf), None

        (buf, out_buf), _ = jax.lax.scan(tick, (zeros_act, out_buf0),
                                         jnp.arange(n_ticks))
        # only the last stage wrote non-zeros; broadcast to all pipe ranks
        return coll.psum(out_buf, PIPE_AXIS)

    def forward(params, batch, rng=None):
        mesh = mesh_mod.get_mesh()
        shards = mesh_mod.axis_size(BATCH_AXES)
        lead = jax.tree_util.tree_leaves(batch)[0].shape[0]
        assert lead % (shards * M) == 0, (
            f"pipelined forward: batch dim {lead} must divide into "
            f"{shards} data shard(s) x {M} microbatches")
        batch_spec = _batch_specs(batch, seq_sharded)
        out_spec = P(BATCH_AXES, SEQ_AXIS) if seq_sharded else P(BATCH_AXES)
        with mesh_mod.constraints_disabled():
            fn = shard_map(local, mesh=mesh,
                           in_specs=(_pipe_inner_specs(params, block_tp_specs),
                                     batch_spec, P()),
                           out_specs=out_spec, check_vma=False)
            return fn(params, batch, rng)

    return forward


def pipeline_param_specs(params, block_tp_specs=None):
    """PartitionSpecs matching pipeline_loss_fn's layout (TP tails optional)."""
    blocks = _block_specs(params, block_tp_specs)
    return {
        "embed": jax.tree_util.tree_map(lambda l: P(*([None] * l.ndim)), params["embed"]),
        "blocks": blocks,
        "head": jax.tree_util.tree_map(lambda l: P(*([None] * l.ndim)), params["head"]),
    }


# ----------------------------------------------------------------------
# pipelined GPT (zoo integration)
# ----------------------------------------------------------------------


def make_gpt_pipeline_model(cfg=None, name="gpt2-pipe", num_stages=2,
                            num_microbatches=4, seed=0, schedule="1f1b",
                            tensor_parallel=None, sequence_parallel=None,
                            grad_reduce_transform="none"):
    """Pipeline-parallel GPT ModelSpec: blocks stacked [PP*Lp, ...] on `pipe`.

    schedule: "1f1b" (default — reference TrainSchedule memory bound) trains
    via `pipeline_grad_fn`; "gpipe" trains by autodiff through the fill-drain
    loss (O(M) activation memory, kept for comparison/debugging).

    tensor_parallel: Megatron TP degree INSIDE each stage (3D pp x tp x
    dp/zero — reference `runtime/pipe/topology.py:251`
    PipeModelDataParallelTopology). Default: the current mesh's `tensor`
    axis size. With tp > 1, block weights use the split-qkv TP layout and the
    stage body runs `make_tp_block_fn` (explicit psum collectives); embed and
    head stay tensor-replicated (their flops run once per tp rank — vocab
    parallelism is a future optimization).

    sequence_parallel: Ulysses degree INSIDE each stage (pipe × data ×
    sequence composition). Default: the current mesh's `sequence` axis size.
    With sp > 1, the batch arrives time-sharded, the stage body runs
    `make_ulysses_block_fn` (all-to-all head↔sequence re-sharding), and the
    batch MUST carry explicit "labels" (the next-token shift crosses shard
    boundaries). Mutually exclusive with tensor_parallel > 1.

    grad_reduce_transform: "none" | "int8" — wire encoding for the
    data-domain grad reduce in the 1F1B finish (qgZ over the outermost data
    axis; the engine's `explicit_grad_reduce` equivalent for models that
    bring their own grad_fn)."""
    from deepspeed_tpu.models.gpt import (GPTConfig, GPT2_CONFIGS, init_gpt_params,
                                          _block, _norm)
    from deepspeed_tpu.runtime.engine import ModelSpec

    cfg = cfg or GPT2_CONFIGS.get(name) or GPTConfig()
    assert cfg.n_layer % num_stages == 0, \
        f"n_layer {cfg.n_layer} must divide evenly into {num_stages} stages"
    if tensor_parallel is None:
        tensor_parallel = (mesh_mod.axis_size(TENSOR_AXIS)
                           if mesh_mod.has_mesh() else 1)
    tp = int(tensor_parallel)
    if sequence_parallel is None:
        sequence_parallel = (mesh_mod.axis_size(SEQ_AXIS)
                             if mesh_mod.has_mesh() else 1)
    sp = int(sequence_parallel)
    if tp > 1 and sp > 1:
        raise ValueError(
            f"in-stage tensor_parallel={tp} and sequence_parallel={sp} are "
            "mutually exclusive: both re-shard attention heads. Put the "
            "degrees on one axis, or compose Ulysses with ring attention "
            "(parallel/ring.py) outside the pipeline instead")
    raw = init_gpt_params(cfg, seed=seed)

    blocks = raw["blocks"]
    block_tp_specs = None
    if tp > 1:
        assert cfg.n_head % tp == 0 and cfg.n_kv_head % tp == 0, \
            f"n_head {cfg.n_head}/n_kv_head {cfg.n_kv_head} must divide tp={tp}"
        blocks = split_block_params(cfg, blocks)
        block_tp_specs = tp_block_specs(cfg, blocks)
    if sp > 1:
        assert cfg.n_head % sp == 0 and cfg.n_kv_head % sp == 0, \
            f"n_head {cfg.n_head}/n_kv_head {cfg.n_kv_head} must divide sp={sp}"

    params = {
        "embed": {"wte": raw["wte"], **({"wpe": raw["wpe"]} if not cfg.use_rotary else {})},
        "blocks": blocks,
        "head": {"lnf_scale": raw["lnf_scale"],
                 **({"lnf_bias": raw["lnf_bias"]} if not cfg.use_rmsnorm else {})},
    }
    if not cfg.tie_embeddings:
        params["head"]["lm_head"] = raw["lm_head"]

    def _embed_tokens(ep, tokens):
        T = tokens.shape[1]
        x = jnp.take(ep["wte"], tokens, axis=0).astype(cfg.dtype)
        if not cfg.use_rotary:
            # sequence-parallel: tokens are the LOCAL time chunk — absolute
            # positions start at this rank's global offset
            t0 = jax.lax.axis_index(SEQ_AXIS) * T if sp > 1 else 0
            pos = t0 + jnp.arange(T, dtype=jnp.int32)[None]
            x = x + jnp.take(ep["wpe"], pos, axis=0).astype(cfg.dtype)
        return x

    def _head_logits(full_params, x):
        hp = full_params["head"]
        head_w = hp.get("lm_head", full_params["embed"]["wte"])  # tied by default
        x = _norm(x, hp["lnf_scale"], hp.get("lnf_bias"), cfg.use_rmsnorm)
        return jnp.einsum("btd,vd->btv", x, head_w.astype(x.dtype))

    def embed_fn(ep, micro_batch, rng):
        # gpt_loss contract: explicit "labels" → tokens are already the
        # (possibly curriculum-transformed) inputs; otherwise shift in-place.
        tokens = micro_batch.get("tokens", micro_batch.get("input_ids"))
        if sp > 1 and micro_batch.get("labels") is None:
            raise ValueError(
                "sequence-parallel pipeline needs explicit 'labels': tokens "
                "are sharded over the `sequence` axis, so the next-token "
                "shift cannot be derived locally (each shard's boundary "
                "label lives on the neighbor rank)")
        inputs = tokens if micro_batch.get("labels") is not None else tokens[:, :-1]
        return _embed_tokens(ep, inputs)

    if tp > 1:
        block_fn = make_tp_block_fn(cfg, tp)
    elif sp > 1:
        block_fn = make_ulysses_block_fn(cfg, sp)
    else:
        def block_fn(lp, x, rng):
            B, T, D = x.shape
            positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
            return _block(x, lp, cfg=cfg, positions=positions)

    def head_loss_fn(full_params, x, micro_batch, rng):
        labels = micro_batch.get("labels")
        if labels is None:
            tokens = micro_batch.get("tokens", micro_batch.get("input_ids"))
            labels = tokens[:, 1:]
        logits = _head_logits(full_params, x).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        safe = jnp.maximum(labels, 0)
        gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        mask = (labels >= 0).astype(jnp.float32)
        num = jnp.sum((logz - gold) * mask)
        den = jnp.sum(mask)
        if sp > 1:
            # token-weighted mean over the sequence shards (this rank holds
            # only T/sp time steps). RAW lax.psum is load-bearing here: under
            # check_vma=False its transpose is psum again, scaling every
            # downstream cotangent by sp — which the finish pmean over
            # data_axes (sequence included) divides back out, turning the
            # per-shard grads into the SUM over sequence ranks that the true
            # gradient requires. A custom-vjp identity-backward psum would
            # undercount by exactly sp. The psum pair runs inside the
            # last-stage lax.cond, which is safe: the predicate is uniform
            # across the `sequence` axis (it depends only on the pipe index).
            num = jax.lax.psum(num, SEQ_AXIS)
            den = jax.lax.psum(den, SEQ_AXIS)
        return num / jnp.maximum(den, 1.0)

    loss_fn = pipeline_loss_fn(embed_fn, block_fn, head_loss_fn,
                               num_stages=num_stages,
                               num_microbatches=num_microbatches,
                               remat_blocks=cfg.remat,
                               block_tp_specs=block_tp_specs,
                               remat_prevent_cse=cfg.remat_prevent_cse,
                               seq_sharded=sp > 1)
    # training backward: 1F1B schedule (O(PP) live activations); the
    # fill-drain loss_fn above stays as the cheaper eval/forward-only path
    schedule = schedule.lower()
    if schedule not in ("1f1b", "gpipe"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         "expected '1f1b' or 'gpipe'")
    grad_fn = (pipeline_grad_fn(embed_fn, block_fn, head_loss_fn,
                                num_stages=num_stages,
                                num_microbatches=num_microbatches,
                                remat_blocks=cfg.remat,
                                block_tp_specs=block_tp_specs,
                                remat_prevent_cse=cfg.remat_prevent_cse,
                                seq_sharded=sp > 1,
                                grad_reduce_transform=grad_reduce_transform)
               if schedule == "1f1b" else None)
    if schedule == "gpipe" and grad_reduce_transform != "none":
        raise ValueError(
            "grad_reduce_transform requires the '1f1b' schedule (gpipe trains "
            "by autodiff through the fill-drain loss — no explicit grad finish "
            "to compress)")

    # pipelined inference forward (reference InferenceSchedule): full-sequence
    # logits, microbatches streamed through the stages
    def fwd_embed_fn(ep, micro_batch, rng):
        return _embed_tokens(ep, micro_batch["tokens"])

    def fwd_head_fn(full_params, x, micro_batch, rng):
        return _head_logits(full_params, x)

    pipelined_fwd = pipeline_forward_fn(fwd_embed_fn, block_fn, fwd_head_fn,
                                        num_stages=num_stages,
                                        num_microbatches=num_microbatches,
                                        block_tp_specs=block_tp_specs,
                                        seq_sharded=sp > 1)

    def apply_fn(params, tokens, rng=None):
        # uniform ModelSpec.apply_fn contract: raw [B, T] token array
        # (models/gpt.py gpt_forward signature); dict batches also accepted
        batch = tokens if isinstance(tokens, dict) else {"tokens": tokens}
        return pipelined_fwd(params, batch, rng)

    pipeline_info = {
        "num_stages": int(num_stages),
        "num_microbatches": int(num_microbatches),
        "schedule": schedule,
        "tensor_parallel": tp,
        "sequence_parallel": sp,
        "grad_reduce_transform": grad_reduce_transform,
        "bubble_fraction": bubble_fraction(num_stages, num_microbatches,
                                           schedule),
    }
    return ModelSpec(loss_fn=loss_fn, params=params, apply_fn=apply_fn,
                     grad_fn=grad_fn,
                     param_specs=pipeline_param_specs(params, block_tp_specs),
                     pipeline_info=pipeline_info,
                     name=name)
