"""Ring attention — context parallelism for long sequences.

The reference snapshot has NO context parallelism (SURVEY §2.3: "Ring attention /
context parallel — absent"); its long-sequence story is Ulysses + block-sparse
attention. On TPU, ring attention is the idiomatic long-context mechanism: each
sequence rank holds a KV shard, KV blocks rotate around the `sequence` ICI ring via
`ppermute` while every rank accumulates online-softmax partials of its Q shard —
compute and transfer overlap, memory stays O(T/sp).

Built from differentiable pieces (block attention + lax.scan + ppermute), so the
backward pass falls out of autodiff with rematerialization; the per-block inner
attention can be swapped for the Pallas flash kernel once its lse output is
threaded through (ops/pallas/flash_attention.py).
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS

NEG_INF = -1e30


def _block_attn_partial(q, k, v, q_offset, k_offset, causal, sm_scale):
    """Unnormalized block attention with running-max bookkeeping.
    q: [B, Tq, H, hd]; k,v: [B, Tk, H, hd] → (scores_max [B,H,Tq],
    exp-sum [B,H,Tq], weighted values [B,Tq,H,hd])."""
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)) * sm_scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(Tq)[:, None]
        k_pos = k_offset + jnp.arange(Tk)[None, :]
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m = -inf → p = exp(-inf - -inf) = nan; guard
    p = jnp.where(jnp.isfinite(m)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return m, l, o


def _can_use_flash(q, causal):
    """Flash inner blocks: long-enough 128-multiple local shards on a real
    backend (interpret-mode pallas on CPU is orders slower than einsum)."""
    Tl = q.shape[1]
    return (causal and Tl % 128 == 0 and Tl >= 1024
            and jax.default_backend() in ("tpu", "axon"))


def _ring_attention_local(q, k, v, axis_name, sp, causal, sm_scale,
                          use_flash=False):
    """Runs inside shard_map. q,k,v local: [B, Tl, H, hd].

    `use_flash=True` routes each ring step's block attention through the
    Pallas flash kernel (ops/pallas/flash_attention.py): ring blocks are
    whole contiguous shards, so every (q_shard, k_shard) pair is exactly one
    of three cases — DIAGONAL (src == mine: standard causal), PAST
    (src < mine: no mask), FUTURE (fully masked: skip, lse = -inf) — which
    avoids offset-aware masking inside the kernel entirely. Partials merge
    by (o, lse): out = Σ_i o_i · exp(lse_i − lse_total)."""
    B, Tl, H, hd = q.shape
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    # the flash branch's diagonal/past/future split is a CAUSAL identity —
    # non-causal rings keep the einsum path
    if use_flash and not causal:
        use_flash = False
    if use_flash:
        from deepspeed_tpu.ops.pallas.flash_attention import \
            flash_attention_with_lse
        qt = jnp.swapaxes(q, 1, 2)                       # [B, H, Tl, hd]

        def step(carry, i):
            acc, lse_run, kv = carry
            k_blk, v_blk = kv
            src = (my_idx - i) % sp

            def diagonal():
                o, lse = flash_attention_with_lse(
                    qt, jnp.swapaxes(k_blk, 1, 2), jnp.swapaxes(v_blk, 1, 2),
                    causal=True, sm_scale=sm_scale)
                return o.astype(jnp.float32), lse

            def past():
                o, lse = flash_attention_with_lse(
                    qt, jnp.swapaxes(k_blk, 1, 2), jnp.swapaxes(v_blk, 1, 2),
                    causal=False, sm_scale=sm_scale)
                return o.astype(jnp.float32), lse

            def future():
                return (jnp.zeros((B, H, Tl, hd), jnp.float32),
                        jnp.full((B, H, Tl), NEG_INF, jnp.float32))

            o_blk, lse_blk = jax.lax.cond(
                src == my_idx, diagonal,
                lambda: jax.lax.cond(src < my_idx, past, future))
            lse_new = jnp.logaddexp(lse_run, lse_blk)
            safe = jnp.where(jnp.isfinite(lse_new), lse_new, 0.0)
            alpha = jnp.where(jnp.isfinite(lse_run),
                              jnp.exp(lse_run - safe), 0.0)
            beta = jnp.where(jnp.isfinite(lse_blk),
                             jnp.exp(lse_blk - safe), 0.0)
            acc = acc * alpha[..., None] + o_blk * beta[..., None]
            kv = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis_name, perm), kv)
            return (acc, lse_new, kv), None

        acc0 = jnp.zeros((B, H, Tl, hd), jnp.float32)
        lse0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        (acc, _, _), _ = jax.lax.scan(step, (acc0, lse0, (k, v)),
                                      jnp.arange(sp))
        return jnp.swapaxes(acc, 1, 2).astype(q.dtype)

    def step(carry, i):
        acc, m_run, l_run, kv = carry
        k_blk, v_blk = kv
        src = (my_idx - i) % sp       # owner of the block we currently hold
        m_blk, l_blk, o_blk = _block_attn_partial(
            q, k_blk, v_blk, my_idx * Tl, src * Tl, causal, sm_scale)
        m_new = jnp.maximum(m_run, m_blk)
        # guard: rows where both are -inf stay -inf
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.where(jnp.isfinite(m_run), jnp.exp(m_run - safe_m), 0.0)
        beta = jnp.where(jnp.isfinite(m_blk), jnp.exp(m_blk - safe_m), 0.0)
        l_new = l_run * alpha + l_blk * beta
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + \
            o_blk * beta.transpose(0, 2, 1)[..., None]
        kv = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), kv)
        return (acc, m_new, l_new, kv), None

    acc0 = jnp.zeros((B, Tl, H, hd), jnp.float32)
    m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    (acc, m, l, _), _ = jax.lax.scan(step, (acc0, m0, l0, (k, v)), jnp.arange(sp))
    l_safe = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc / l_safe).astype(q.dtype)


def ring_attention(q, k, v, causal=True, sm_scale=None, axis_name=SEQ_AXIS,
                   mesh=None, use_flash=None):
    """Global-array entry: q,k,v [B, T, H, hd] sharded (data, sequence, tensor).
    Returns attention output with the same layout/sharding.

    use_flash: None = auto — per-step block attention runs the Pallas flash
    kernel when the LOCAL shard is a 128-multiple >= 1024 tokens on a real
    TPU backend (measured r4: the kernel beats materialized attention 1.6x
    at 1k, 2.3x at 2k, 3.4x at 4k fwd+bwd; interpret mode on CPU would be
    orders slower, so the einsum path is kept there)."""
    mesh = mesh or mesh_mod.get_mesh()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    sp = sizes.get(axis_name, 1)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if sp == 1:
        m, l, o = _block_attn_partial(q, k, v, 0, 0, causal, sm_scale)
        return (o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]).astype(q.dtype)

    local_q_shape = (q.shape[0], q.shape[1] // sp, *q.shape[2:])
    if use_flash is None:
        use_flash = _can_use_flash(
            jax.ShapeDtypeStruct(local_q_shape, q.dtype), causal)

    spec = P(BATCH_AXES, axis_name, TENSOR_AXIS, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis_name, sp=sp, causal=causal,
                sm_scale=sm_scale, use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)
