"""Ring attention — context parallelism for long sequences.

The reference snapshot has NO context parallelism (SURVEY §2.3: "Ring attention /
context parallel — absent"); its long-sequence story is Ulysses + block-sparse
attention. On TPU, ring attention is the idiomatic long-context mechanism: each
sequence rank holds a KV shard, KV blocks rotate around the `sequence` ICI ring via
`ppermute` while every rank accumulates online-softmax partials of its Q shard —
compute and transfer overlap, memory stays O(T/sp).

PRIMARY path (`ring_flash_attention` / `use_flash=True`): each ring step runs
the HBM-streaming Pallas flash kernel (`ops/pallas/flash_attention.py`,
`flash_attention_with_lse`) on the whole held K/V shard; partials merge by
(o, lse), so the online-softmax state carries across ring steps in the
forward AND — via the lse cotangent threaded through the kernel's custom
VJP — the backward. Causal rings SKIP future-only steps entirely (the held
shard's owner is later in token order than every local query: no compute,
no HBM traffic — the step contributes (o=0, lse=-inf)), the diagonal step
runs the kernel's masked form, and past steps run unmasked, so causal ring
work is ~half of full ((sp+1)/2sp of the steps compute on average).

ORACLE/fallback (`ring_attention_blockwise` / `use_flash=False`): the same
ring schedule from differentiable lax pieces (blockwise einsum + running
(m, l, acc) merge), numerically the dense-softmax identity. It keeps the
flash path parity-testable on the CPU harness (interpret-mode Pallas is
orders slower than einsum there) and carries the shapes the kernel cannot
(local shards that are not 128-multiples).

COMPOSITION (`ring_ulysses_attention`): DeepSpeed-Ulysses' head-scatter
all-to-all composed with the ring — sp = ulysses_degree × ring_degree, as in
the reference's hybrid. The `sequence` mesh axis is factored into
(`seq_ring`, `seq_ulysses`) sub-axes; inside the shard_map each rank trades
its T/sp token shard for an H/ulysses head shard over `seq_ulysses`
(tokens gather to T/ring_degree, contiguous in ring order), runs the ring
over `seq_ring`, and trades back. Per-chip attention memory is
O(T/(ring·ulysses)) for K/V residency with ulysses-fold fewer heads per
ring step.

All three register in the attention dispatch layer
(`ops/attention_dispatch.py`) — the GPT zoo engages them via
`GPTConfig.attention_backend` rather than per-call-site wiring.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS

NEG_INF = -1e30

# factored sub-axes of SEQ_AXIS for the ring∘Ulysses hybrid
RING_SUBAXIS = "seq_ring"
ULYSSES_SUBAXIS = "seq_ulysses"


def _block_attn_partial(q, k, v, q_offset, k_offset, causal, sm_scale):
    """Unnormalized block attention with running-max bookkeeping.
    q: [B, Tq, H, hd]; k,v: [B, Tk, H, hd] → (scores_max [B,H,Tq],
    exp-sum [B,H,Tq], weighted values [B,Tq,H,hd])."""
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)) * sm_scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(Tq)[:, None]
        k_pos = k_offset + jnp.arange(Tk)[None, :]
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                                   # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m = -inf → p = exp(-inf - -inf) = nan; guard
    p = jnp.where(jnp.isfinite(m)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))
    return m, l, o


def _can_use_flash(q, causal):
    """Flash inner blocks: long-enough kernel-tileable local shards on a
    real backend (interpret-mode pallas on CPU is orders slower than
    einsum). Causal and non-causal rings both qualify — the non-causal
    ring runs the unmasked kernel every step."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_seq_tileable
    del causal
    Tl = q.shape[1]
    return (flash_seq_tileable(Tl) and Tl >= 1024
            and jax.default_backend() in ("tpu", "axon"))


def _ring_attention_local(q, k, v, axis_name, sp, causal, sm_scale,
                          use_flash=False):
    """Runs inside shard_map. q,k,v local: [B, Tl, H, hd].

    `use_flash=True` routes each ring step's block attention through the
    Pallas flash kernel (ops/pallas/flash_attention.py): ring blocks are
    whole contiguous shards, so under a causal mask every (q_shard, k_shard)
    pair is exactly one of three cases — DIAGONAL (src == mine: standard
    causal), PAST (src < mine: no mask), FUTURE (fully masked: skip, no
    compute, lse = -inf) — which avoids offset-aware masking inside the
    kernel entirely; a non-causal ring runs the unmasked kernel every step.
    Partials merge by (o, lse): out = Σ_i o_i · exp(lse_i − lse_total) —
    the online-softmax carry across ring steps, fwd and (via the kernel's
    lse cotangent) bwd.

    The einsum path applies the SAME causal step-skipping: future-only
    steps return the empty partial (m=-inf, l=0, o=0) through a lax.cond
    instead of computing a fully-masked block — causal ring work is ~half
    of full on both paths."""
    B, Tl, H, hd = q.shape
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    if use_flash:
        from deepspeed_tpu.ops.pallas.flash_attention import \
            flash_attention_with_lse
        qt = jnp.swapaxes(q, 1, 2)                       # [B, H, Tl, hd]

        def step(carry, i):
            acc, lse_run, kv = carry
            k_blk, v_blk = kv
            src = (my_idx - i) % sp

            def diagonal():
                o, lse = flash_attention_with_lse(
                    qt, jnp.swapaxes(k_blk, 1, 2), jnp.swapaxes(v_blk, 1, 2),
                    causal=True, sm_scale=sm_scale)
                return o.astype(jnp.float32), lse

            def past():
                o, lse = flash_attention_with_lse(
                    qt, jnp.swapaxes(k_blk, 1, 2), jnp.swapaxes(v_blk, 1, 2),
                    causal=False, sm_scale=sm_scale)
                return o.astype(jnp.float32), lse

            def future():
                return (jnp.zeros((B, H, Tl, hd), jnp.float32),
                        jnp.full((B, H, Tl), NEG_INF, jnp.float32))

            if causal:
                o_blk, lse_blk = jax.lax.cond(
                    src == my_idx, diagonal,
                    lambda: jax.lax.cond(src < my_idx, past, future))
            else:
                o_blk, lse_blk = past()
            lse_new = jnp.logaddexp(lse_run, lse_blk)
            safe = jnp.where(jnp.isfinite(lse_new), lse_new, 0.0)
            alpha = jnp.where(jnp.isfinite(lse_run),
                              jnp.exp(lse_run - safe), 0.0)
            beta = jnp.where(jnp.isfinite(lse_blk),
                             jnp.exp(lse_blk - safe), 0.0)
            acc = acc * alpha[..., None] + o_blk * beta[..., None]
            kv = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis_name, perm), kv)
            return (acc, lse_new, kv), None

        acc0 = jnp.zeros((B, H, Tl, hd), jnp.float32)
        lse0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
        (acc, _, _), _ = jax.lax.scan(step, (acc0, lse0, (k, v)),
                                      jnp.arange(sp))
        return jnp.swapaxes(acc, 1, 2).astype(q.dtype)

    def step(carry, i):
        acc, m_run, l_run, kv = carry
        k_blk, v_blk = kv
        src = (my_idx - i) % sp       # owner of the block we currently hold

        def live():
            return _block_attn_partial(
                q, k_blk, v_blk, my_idx * Tl, src * Tl, causal, sm_scale)

        if causal:
            def future():
                # fully-masked shard: skip the einsum entirely — the empty
                # partial merges as a no-op through the finite-mass guards
                return (jnp.full((B, H, Tl), NEG_INF, jnp.float32),
                        jnp.zeros((B, H, Tl), jnp.float32),
                        jnp.zeros((B, Tl, H, hd), jnp.float32))

            m_blk, l_blk, o_blk = jax.lax.cond(src <= my_idx, live, future)
        else:
            m_blk, l_blk, o_blk = live()
        m_new = jnp.maximum(m_run, m_blk)
        # guard: rows where both are -inf stay -inf
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.where(jnp.isfinite(m_run), jnp.exp(m_run - safe_m), 0.0)
        beta = jnp.where(jnp.isfinite(m_blk), jnp.exp(m_blk - safe_m), 0.0)
        l_new = l_run * alpha + l_blk * beta
        acc = acc * alpha.transpose(0, 2, 1)[..., None] + \
            o_blk * beta.transpose(0, 2, 1)[..., None]
        kv = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), kv)
        return (acc, m_new, l_new, kv), None

    acc0 = jnp.zeros((B, Tl, H, hd), jnp.float32)
    m0 = jnp.full((B, H, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    (acc, m, l, _), _ = jax.lax.scan(step, (acc0, m0, l0, (k, v)), jnp.arange(sp))
    l_safe = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc / l_safe).astype(q.dtype)


def _check_flash_shard(Tl, sp, what="ring"):
    """use_flash=True demands kernel-tileable local shards; surface the
    contract instead of the flash kernel's deep block-divisibility assert."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_seq_tileable
    if not flash_seq_tileable(Tl):
        raise ValueError(
            f"{what} flash attention: local shard T/sp = {Tl} (sp={sp}) is "
            f"not a 128-multiple — the Pallas kernel tiles 128-lane blocks. "
            f"Pad T to a multiple of sp*128, or drop use_flash to run the "
            f"blockwise oracle path")


def ring_attention(q, k, v, causal=True, sm_scale=None, axis_name=SEQ_AXIS,
                   mesh=None, use_flash=None):
    """Global-array entry: q,k,v [B, T, H, hd] sharded (data, sequence, tensor).
    Returns attention output with the same layout/sharding.

    use_flash: None = auto — per-step block attention runs the Pallas flash
    kernel when the LOCAL shard is a 128-multiple >= 1024 tokens on a real
    TPU backend (measured r4: the kernel beats materialized attention 1.6x
    at 1k, 2.3x at 2k, 3.4x at 4k fwd+bwd; interpret mode on CPU would be
    orders slower, so the einsum oracle is kept there). True forces the
    kernel (128-multiple local shards required — clear ValueError
    otherwise); False forces the blockwise oracle."""
    mesh = mesh or mesh_mod.get_mesh()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    sp = sizes.get(axis_name, 1)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if sp == 1:
        # degenerate ring: honor the use_flash contract anyway — True must
        # run (and shape-check) the kernel, not silently fall to einsum
        if use_flash:
            _check_flash_shard(q.shape[1], 1)
            from deepspeed_tpu.ops.pallas.flash_attention import \
                flash_attention
            return flash_attention(q, k, v, causal=causal,
                                   sm_scale=sm_scale)
        m, l, o = _block_attn_partial(q, k, v, 0, 0, causal, sm_scale)
        return (o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]).astype(q.dtype)

    if q.shape[1] % sp != 0:
        raise ValueError(
            f"ring attention: T = {q.shape[1]} does not divide over the "
            f"{sp}-way `{axis_name}` mesh axis")
    local_q_shape = (q.shape[0], q.shape[1] // sp, *q.shape[2:])
    if use_flash is None:
        use_flash = _can_use_flash(
            jax.ShapeDtypeStruct(local_q_shape, q.dtype), causal)
    if use_flash:
        _check_flash_shard(local_q_shape[1], sp)

    spec = P(BATCH_AXES, axis_name, TENSOR_AXIS, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis_name, sp=sp, causal=causal,
                sm_scale=sm_scale, use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ring_flash_attention(q, k, v, causal=True, sm_scale=None,
                         axis_name=SEQ_AXIS, mesh=None):
    """The PRIMARY long-context path: ring attention with the Pallas flash
    kernel forced for every ring step (see `_ring_attention_local`)."""
    return ring_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                          axis_name=axis_name, mesh=mesh, use_flash=True)


def ring_attention_blockwise(q, k, v, causal=True, sm_scale=None,
                             axis_name=SEQ_AXIS, mesh=None):
    """The lax-level blockwise ORACLE: same ring schedule, einsum block
    attention — the parity reference for the flash path and the fallback
    for shard shapes the kernel cannot tile."""
    return ring_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                          axis_name=axis_name, mesh=mesh, use_flash=False)


# ----------------------------------------------------------------------
# ring ∘ Ulysses composition (the reference hybrid: sp = ulysses × ring)
# ----------------------------------------------------------------------


def factored_sequence_mesh(mesh, ulysses_degree):
    """Split `mesh`'s `sequence` axis into (seq_ring, seq_ulysses) sub-axes
    of sizes (sp // ulysses_degree, ulysses_degree). Device order is
    preserved: seq_ulysses is the INNER factor, so Ulysses' all-to-all —
    the bandwidth-hungry collective of the pair — rides adjacent ICI
    neighbors while the ring's ppermute spans the outer stride, mirroring
    the mesh module's slow-outer/fast-inner axis convention."""
    names = list(mesh.axis_names)
    i = names.index(SEQ_AXIS)
    shape = mesh.devices.shape
    sp = shape[i]
    if sp % ulysses_degree != 0:
        raise ValueError(
            f"ring∘Ulysses: ulysses_degree {ulysses_degree} does not divide "
            f"the `sequence` axis size {sp}")
    ring_degree = sp // ulysses_degree
    devices = mesh.devices.reshape(
        shape[:i] + (ring_degree, ulysses_degree) + shape[i + 1:])
    new_names = names[:i] + [RING_SUBAXIS, ULYSSES_SUBAXIS] + names[i + 1:]
    return Mesh(devices, tuple(new_names)), ring_degree


def ring_ulysses_attention(q, k, v, causal=True, sm_scale=None,
                           ulysses_degree=None, mesh=None, use_flash=None):
    """Context parallelism composed with Ulysses head parallelism over ONE
    `sequence` mesh axis: sp = ulysses_degree × ring_degree.

    q,k,v: [B, T, H, hd] global arrays (matched q/kv head counts — GQA
    callers repeat K/V first, as for every external attention program).
    Inside the factored mesh's shard_map, each rank:

      1. all-to-alls over `seq_ulysses`: trades its T/sp token shard for an
         H/ulysses head shard — tokens gather CONTIGUOUSLY in ring order
         (seq_ulysses is the inner factor of the T sharding), so ring rank
         r then holds tokens [r·T/ring, (r+1)·T/ring);
      2. runs the ring over `seq_ring` (flash kernel per step when
         engaged — same auto rule as `ring_attention`, on the post-
         all-to-all local shape);
      3. all-to-alls back to the [B, T/sp, H, hd] layout.

    `ulysses_degree=None` auto-picks the largest divisor of sp that also
    divides the per-tensor-shard head count — all heads busy, remainder of
    sp goes to the ring. Degenerate ends are exact: ulysses_degree == sp is
    pure Ulysses, ulysses_degree == 1 is pure ring."""
    mesh = mesh or mesh_mod.get_mesh()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    sp = sizes.get(SEQ_AXIS, 1)
    tp = sizes.get(TENSOR_AXIS, 1)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if sp == 1:
        # degenerate hybrid = degenerate ring (which honors use_flash)
        return ring_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                              mesh=mesh, use_flash=use_flash)

    B, T, H, hd = q.shape
    if H % tp != 0:
        raise ValueError(f"ring∘Ulysses: {H} heads do not divide over the "
                         f"{tp}-way `tensor` axis")
    local_h = H // tp
    if ulysses_degree is None:
        ulysses_degree = 1
        for d in range(min(sp, local_h), 0, -1):
            if sp % d == 0 and local_h % d == 0:
                ulysses_degree = d
                break
    if local_h % ulysses_degree != 0:
        raise ValueError(
            f"ring∘Ulysses: ulysses_degree {ulysses_degree} does not divide "
            f"the per-tensor-shard head count {local_h} (H={H}, tp={tp}) — "
            f"the head-scatter all-to-all needs whole heads per rank. "
            f"Lower ulysses_degree (its factor of sp moves to the ring)")
    if k.shape[2] != H or v.shape[2] != H:
        raise ValueError(
            f"ring∘Ulysses: k/v head count {k.shape[2]} != q head count {H} "
            f"— repeat GQA K/V heads before the all-to-all (the zoo's "
            f"dispatch layer does this for external programs)")
    if T % sp != 0:
        raise ValueError(f"ring∘Ulysses: T = {T} does not divide over the "
                         f"{sp}-way `sequence` axis")

    fmesh, ring_degree = factored_sequence_mesh(mesh, ulysses_degree)
    if use_flash is None:
        use_flash = _can_use_flash(
            jax.ShapeDtypeStruct(
                (B, T // ring_degree, local_h // ulysses_degree, hd),
                q.dtype), causal)
    if use_flash:
        _check_flash_shard(T // ring_degree, ring_degree, what="ring∘Ulysses")

    spec = P(BATCH_AXES, (RING_SUBAXIS, ULYSSES_SUBAXIS), TENSOR_AXIS, None)

    def local(q, k, v):
        # [b, T/sp, h_tp, hd] → head-scatter / token-gather over ulysses
        a2a = partial(jax.lax.all_to_all, axis_name=ULYSSES_SUBAXIS,
                      tiled=True)
        q, k, v = (a2a(x, split_axis=2, concat_axis=1) for x in (q, k, v))
        o = _ring_attention_local(q, k, v, axis_name=RING_SUBAXIS,
                                  sp=ring_degree, causal=causal,
                                  sm_scale=sm_scale, use_flash=use_flash)
        return a2a(o, split_axis=1, concat_axis=2)

    fn = shard_map(local, mesh=fmesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)
