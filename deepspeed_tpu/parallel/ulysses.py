"""DeepSpeed-Ulysses sequence parallelism, TPU-native.

Reference: `deepspeed/sequence/layer.py:15-85` — `_SeqAllToAll` (all-to-all that
re-shards [B, T/sp, H, hd] → [B, T, H/sp, hd]) and `DistributedAttention` (the
all-to-all sandwich around any local attention), with seq groups from
`utils/groups.py:420-466`.

TPU-native formulation: under SPMD the two all-to-alls are *sharding constraints* —
activations arrive sequence-sharded, we constrain q/k/v to head-sharded before the
attention and constrain the output back to sequence-sharded; XLA emits exactly the
two all-to-alls of the reference over the `sequence` ICI axis. An explicit
`shard_map` variant is provided for when manual scheduling is needed.
"""

from functools import partial

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, shard_constraint


def ulysses_attention(attn_fn):
    """Wrap a local attention fn ([B,T,H,hd]×3 → [B,T,H,hd]) with the Ulysses
    sequence↔head re-sharding sandwich (SPMD-constraint formulation)."""

    def wrapped(q, k, v, *args, **kwargs):
        # incoming: sequence-sharded on T (and possibly TP-sharded on H)
        # before attention: all heads local per (sequence,tensor) shard of H; full T
        q = shard_constraint(q, BATCH_AXES, None, (SEQ_AXIS, TENSOR_AXIS), None)
        k = shard_constraint(k, BATCH_AXES, None, (SEQ_AXIS, TENSOR_AXIS), None)
        v = shard_constraint(v, BATCH_AXES, None, (SEQ_AXIS, TENSOR_AXIS), None)
        out = attn_fn(q, k, v, *args, **kwargs)
        # back to sequence-sharded layout
        return shard_constraint(out, BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)

    return wrapped


class DistributedAttention:
    """API-parity class (reference `sequence/layer.py:37`): construct with a local
    attention callable; call with q,k,v shaped [B, T, H, hd]."""

    def __init__(self, local_attention, sequence_process_group=None,
                 scatter_idx=2, gather_idx=1):
        self.local_attn = local_attention
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx
        self._wrapped = ulysses_attention(local_attention)

    def __call__(self, query, key, value, *args, **kwargs):
        return self._wrapped(query, key, value, *args, **kwargs)


def seq_all_to_all(x, scatter_axis, gather_axis, axis_name=SEQ_AXIS):
    """Explicit in-shard_map all-to-all (reference `_SeqAllToAll.forward`):
    scatters `scatter_axis` over the sequence ranks and gathers `gather_axis`."""
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_axis,
                              concat_axis=gather_axis, tiled=True)


def ulysses_shard_map_attention(attn_fn, mesh=None):
    """Explicit shard_map Ulysses for manual control: q,k,v are global arrays
    sharded [B@data, T@sequence, H@tensor, hd]; inside, each sequence rank trades
    its sequence shard for a head shard, runs local attention on the full sequence,
    then trades back.

    The head-scatter all-to-all hands each of the sp sequence ranks a whole
    number of heads, so the per-tensor-shard head count must divide by sp —
    validated eagerly per call with a clear ValueError (the alternative is a
    shape-mismatch error deep inside XLA's all-to-all lowering)."""
    mesh = mesh or mesh_mod.get_mesh()
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    sp = sizes.get(SEQ_AXIS, 1)
    tp = sizes.get(TENSOR_AXIS, 1)

    spec = P(BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, None)

    def local(q, k, v):
        # local shapes: [b, t/sp, h/tp, hd]
        q = seq_all_to_all(q, scatter_axis=2, gather_axis=1)  # → [b, t, h/(tp·sp), hd]
        k = seq_all_to_all(k, scatter_axis=2, gather_axis=1)
        v = seq_all_to_all(v, scatter_axis=2, gather_axis=1)
        o = attn_fn(q, k, v)
        return seq_all_to_all(o, scatter_axis=1, gather_axis=2)

    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)

    def validated(q, k, v):
        for name, x in (("q", q), ("k", k), ("v", v)):
            h_local = x.shape[2] // tp if x.shape[2] % tp == 0 else None
            if h_local is None or h_local % sp != 0:
                raise ValueError(
                    f"ulysses_shard_map_attention: {name} has {x.shape[2]} "
                    f"heads — after the {tp}-way tensor split, the per-shard "
                    f"head count must divide by the {sp}-way `sequence` axis "
                    f"(the all-to-all scatters whole heads per rank). Use a "
                    f"head count divisible by tp*sp={tp * sp}, lower the "
                    f"sequence axis, or compose with ring context "
                    f"parallelism (parallel/ring.py ring_ulysses_attention: "
                    f"the non-dividing factor of sp moves to the K/V ring)")
        return fn(q, k, v)

    return validated
