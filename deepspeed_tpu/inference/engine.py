"""Inference engine.

Analog of `InferenceEngine` (`inference/engine.py:39`) + `deepspeed.init_inference`
(`deepspeed/__init__.py:269`). The reference swaps HF modules for fused CUDA blocks
(kernel injection, `module_inject/replace_module.py:182`) or auto-shards linears
(AutoTP, `module_inject/auto_tp.py:175`); the TPU-native equivalent compiles a
decode step with a static-shape KV cache and shards it over the `tensor` mesh axis.

A model for inference is a `DecodeModelSpec`:
  * `prefill_fn(params, tokens, cache) -> (logits, cache)`
  * `decode_fn(params, token, pos, cache) -> (logits, cache)`
  * `init_cache(batch, max_len)` -> KV cache pytree
The model zoo (deepspeed_tpu.models) provides these for GPT-2/LLaMA-style nets;
the adapters in inference/adapters.py build them from HF checkpoints (the
"containers" role, `module_inject/containers/*`).
"""

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu import comm
from deepspeed_tpu.inference.config import TpuInferenceConfig
from deepspeed_tpu.utils.logging import logger, log_dist
from deepspeed_tpu.utils.tree import tree_cast


def sample_logits(logits, rng, greedy=True, temperature=1.0, top_k=0,
                  top_p=1.0):
    """One sampling rule for every inference engine (resident + spill +
    serving): greedy argmax, or temperature/top-k/top-p categorical.
    Filters compose in the standard order: temperature, then top-k, then
    nucleus (top-p) on the surviving distribution."""
    if greedy or rng is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    want_k = bool(top_k) and top_k > 0
    want_p = top_p is not None and top_p < 1.0
    if want_k or want_p:
        # ONE sort pass for both filters: lax.top_k's descending head is the
        # kth-value source for the top-k cut AND the sorted prefix the
        # nucleus cumsum walks. (The old path paid two full-vocab jnp.sorts —
        # one for kth, one for the nucleus — and the nucleus only ever reads
        # the head anyway: past the kept set the cumulative mass is 1, so no
        # tail entry can pass the `< top_p` test.)
        k_eff = min(int(top_k), logits.shape[-1]) if want_k \
            else logits.shape[-1]
        head = jax.lax.top_k(logits, k_eff)[0]
        if want_k:
            logits = jnp.where(logits < head[..., -1:], -jnp.inf, logits)
        if want_p:
            # nucleus sampling (Holtzman et al.): keep the smallest head of
            # the sorted distribution whose cumulative probability reaches
            # top_p. With top-k active, softmax over the k-entry head equals
            # the softmax of the filtered distribution whenever the kth
            # value is unique — logits tied EXACTLY at the kth value survive
            # the `< kth` filter but fall outside the head, so their mass is
            # missing from this cumsum (the old two-sort path counted it).
            # Tied logits carry equal probability, so either cutoff is a
            # valid nucleus rule; exact ties are measure-zero for real model
            # logits. The exclusive cumsum (cum - probs) keeps the argmax
            # even when its own probability already exceeds top_p; ties at
            # the cutoff logit are all kept (harmless: equal probability).
            probs = jax.nn.softmax(head, axis=-1)
            keep = jnp.cumsum(probs, axis=-1) - probs < top_p
            # top-1 survives unconditionally, including top_p <= 0 (a common
            # spelling of "argmax"): an all-False keep would mask EVERY token
            # and categorical over all -inf degenerates to token id 0
            keep = keep.at[..., 0].set(True)
            cutoff = jnp.min(jnp.where(keep, head, jnp.inf), axis=-1,
                             keepdims=True)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@dataclasses.dataclass
class DecodeModelSpec:
    prefill_fn: Callable       # (params, tokens[B,T], cache, pad_mask) -> (logits[B,T,V], cache)
    decode_fn: Callable        # (params, token[B], pos[B], cache) -> (logits[B,V], cache)
    init_cache: Callable       # (batch_size, max_len, dtype) -> cache pytree
    params: Any
    param_specs: Any = None
    eos_token_id: Optional[int] = None
    name: str = "model"
    # paged-pool serving contract (inference/scheduler.py). Optional: models
    # without it serve through generate() only. Shapes are FIXED per engine —
    # that is what keeps the serving step at one compile for its lifetime.
    #   prefill_paged_fn(params, tokens[B,C], start_pos[B], last_idx[B],
    #                    pool, block_tables[B,nb]) -> (logits[B,V], pool)
    #     one chunk of chunked prefill: writes the chunk's k/v into the
    #     slot's pool blocks and returns the logits at last_idx (the true
    #     final prompt token on the last chunk; ignored on earlier chunks)
    #   decode_paged_fn(params, token[B], pos[B], pool, block_tables[B,nb])
    #       -> (logits[B,V], pool)
    #   init_paged_pool(num_blocks, block_size, dtype[, kv_group_size])
    #       -> pool pytree. dtype int8 selects the QUANTIZED pool: the
    #     k/v payload leaves stay [L, N, Hkv, block, hd] but int8, and the
    #     pool grows k_scale/v_scale f32 leaves [L, N, Hkv, block, hd//g]
    #     (g = kv_group_size, 0 = head_dim) — the serving scheduler passes
    #     the 4th arg only for int8, so 3-arg implementations keep working
    #     for fp pools
    #   verify_paged_fn(params, tokens[B,C], pos[B], pool, block_tables[B,nb])
    #       -> (logits[B,C,V], pool)
    #     speculative-decoding verify: writes ALL C tokens' k/v at absolute
    #     positions pos..pos+C-1 (token [b,0] is the slot's last emitted
    #     token at its cursor, [b,1:] are draft tokens) and returns the
    #     logits at EVERY position — row i scores the draft at i+1, the
    #     first disagreeing row supplies the bonus token. Same chunked-
    #     prefill machinery as prefill_paged_fn, at an arbitrary cursor.
    prefill_paged_fn: Optional[Callable] = None
    decode_paged_fn: Optional[Callable] = None
    verify_paged_fn: Optional[Callable] = None
    init_paged_pool: Optional[Callable] = None
    # cache-identity fingerprint for the prefix cache's hash chain
    # (inference/prefix_cache.py): every arch field that changes the KV
    # VALUES written for a given token stream must be folded in, so two
    # specs can never serve each other's cached blocks. None falls back to
    # `name` (weights are engine-local, so the fingerprint guards config
    # divergence, not parameters).
    cache_fingerprint: Optional[str] = None


class InferenceEngine:
    def __init__(self, model: DecodeModelSpec, config: TpuInferenceConfig, mesh=None):
        self.model_spec = model
        self.config = config

        if mesh is not None:
            mesh_mod.set_mesh(mesh)
        elif not mesh_mod.has_mesh():
            from deepspeed_tpu.config.core import MeshConfig
            tp = config.tensor_parallel.tp_size
            comm.init_distributed(mesh_config=MeshConfig(data=-1, tensor=tp))
        self.mesh = mesh_mod.get_mesh()

        dtype = jnp.dtype(config.dtype) if config.dtype != "float" else jnp.float32
        self.dtype = dtype

        # TP placement: params sharded per their specs over the tensor axis,
        # replicated over everything else.
        if model.param_specs is not None:
            shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec), model.param_specs)
        else:
            shardings = jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P()), model.params)
        params = jax.device_put(tree_cast(model.params, dtype), shardings)

        self.quant_stats = None
        self._weight_quant = None      # (bits, group_size) once quantized
        self._fn_transform = lambda fn: fn
        self.params = params
        if config.quant.enabled:
            # weight-only quantization: HBM keeps int8/int4, XLA fuses dequant
            # into consumers (inference/quantization.py). enable_weight_quant
            # builds the resident programs against the quantized tree, so the
            # dense-path builds below are skipped
            self.enable_weight_quant(bits=config.quant.bits,
                                     group_size=config.quant.group_size)
        else:
            self._prefill = jax.jit(self._fn_transform(model.prefill_fn))
            self._decode = jax.jit(self._fn_transform(model.decode_fn),
                                   donate_argnums=(3,))
        self._generate_jit = None
        # engine-owned KV cache: forward()/generate() reuse the zeros
        # template when (B, max_len, dtype) matches the previous call
        # instead of re-allocating (and re-zeroing) a fresh cache every
        # call. ONE entry only — a multi-shape store would pin several
        # full-size caches in HBM, a peak-memory regression; a shape miss
        # just re-allocates, which is exactly the old per-call behavior.
        # The template is never mutated: the jitted programs are functional
        # and nothing donates it.
        self._cache_entry = None          # ((B, max_len, dtype), cache)
        self._cache_hits = 0
        log_dist(f"inference engine: {model.name} dtype={dtype} "
                 f"tp={config.tensor_parallel.tp_size} "
                 f"quant={'int%d' % config.quant.bits if config.quant.enabled else 'off'}",
                 ranks=[0])

    def enable_weight_quant(self, bits=8, group_size=64):
        """Pytree-wide weight-only quantization of the RESIDENT params
        (ZeroQuant-style WOQ, `inference/quantization.py`): every large
        float matrix leaf becomes int8 (or int4 packed two-per-byte) with
        per-group scales, and every program factory switches to the
        dequantize-on-use view — XLA fuses the dequant into the consuming
        matmul, so HBM holds the quantized tree and compute still runs in
        the engine dtype. The dense tree is DROPPED (this is where the
        2x/4x weight-memory saving comes from); the resident prefill/decode
        programs are re-jitted against the new param pytree and the
        generate program rebuilds lazily.

        Called at engine build for `config.quant.enabled`, and by the
        serving scheduler for `ServingConfig.quantization.weights` —
        idempotent for matching settings, an error for conflicting ones
        (re-quantizing already-quantized leaves would compound the error)."""
        if self._weight_quant is not None:
            if self._weight_quant == (int(bits), int(group_size)):
                return self.quant_stats
            raise ValueError(
                f"params already quantized as int{self._weight_quant[0]} "
                f"(group {self._weight_quant[1]}) — cannot re-quantize as "
                f"int{bits} (group {group_size}); pick one of config.quant "
                f"and serving.quantization.weights, or make them agree")
        from deepspeed_tpu.inference.quantization import (quantize_param_tree,
                                                          wrap_fn_dequant)
        self.params, self.quant_stats = quantize_param_tree(
            self.params, bits=int(bits), group_size=int(group_size))
        self._weight_quant = (int(bits), int(group_size))
        self._fn_transform = wrap_fn_dequant
        # dstpu: ignore[DT004]: one-shot re-jit — the _weight_quant guard above makes this method run at most once per engine, exactly like __init__'s builds
        self._prefill = jax.jit(self._fn_transform(self.model_spec.prefill_fn))
        # dstpu: ignore[DT004]: same one-shot rebuild as the line above
        self._decode = jax.jit(self._fn_transform(self.model_spec.decode_fn),
                               donate_argnums=(3,))
        self._generate_jit = None
        return self.quant_stats

    def _cache_len(self, min_len):
        """Blocked KV-cache sizing: round up to whole kv_block_size blocks
        (the streaming decode kernel's DMA unit — see init_kv_cache). The
        over-allocation is free at decode time: the kernel walks only the
        blocks covering each row's live prefix."""
        bs = int(getattr(self.config, "kv_block_size", 0) or 0)
        return -(-min_len // bs) * bs if bs else min_len

    def _get_cache(self, batch, max_len):
        """Engine-owned KV cache for (batch, max_len): reused whenever the
        shape matches the last call (the old per-call init_cache was a fresh
        HBM allocation + zero-fill per generate()); a shape change replaces
        the single retained template, so peak HBM never exceeds the old
        behavior by more than one cache."""
        if jnp.dtype(self.config.kv_cache_dtype) == jnp.int8:
            raise ValueError(
                "kv_cache_dtype='int8' is a paged-pool serving feature "
                "(ServingConfig.quantization / engine.serving()): the "
                "contiguous generate() cache has no scale storage — serve "
                "through the continuous-batching scheduler, or keep "
                "kv_cache_dtype float for generate()")
        key = (int(batch), int(max_len), str(self.config.kv_cache_dtype))
        if self._cache_entry is not None and self._cache_entry[0] == key:
            self._cache_hits += 1
            return self._cache_entry[1]
        cache = self.model_spec.init_cache(
            batch, max_len, jnp.dtype(self.config.kv_cache_dtype))
        self._cache_entry = (key, cache)
        return cache

    def forward(self, tokens, cache=None, pad_mask=None):
        """Prefill forward (logits for a full sequence)."""
        tokens = jnp.asarray(tokens)
        if cache is None:
            cache = self._get_cache(
                tokens.shape[0],
                self._cache_len(max(self.config.max_out_tokens,
                                    tokens.shape[1])))
        return self._prefill(self.params, tokens, cache, pad_mask)

    __call__ = forward

    def _build_generate(self):
        decode_fn = self._fn_transform(self.model_spec.decode_fn)
        prefill_fn = self._fn_transform(self.model_spec.prefill_fn)
        greedy = self.config.greedy
        temperature = self.config.temperature
        top_k = self.config.top_k
        top_p = self.config.top_p

        def sample(logits, rng):
            return sample_logits(logits, rng, greedy=greedy,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p)

        def generate(params, tokens, cache, prompt_len, max_new, rng, eos_id, pad_id):
            B, T = tokens.shape
            logits, cache = prefill_fn(params, tokens, cache, None)
            # last prompt logits, per sample (ragged batches: rows are
            # right-padded, causal masking keeps pads out of these logits)
            last = jnp.take_along_axis(
                logits, (prompt_len - 1)[:, None, None], axis=1)[:, 0, :]
            first_tok = sample(last, rng)
            done0 = jnp.zeros((B,), bool)

            def body(carry, i):
                tok, pos, cache, rng, done = carry
                rng, sub = jax.random.split(rng)
                lg, cache = decode_fn(params, tok, pos, cache)
                nxt = sample(lg, sub)
                # eos semantics (reference generate(): stop per sequence once
                # eos is emitted): the eos token itself is kept in the output,
                # everything after it is pad_id. eos_id < 0 disables.
                new_done = done | ((tok == eos_id) & (eos_id >= 0))
                nxt = jnp.where(new_done, pad_id, nxt)
                emit = jnp.where(done, pad_id, tok)
                return (nxt, pos + 1, cache, rng, new_done), emit

            (_, _, cache, _, _), toks = jax.lax.scan(
                body, (first_tok, prompt_len, cache, rng, done0),
                jnp.arange(max_new))
            return jnp.moveaxis(toks, 0, 1)  # [B, max_new]

        return jax.jit(generate, static_argnums=(4,))

    @staticmethod
    def _pad_ragged(tokens):
        """Right-pad a list of variable-length sequences to [B, T_max].

        Returns (tokens[B,T], prompt_lens[B]). Right padding (not left) is the
        natural layout for a per-sample-position KV cache: each row's decode
        starts at its own prompt_len and overwrites the pad slots, and causal
        masking keeps trailing pads out of the prompt logits. The reference
        relies on the HF tokenizer's left-pad + attention_mask for the same
        ragged-batch contract (`inference/engine.py:577-606`).

        The fill value is always 0, NOT pad_token_id: pad slots are provably
        never attended, but an out-of-vocab fill (e.g. a sentinel pad id)
        turns the embedding gather out-of-bounds, which is NaN on the TPU
        backend. pad_token_id only masks the *output*.
        """
        lens = np.asarray([len(t) for t in tokens], np.int32)
        T = int(lens.max())
        out = np.zeros((len(tokens), T), np.int32)
        # single boolean-mask scatter instead of a per-row Python loop: the
        # mask enumerates valid slots row-major, matching the concatenation
        # order of the ragged rows
        mask = np.arange(T)[None, :] < lens[:, None]
        out[mask] = np.concatenate([np.asarray(t, np.int32) for t in tokens])
        return out, lens

    def generate(self, tokens, max_new_tokens=32, rng=None, prompt_lens=None,
                 eos_token_id=None, pad_token_id=0, stop_on_eos=True):
        """Greedy/sampled generation with a static-shape decode loop (lax.scan).

        `tokens` may be a rectangular [B, T] batch or a list of ragged
        sequences (padded internally). `prompt_lens` gives per-sample prompt
        lengths for rectangular-but-right-padded input. Sequences stop at
        `eos_token_id` (default: the model spec's) — the eos is kept, later
        slots are `pad_token_id`.
        """
        if self._generate_jit is None:
            self._generate_jit = self._build_generate()
        if isinstance(tokens, (list, tuple)) and tokens and np.ndim(tokens[0]) == 1 \
                and len({len(t) for t in tokens}) > 1:
            tokens, prompt_lens = self._pad_ragged(tokens)
        tokens = jnp.asarray(tokens)
        B, T = tokens.shape
        # max_new is a static argnum of the jitted loop (the scan length must
        # be a compile-time constant), so every distinct value used to build
        # a fresh executable. Bucket it to the next power of two and trim the
        # surplus host-side: a mixed-request server compiles O(log max_new)
        # programs instead of one per distinct value. EOS semantics survive
        # the over-generation — finished rows emit pad_token_id, and the
        # extra columns are sliced off before anyone sees them. The trade-off
        # is deliberate: the surplus scan steps (up to 2x decode compute at
        # the bucket edge, ~1.4x expected) run on every call, bought against
        # a multi-second XLA compile per distinct max_new; workloads where
        # per-call decode cost dominates compile amortization should serve
        # through the continuous-batching scheduler, which has neither cost.
        max_new_bucket = max(1, 1 << (int(max_new_tokens) - 1).bit_length())
        max_len = self._cache_len(T + max_new_bucket)
        cache = self._get_cache(B, max_len)
        if prompt_lens is None:
            prompt_len = jnp.full((B,), T, jnp.int32)
        else:
            prompt_len = jnp.asarray(prompt_lens, jnp.int32)
        eos = eos_token_id
        if eos is None:
            eos = getattr(self.config, "eos_token_id", None)
        if eos is None:
            eos = self.model_spec.eos_token_id
        if not stop_on_eos or eos is None:
            eos = -1
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        out = self._generate_jit(self.params, tokens, cache, prompt_len,
                                 max_new_bucket, rng,
                                 jnp.int32(eos), jnp.int32(pad_token_id))
        # dstpu: ignore[DT001]: generate() API boundary — the whole rollout returns to the host caller in one transfer
        return np.asarray(jax.device_get(out))[:, :max_new_tokens]

    def serving(self, **overrides):
        """Continuous-batching serving engine over this engine's params:
        persistent paged KV pool + request scheduler (inference/scheduler.py).
        `overrides` patch `config.serving` fields (max_slots, max_context,
        num_kv_blocks, prefill_chunk, prefill_chunks_per_step, spec_decode
        — pass a dict for the nested speculative-decoding block, plus
        `draft_spec=` for its draft-model drafter). The scheduler also
        reads this config's `telemetry` block: when enabled it records
        TTFT/TPOT/queue-wait/e2e histograms and pool gauges
        (docs/profiling.md "Telemetry")."""
        from deepspeed_tpu.inference.scheduler import ServingEngine
        return ServingEngine(self, **overrides)


def init_inference(model=None, config=None, **kwargs):
    """Reference signature (`deepspeed/__init__.py:269`): accepts config dict/path +
    kwargs overrides."""
    if config is None:
        config = {}
    if isinstance(config, str):
        import json
        with open(config) as f:
            config = json.load(f)
    if isinstance(config, dict):
        config = {**config, **kwargs}
        cfg = TpuInferenceConfig.from_dict(config)
    else:
        cfg = config
    from deepspeed_tpu.inference.zero_inference import (LayeredModelSpec,
                                                        ZeroInferenceEngine)
    off = (cfg.zero or {}).get("offload_param")
    if isinstance(model, LayeredModelSpec):
        off = off or {}
        return ZeroInferenceEngine(
            model, cfg, offload_device=off.get("device", "cpu"),
            nvme_path=off.get("nvme_path"),
            lookahead=int(off.get("lookahead", 1)),
            staging=int(off.get("staging", 3)))
    if off:
        raise ValueError(
            "zero.offload_param (ZeRO-Inference) needs a LayeredModelSpec — "
            "build one with models.gpt.make_gpt_layered_model")
    assert isinstance(model, DecodeModelSpec), \
        "init_inference expects a DecodeModelSpec (see deepspeed_tpu.models / inference.adapters)"
    return InferenceEngine(model, cfg)
