"""PoolAuditor: online invariant checking + repair for the paged KV pool.

The serving engine's host-side bookkeeping — the allocator's free list and
refcounts, the prefix cache's hash chains, each slot's block table — is
plain Python state mutated a few times per scheduler sync. A single missed
decref, double free, or stale hash entry does not crash anything; it
silently leaks capacity (admission backpressure tightens for no reason),
lets two slots scribble over one physical block (wrong tokens, no
exception), or serves evicted KV content to a future prefix hit. Those are
exactly the corruptions that surface days later as "throughput slowly
degraded" or "one in ten thousand answers was garbage".

The auditor turns the bookkeeping's redundancy into a checkable contract.
Every physical block's ownership story is recorded three times — the free
list, the refcount map, the slot tables (plus the hash registry when
caching is on) — and the invariants below say how those copies must agree:

  I1  free/referenced disjoint: no block is simultaneously on the free
      list and refcounted (a free-listed block WILL be reallocated and
      overwritten under a live reader);
  I2  refcount truth: each block's refcount equals the number of slot
      references to it (slots sharing a prefix each count once); a
      refcount-0 block must be parked on the reclaimable LRU;
  I3  hash-chain liveness: every registered content hash points at a
      block the allocator still tracks (live or reclaimable), and the
      hash<->block maps are inverse bijections;
  I4  trash sanctity: block 0 is never free-listed, refcounted, slot-
      referenced, or registered — it is the write sink for dead slots;
  I5  no leaks: every usable block is either free or tracked by the
      refcount map — a block in neither is unreachable forever;
  I6  table fidelity: each active slot's device-visible table row equals
      its host block list (padded with trash), and FREE slots point every
      entry at trash.

Checking is pure reads over host state (O(blocks + slots·table_width) —
microseconds at serving scale), so it can run on demand, every
`serving.audit_interval` scheduler syncs, and at engine shutdown. On a
violation the engine dumps the flight recorder (ring + audit report +
a portable state snapshot) and either REPAIRS — the slot tables are the
ground truth, because they are what the compiled step programs actually
read, so free list/refcounts/reclaimable are rebuilt from them — or
raises `PoolCorruptionError` so the serving router quarantines the
replica through the same failover path a crash takes.

`audit_state()` / `audit_state_dict()` make the whole story portable:
the same checks run against a live engine or a JSON dump
(`bin/dstpu_audit`), so a flight-recorder black box from production can
be audited offline.
"""

import dataclasses
import json
from typing import Any, Dict, List, Optional

from deepspeed_tpu.inference.kv_cache import TRASH_BLOCK

__all__ = ["AuditReport", "PoolAuditor", "PoolCorruptionError",
           "Violation", "audit_main"]

# the invariant classes a report buckets violations into (I1..I6 above)
VIOLATION_KINDS = ("free_referenced", "free_list_corrupt", "refcount_drift",
                   "stale_hash", "trash_referenced", "leak",
                   "table_mismatch", "reclaimable_corrupt")


class PoolCorruptionError(RuntimeError):
    """The pool's host-side bookkeeping failed its invariant audit and the
    engine is configured not to self-repair (`serving.audit_action`).
    Raised out of `ServingEngine.step()` so the serving router's existing
    failover path quarantines the replica like any other step failure."""

    def __init__(self, report: "AuditReport"):
        self.report = report
        super().__init__(f"KV-pool audit failed: {report.summary()}")


@dataclasses.dataclass
class Violation:
    kind: str                 # one of VIOLATION_KINDS
    block: Optional[int]      # offending physical block (None for structural)
    detail: str

    def to_dict(self):
        return {"kind": self.kind, "block": self.block, "detail": self.detail}


class AuditReport:
    """Outcome of one audit pass: violations bucketed by invariant class."""

    def __init__(self, violations: List[Violation], checked_blocks: int,
                 checked_slots: int):
        self.violations = violations
        self.checked_blocks = checked_blocks
        self.checked_slots = checked_slots

    @property
    def ok(self) -> bool:
        return not self.violations

    def by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.violations:
            out[v.kind] = out.get(v.kind, 0) + 1
        return out

    def summary(self) -> str:
        if self.ok:
            return (f"clean ({self.checked_blocks} blocks, "
                    f"{self.checked_slots} active slots)")
        kinds = ", ".join(f"{k}={n}" for k, n in sorted(self.by_kind().items()))
        return f"{len(self.violations)} violations ({kinds})"

    def to_dict(self):
        return {"ok": self.ok, "checked_blocks": self.checked_blocks,
                "checked_slots": self.checked_slots,
                "by_kind": self.by_kind(),
                "violations": [v.to_dict() for v in self.violations]}


def _state_from_engine(engine) -> Dict[str, Any]:
    """Snapshot the host-side pool bookkeeping into the portable audit-state
    dict (all-JSON types — the `bin/dstpu_audit` interchange format)."""
    alloc = engine.allocator
    slots = []
    for s in engine.slots:
        if s.state == 0:                           # _FREE
            continue
        slots.append({"idx": s.idx, "uid": str(s.uid), "state": int(s.state),
                      "blocks": [int(b) for b in s.blocks]})
    registered, rev = {}, {}
    if engine.prefix_cache is not None:
        registered = engine.prefix_cache.snapshot()
        rev = engine.prefix_cache.reverse_snapshot()
    return {
        "num_blocks": int(alloc.num_blocks),
        "policy": alloc.policy,
        "free": [int(b) for b in alloc._free],
        "free_set": sorted(int(b) for b in alloc._free_set),
        "refs": {str(b): int(c) for b, c in alloc._refs.items()},
        "reclaimable": [int(b) for b in alloc._reclaimable],
        "registered": registered,          # hash hex -> block
        "registered_rev": rev,             # block -> hash hex
        "slots": slots,
        "tables": [[int(b) for b in row] for row in engine.tables],
    }


def audit_state_dict(state: Dict[str, Any]) -> AuditReport:
    """Run every invariant over a portable audit-state dict (live snapshot
    or a JSON dump). Pure function — never mutates the state."""
    bad: List[Violation] = []
    n = int(state["num_blocks"])
    free = [int(b) for b in state["free"]]
    free_set = set(int(b) for b in state.get("free_set", free))
    refs = {int(b): int(c) for b, c in state["refs"].items()}
    reclaimable = [int(b) for b in state.get("reclaimable", ())]
    registered = {h: int(b) for h, b in state.get("registered", {}).items()}
    registered_rev = {int(b): h
                      for b, h in state.get("registered_rev", {}).items()}
    slots = state.get("slots", [])
    tables = state.get("tables")

    # I1 + free-list structure: duplicates, shadow-set drift, range
    seen = set()
    for b in free:
        if b in seen:
            bad.append(Violation("free_list_corrupt", b,
                                 f"block {b} appears twice on the free list"))
        seen.add(b)
        if not (0 < b < n):
            bad.append(Violation("free_list_corrupt", b,
                                 f"free-listed block {b} outside pool "
                                 f"[1, {n})"))
    if seen != free_set:
        drift = sorted(seen.symmetric_difference(free_set))
        bad.append(Violation("free_list_corrupt", None,
                             f"free list / shadow set disagree on blocks "
                             f"{drift[:8]}"))
    for b in sorted(seen & set(refs)):
        bad.append(Violation("free_referenced", b,
                             f"block {b} is on the free list AND refcounted "
                             f"({refs[b]}) — it will be reallocated under a "
                             f"live reader"))

    # I2: refcount truth against the slot tables (ground truth)
    slot_refs: Dict[int, int] = {}
    for s in slots:
        for b in s["blocks"]:
            slot_refs[int(b)] = slot_refs.get(int(b), 0) + 1
    for b in sorted(set(refs) | set(slot_refs)):
        if b == TRASH_BLOCK:
            continue                                   # I4 reports it
        expect = slot_refs.get(b, 0)
        actual = refs.get(b)
        if actual is None:
            bad.append(Violation("refcount_drift", b,
                                 f"block {b} referenced by {expect} slot(s) "
                                 f"but unknown to the allocator"))
        elif actual != expect:
            if expect == 0 and b in reclaimable:
                pass                                   # parked: refcount 0 ok
            else:
                bad.append(Violation(
                    "refcount_drift", b,
                    f"block {b}: refcount {actual} != {expect} slot "
                    f"reference(s)"))
        if actual == 0 and b not in reclaimable:
            bad.append(Violation("refcount_drift", b,
                                 f"block {b}: refcount 0 but not parked on "
                                 f"the reclaimable list"))

    # reclaimable structure: refcount-0 registered blocks only, never free
    reclaim_seen = set()
    for b in reclaimable:
        if b in reclaim_seen:
            bad.append(Violation("reclaimable_corrupt", b,
                                 f"block {b} parked twice on the "
                                 f"reclaimable list"))
        reclaim_seen.add(b)
        if refs.get(b, None) != 0:
            bad.append(Violation("reclaimable_corrupt", b,
                                 f"reclaimable block {b} has refcount "
                                 f"{refs.get(b)!r} (must be exactly 0)"))
        if b in free_set:
            bad.append(Violation("reclaimable_corrupt", b,
                                 f"block {b} is both reclaimable and free"))

    # I3: hash-chain liveness + bijection
    for h, b in sorted(registered.items()):
        if b not in refs:
            bad.append(Violation("stale_hash", b,
                                 f"hash {h[:12]}… registered to block {b}, "
                                 f"which the allocator no longer tracks"))
        if registered_rev.get(b) != h:
            bad.append(Violation("stale_hash", b,
                                 f"hash {h[:12]}… -> block {b} has no "
                                 f"matching reverse entry"))
    for b, h in sorted(registered_rev.items()):
        if registered.get(h) != b:
            bad.append(Violation("stale_hash", b,
                                 f"block {b} -> hash {h[:12]}… has no "
                                 f"matching forward entry"))

    # I4: trash sanctity
    if TRASH_BLOCK in free_set:
        bad.append(Violation("trash_referenced", TRASH_BLOCK,
                             "trash block 0 is on the free list"))
    if TRASH_BLOCK in refs:
        bad.append(Violation("trash_referenced", TRASH_BLOCK,
                             "trash block 0 is refcounted"))
    if TRASH_BLOCK in slot_refs:
        bad.append(Violation("trash_referenced", TRASH_BLOCK,
                             "trash block 0 appears in a slot's block list"))
    if TRASH_BLOCK in registered_rev:
        bad.append(Violation("trash_referenced", TRASH_BLOCK,
                             "trash block 0 is registered in the prefix "
                             "cache"))

    # I5: no leaks — every usable block is free or tracked
    for b in range(1, n):
        if b not in free_set and b not in refs:
            bad.append(Violation("leak", b,
                                 f"block {b} is neither free nor tracked — "
                                 f"unreachable forever"))

    # I6: device-visible tables mirror the host block lists
    if tables is not None:
        active = {s["idx"]: s for s in slots}
        for idx, row in enumerate(tables):
            s = active.get(idx)
            if s is None:
                if any(int(b) != TRASH_BLOCK for b in row):
                    bad.append(Violation(
                        "table_mismatch", None,
                        f"free slot {idx}'s table row references non-trash "
                        f"blocks"))
                continue
            blocks = [int(b) for b in s["blocks"]]
            head = [int(b) for b in row[:len(blocks)]]
            if head != blocks:
                bad.append(Violation(
                    "table_mismatch", None,
                    f"slot {idx} (uid {s['uid']}): table row {head[:8]} != "
                    f"host blocks {blocks[:8]}"))
            if any(int(b) != TRASH_BLOCK for b in row[len(blocks):]):
                bad.append(Violation(
                    "table_mismatch", None,
                    f"slot {idx} (uid {s['uid']}): table tail past the "
                    f"block list is not all trash"))

    return AuditReport(bad, checked_blocks=n, checked_slots=len(slots))


class PoolAuditor:
    """Invariant checker + repairer bound to a live `ServingEngine`.

    `audit()` snapshots the host bookkeeping and checks I1..I6;
    `repair()` rebuilds the allocator's refcounts, reclaimable LRU, and
    free list from the slot tables (the state the compiled programs
    actually consume — the only copy that cannot be wrong about what the
    device will read/write next step) and re-syncs the device-visible
    table rows and prefix-cache maps to match."""

    def __init__(self, engine):
        self.engine = engine

    def snapshot(self) -> Dict[str, Any]:
        return _state_from_engine(self.engine)

    def audit(self) -> AuditReport:
        return audit_state_dict(self.snapshot())

    def repair(self) -> Dict[str, Any]:
        """Rebuild from ground truth. Returns a summary of what changed.

        Policy on ambiguous blocks: a registered (content-hashed) block no
        slot references parks refcount-0 on the reclaimable LRU — its KV
        content is assumed intact, and a wrong assumption costs only a
        future cache miss, never wrong tokens (eviction unregisters it
        before reuse). A hash entry pointing at a slot-referenced block is
        kept (registration of live shared blocks is the normal state). A
        block in no slot and no registry goes back to the free list."""
        eng = self.engine
        alloc = eng.allocator
        before = self.audit()

        slot_refs: Dict[int, int] = {}
        for s in eng.slots:
            if s.state == 0:                           # _FREE
                continue
            for b in s.blocks:
                if b == TRASH_BLOCK:
                    continue
                slot_refs[int(b)] = slot_refs.get(int(b), 0) + 1

        pc = eng.prefix_cache
        if pc is not None:
            # re-derive a consistent bijection: forward map wins, entries
            # pointing at the trash block or out-of-range blocks drop
            fwd = {h: b for h, b in pc._by_hash.items()
                   if 0 < int(b) < alloc.num_blocks}
            pc._by_hash.clear()
            pc._by_block.clear()
            for h, b in fwd.items():
                if b in pc._by_block:                  # two hashes, one block
                    continue
                pc._by_hash[h] = b
                pc._by_block[b] = h
            registered = set(pc._by_block)
        else:
            registered = set()

        new_refs: Dict[int, int] = dict(slot_refs)
        new_reclaim: "Dict[int, None]" = {}
        if alloc.policy == "lru":
            # preserve the surviving LRU order, then adopt any registered
            # block that lost its parking spot (appended newest — they were
            # live a moment ago)
            for b in alloc._reclaimable:
                if b in registered and b not in new_refs:
                    new_reclaim[b] = None
                    new_refs[b] = 0
            for b in sorted(registered):
                if b not in new_refs:
                    new_reclaim[b] = None
                    new_refs[b] = 0
        elif pc is not None:
            # policy "none": nothing parks; unregister orphaned hashes
            for b in sorted(registered):
                if b not in new_refs:
                    pc._unregister_block(b)

        import collections
        alloc._refs = new_refs
        alloc._reclaimable = collections.OrderedDict(new_reclaim)
        # descending ids so pop() keeps yielding low ids first (the
        # allocator's deterministic-order contract)
        alloc._free = [b for b in range(alloc.num_blocks - 1, 0, -1)
                       if b not in new_refs]
        alloc._free_set = set(alloc._free)

        # re-sync the device-visible table rows to the host block lists
        for s in eng.slots:
            eng.tables[s.idx, :] = TRASH_BLOCK
            if s.state != 0 and s.blocks:
                eng.tables[s.idx, :len(s.blocks)] = s.blocks

        after = self.audit()
        return {"violations_before": len(before.violations),
                "violations_after": len(after.violations),
                "by_kind": before.by_kind(),
                "rebuilt_refs": len(new_refs),
                "rebuilt_free": len(alloc._free),
                "reclaimable": len(new_reclaim),
                "clean": after.ok}


# ----------------------------------------------------------------------
# CLI: bin/dstpu_audit
# ----------------------------------------------------------------------


def _find_audit_states(doc, path="$"):
    """Recursively locate audit-state dicts inside an arbitrary JSON
    document — a raw `audit_state()` snapshot, a flight-recorder dump whose
    state carries `audit_state`, or a router dump with per-replica
    states."""
    found = []
    if isinstance(doc, dict):
        if "num_blocks" in doc and "refs" in doc and "free" in doc:
            return [(path, doc)]
        for k, v in doc.items():
            found.extend(_find_audit_states(v, f"{path}.{k}"))
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            found.extend(_find_audit_states(v, f"{path}[{i}]"))
    return found


def audit_main(argv=None) -> int:
    """`bin/dstpu_audit` entry: audit one or more dumped pool states.
    Exit code 0 = every state clean, 1 = violations found, 2 = no audit
    state located in the input."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="dstpu_audit",
        description="Run the KV-pool invariant auditor (inference/audit.py) "
                    "against a dumped engine state: a raw audit_state() "
                    "snapshot, or a flight-recorder .flightrec.NNN.json "
                    "dump containing one.")
    ap.add_argument("path", help="JSON file to audit")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    with open(args.path) as f:
        doc = json.load(f)
    states = _find_audit_states(doc)
    if not states:
        print(f"dstpu_audit: no audit state found in {args.path} "
              f"(expected an audit_state() snapshot or a flight dump "
              f"containing one)")
        return 2

    reports = [(where, audit_state_dict(state)) for where, state in states]
    if args.json:
        print(json.dumps({"path": args.path,
                          "states": [{"at": where, **rep.to_dict()}
                                     for where, rep in reports]}, indent=1))
    else:
        for where, rep in reports:
            print(f"{where}: {rep.summary()}")
            for v in rep.violations:
                print(f"  [{v.kind}] block={v.block}: {v.detail}")
    return 0 if all(rep.ok for _, rep in reports) else 1
