"""Continuous-batching serving scheduler over the paged KV-cache pool.

The Orca insight, TPU-style: a static-batch `generate()` call stalls its
whole batch on the slowest sequence and pays one XLA compile per request
shape. This scheduler instead owns `max_slots` fixed sequence slots and ONE
paged KV pool (`inference/kv_cache.py`), and drives every request through
two persistent jitted programs whose shapes never change:

  * `prefill_step` — [1, chunk] slice of a prompt: chunked prefill writes
    the chunk's K/V through the slot's block table and interleaves with
    in-flight decode (`prefill_chunks_per_step` bounds the stall an
    arriving prompt can impose on the running batch);
  * `decode_step` — one token for ALL slots at once: inactive slots ride
    along pointed at the trash block, so slot liveness never changes the
    program shape.

Iteration-level scheduling happens between the two calls, on the host, in
plain Python: admit queued requests into freed slots (admission is a
free-list pop — all-or-nothing, so a too-big request waits instead of
half-occupying the pool), retire sequences the step they emit EOS, free
their blocks immediately. The result is one compile per program for the
lifetime of the engine — the recompile tax and the convoy effect die
together.

Compile accounting is first-class: `compile_stats()` reads the jit caches,
and the serving tests assert <= 1 compile per bucket across a mixed-length
request trace.

Automatic prefix caching (`serving.enable_prefix_caching`,
`inference/prefix_cache.py`) rides the same machinery: at admission the
prompt's hash chain is matched against previously written full blocks, hit
blocks are mapped into the new slot's table with a refcount bump, and the
chunked-prefill cursor starts at the cached boundary — a shared system
prompt prefills once per engine, not once per request. Only host-side state
changes; the two compiled programs and their shapes are untouched.

Speculative decoding (`serving.spec_decode`, `inference/spec_decode.py`)
swaps the decode step for a draft+verify loop: a drafter (model-free n-gram
prompt lookup, or a second smaller model) proposes `draft_k` tokens per
slot, ONE fixed-shape jitted verify call scores them for all slots at once
(chunked prefill at positions pos..pos+k), and the longest agreeing prefix
plus a bonus token is emitted — 1..k+1 tokens per model step. Rejection is
an O(1) rewind of the slot's length cursor: the rejected tokens' k/v sits
past the cursor where later writes overwrite it, and the block table never
moves.
"""

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.audit import PoolAuditor, PoolCorruptionError
from deepspeed_tpu.inference.engine import sample_logits
from deepspeed_tpu.inference.kv_cache import (BlockAllocator, TRASH_BLOCK,
                                              blocks_needed, max_written_pos,
                                              transplant_blocks)
from deepspeed_tpu.inference.spec_decode import accept_greedy, make_drafter
from deepspeed_tpu.telemetry import Telemetry
from deepspeed_tpu.utils.logging import log_dist


class InadmissibleRequestError(ValueError):
    """The request can NEVER be admitted by this engine — the prompt plus
    its generation budget exceeds `max_context`, or it needs more KV blocks
    than the whole pool holds. Raised at submit() so an impossible request
    fails fast instead of wedging the FIFO head forever; the serving router
    catches it per replica to find one whose limits do fit."""


@dataclasses.dataclass
class Request:
    """One generation request. `eos_token_id=None` falls back to the engine /
    model default; `stop_on_eos=False` disables early stop entirely.

    `deadline_ms` is a hard end-to-end budget from submission: unlike the
    router's TTL (which only cancels QUEUED requests), the deadline is
    enforced past admission — a request still generating when its budget
    runs out retires at the next scheduler sync with
    ``finish_reason="deadline"`` (tokens emitted so far are kept).
    `priority` orders degradation-time shedding (`serving/degradation.py`):
    under the ladder's top level, queued requests with priority below the
    configured threshold are shed first; it never affects FIFO order."""
    uid: Any
    tokens: Sequence[int]
    max_new_tokens: int = 32
    eos_token_id: Optional[int] = None
    stop_on_eos: bool = True
    deadline_ms: Optional[float] = None
    priority: int = 0


@dataclasses.dataclass
class CompletedRequest:
    uid: Any
    prompt_len: int
    tokens: np.ndarray        # generated tokens; the EOS (if emitted) is kept
    finish_reason: str        # "eos" | "length" | "cancelled" (withdrawn via
                              # cancel() before finishing; router TTL/shedding
                              # surfaces as this too) | "deadline" (hard
                              # per-request budget expired mid-flight)
    cached_prefix_tokens: int = 0  # prompt tokens whose KV came from the
                              # prefix cache (0 when caching is off/missed)
    timing: Optional[Dict[str, float]] = None  # telemetry only: monotonic
                              # arrival/admit/first_token/finish stamps
                              # (None when telemetry is disabled)


_FREE, _PREFILL, _DECODE, _HANDOFF = 0, 1, 2, 3


class _Slot:
    __slots__ = ("idx", "state", "uid", "prompt", "prompt_len", "padded_len",
                 "max_new", "eos", "blocks", "cursor", "pos", "emitted",
                 "hashes", "reg", "cached", "prefill_only", "deadline",
                 "t_arrive", "t_admit", "t_first", "t_prev", "trace")

    def __init__(self, idx):
        self.idx = idx
        self.reset()

    def reset(self):
        self.state = _FREE
        self.uid = self.prompt = None
        self.prompt_len = self.padded_len = self.max_new = 0
        self.eos = None
        self.blocks = []
        self.cursor = self.pos = 0
        self.emitted = []
        self.hashes = None      # prefix-cache hash chain (full prompt blocks)
        self.reg = 0            # blocks [0, reg) already registered/cached
        self.cached = 0         # blocks mapped from the cache at admission
        self.prefill_only = False  # disaggregated serving: park in _HANDOFF
                                # after the last chunk instead of decoding
        self.deadline = None    # absolute hard deadline (engine clock)
        self.t_arrive = self.t_admit = self.t_first = None  # telemetry stamps
        self.t_prev = None      # last emission sync (TPOT interpolation anchor)
        self.trace = None       # TraceContext (None unless tracing is on)


class ServingEngine:
    """Continuous-batching server on top of an `InferenceEngine` whose model
    spec carries the paged contract (prefill_paged_fn / decode_paged_fn /
    init_paged_pool — the GPT zoo provides it).

    Usage::

        serving = engine.serving(max_slots=8, max_context=2048)
        serving.submit(Request(uid=0, tokens=prompt, max_new_tokens=64))
        while True:
            for done in serving.step():
                ...                       # done.tokens, done.finish_reason
        # or, batch-style: results = serving.run(requests)
    """

    def __init__(self, engine, draft_spec=None, clock=None, **overrides):
        spec = engine.model_spec
        # streamed (offloaded-weights) mode: a LayeredModelSpec served
        # through a ZeroInferenceEngine — the stacked blocks live in the
        # host/disk store and ONE jitted per-layer program walks the paged
        # pool with weights fed by the async staging pool. The resident
        # mode's whole-model paged contract is replaced by the per-layer
        # one (layer_paged_fn + embed/final).
        self.streamed = getattr(spec, "layer_paged_fn", None) is not None \
            and getattr(spec, "prefill_paged_fn", None) is None
        if self.streamed:
            missing = [n for n in ("layer_paged_fn", "init_paged_pool",
                                   "embed_fn", "final_fn")
                       if getattr(spec, n, None) is None]
            if missing:
                raise ValueError(
                    f"layered model spec '{spec.name}' has no streamed "
                    f"paged contract (missing {missing}); build it with "
                    f"make_gpt_layered_model")
        else:
            missing = [n for n in ("prefill_paged_fn", "decode_paged_fn",
                                   "init_paged_pool")
                       if getattr(spec, n, None) is None]
            if missing:
                raise ValueError(
                    f"model spec '{spec.name}' has no paged serving contract "
                    f"(missing {missing}); build it with make_gpt_decode_model "
                    f"or serve through generate()")
        self.engine = engine
        self.config = engine.config
        scfg = dataclasses.replace(engine.config.serving, **overrides)
        if isinstance(scfg.spec_decode, dict):
            # `serving(spec_decode={"drafter": "ngram", ...})` overrides
            from deepspeed_tpu.inference.config import SpecDecodeConfig
            scfg = dataclasses.replace(
                scfg, spec_decode=SpecDecodeConfig.from_dict(scfg.spec_decode))
        if isinstance(scfg.degradation, dict):
            # `serving(degradation={"enabled": True, ...})` overrides
            from deepspeed_tpu.inference.config import DegradationConfig
            scfg = dataclasses.replace(
                scfg, degradation=DegradationConfig.from_dict(scfg.degradation))
        if isinstance(scfg.quantization, dict):
            # `serving(quantization={"kv_cache_dtype": "int8", ...})` overrides
            from deepspeed_tpu.inference.config import ServingQuantizationConfig
            scfg = dataclasses.replace(
                scfg,
                quantization=ServingQuantizationConfig.from_dict(
                    scfg.quantization))
        self.serving_config = scfg

        # quantized serving (inference/quantization.py). Weight-only quant
        # runs FIRST — it replaces the engine's resident param tree (and its
        # dequantize-on-use fn transform), which everything below snapshots:
        # the step programs close over the transform, memscope's preflight
        # sizes params_bytes from the live tree, and the pool capacity math
        # should see the post-quant weights footprint.
        qcfg = scfg.quantization
        weights = str(qcfg.weights or "off")
        if weights not in ("off", "int8", "int4"):
            raise ValueError(
                f"unknown serving.quantization.weights {weights!r} "
                f"(expected 'off', 'int8' or 'int4')")
        self.weight_quant = weights
        self.weight_quant_stats = None
        if weights != "off":
            self.weight_quant_stats = engine.enable_weight_quant(
                bits=8 if weights == "int8" else 4,
                group_size=int(qcfg.weight_group_size))
        # effective KV-pool dtype: the quantization block wins, else the
        # engine-level kv_cache_dtype (so a plain engine config can still
        # select the int8 pool for every serving engine it builds)
        kvd = str(qcfg.kv_cache_dtype or "") or str(engine.config.kv_cache_dtype)
        # ONE alias table for dtype spellings (bf16/fp16/torch.* etc.):
        # the engine config's legacy map, not a second copy that drifts
        kvd = getattr(type(engine.config), "_LEGACY_DTYPES", {}).get(kvd, kvd)
        # int8 is the ONE quantized layout (scale leaves + quantized write
        # path); every other integer dtype would silently truncate float
        # K/V into a handful of levels through the fp write path's cast —
        # refuse it here instead of serving garbage with a happy log line
        if kvd != "int8" and not jnp.issubdtype(jnp.dtype(kvd),
                                                jnp.floating):
            raise ValueError(
                f"unsupported KV-cache dtype {kvd!r}: expected a float "
                f"dtype or 'int8' (the quantized paged pool — "
                f"serving.quantization.kv_cache_dtype)")
        self.kv_cache_dtype = kvd
        self.kv_quant = kvd == "int8"
        self.kv_group_size = int(qcfg.kv_group_size or 0)
        # injectable clock (tests pin TTFT/TPOT interpolation with it; the
        # router injects its own for TTL — this one stamps request timing)
        self._clock = clock if clock is not None else time.monotonic

        bs = int(getattr(engine.config, "kv_block_size", 0) or 0)
        if bs <= 0:
            raise ValueError("serving needs kv_block_size > 0 (the paged "
                             "pool's physical block unit)")
        self.block_size = bs
        self.max_context = int(scfg.max_context or engine.config.max_out_tokens)
        self.nb = -(-self.max_context // bs)       # block-table width
        self.max_slots = int(scfg.max_slots)
        self.chunk = int(scfg.prefill_chunk or bs)
        self.prefill_budget = max(1, int(scfg.prefill_chunks_per_step))
        self.window = max(1, int(scfg.decode_steps_per_sync))
        # speculative decoding: the verify step REPLACES the decode step
        # (and its window) when a drafter is configured
        self.spec_on = str(scfg.spec_decode.drafter or "off") != "off"
        if self.streamed:
            # streamed-mode envelope: every decode token already walks the
            # host link once (the cost model of the tier) — a K-step jitted
            # window or a verify chunk cannot host a per-layer Python walk,
            # so both are refused rather than silently degraded
            if self.spec_on:
                raise ValueError(
                    "speculative decoding is a resident-engine feature: the "
                    "streamed (offloaded-weights) serving mode walks one "
                    "jitted per-layer program per token and has no verify "
                    "contract — drop spec_decode, or serve resident")
            if self.window != 1:
                raise ValueError(
                    f"decode_steps_per_sync={self.window} needs the whole "
                    f"stack resident inside one jitted scan; the streamed "
                    f"(offloaded-weights) mode streams layers through HBM "
                    f"per token — set decode_steps_per_sync=1")
        self.draft_k = int(scfg.spec_decode.draft_k) if self.spec_on else 0
        if self.spec_on and spec.verify_paged_fn is None:
            raise ValueError(
                f"model spec '{spec.name}' has no verify_paged_fn — "
                f"speculative decoding needs the k-token paged verify "
                f"contract (make_gpt_decode_model provides it)")
        num_blocks = int(scfg.num_kv_blocks or
                         (self.max_slots * self.nb + 1))

        # telemetry (deepspeed_tpu/telemetry/): TTFT/TPOT/queue-wait/e2e
        # histograms + queue/slot/pool gauges + per-phase spans — built
        # BEFORE the step programs so the compile watchdog can wrap them.
        # Disabled by default — then every record site below is a single
        # attribute check and NOTHING is written anywhere.
        self.telemetry = Telemetry(getattr(engine.config, "telemetry", None),
                                   subsystem="serving")
        if self.telemetry.enabled and self.spec_on:
            # acceptance rates live in [0, 1] — the default log-scale ms
            # buckets would smear them into one decade; pin linear bounds
            self.telemetry.registry.histogram(
                "serving/spec_accept_rate",
                bounds=[i / 20 for i in range(1, 21)])
        # request tracing + flight recorder: the engine's own (from its
        # telemetry config) until a router injects the POOL-shared ones
        # via attach_observability — then every replica's spans land in
        # one file under one trace id, on one Perfetto track per replica
        self.tracer = self.telemetry.tracer
        self.flightrec = self.telemetry.flightrec
        self.trace_tid = 0

        # memscope pre-flight runs BEFORE the pool device_put below: the
        # plan is pure shape arithmetic (jax.eval_shape over
        # init_paged_pool — no device memory touched), so a predicted-OOM
        # config can warn or refuse ahead of the allocation that would
        # otherwise crash a real chip with a raw RESOURCE_EXHAUSTED
        tcfg = getattr(engine.config, "telemetry", None)
        self._memscope_on = self.telemetry.enabled and \
            getattr(tcfg, "memscope", False)
        self._preflight_plan = None
        if self._memscope_on:
            from deepspeed_tpu.telemetry import memscope as _ms
            mode = str(getattr(tcfg, "memscope_preflight", "warn"))
            if mode != "off":
                cap = int(getattr(tcfg, "memscope_capacity_bytes", 0) or 0) \
                    or int(_ms.device_memory_stats().get("bytes_limit", 0)
                           or 0)
                plan = _ms.plan_serving_prealloc(
                    spec, num_kv_blocks=num_blocks, kv_block_size=bs,
                    kv_cache_dtype=self.kv_cache_dtype,
                    kv_group_size=self.kv_group_size,
                    params=engine.params,
                    draft_spec=draft_spec
                    if scfg.spec_decode.drafter == "model" else None,
                    param_dtype=engine.dtype, capacity_bytes=cap)
                self._preflight_plan = _ms.preflight_check(
                    plan, refuse=(mode == "refuse"))

        # place the pool with the engine mesh's (replicated) NamedSharding up
        # front: the step programs RETURN pools with exactly this sharding,
        # so a plain uncommitted jnp.zeros pool would give the very first
        # call of each program a different arg signature than every later
        # call — one phantom extra compile, which the serving compile-count
        # guarantee (and its test) would flag
        from jax.sharding import NamedSharding, PartitionSpec
        if self.kv_quant:
            # int8 pool: payload + per-group scale leaves. The 4-arg call is
            # part of the quantized paged contract — a 3-arg legacy spec
            # raises TypeError right here, and a spec that accepts the group
            # arg but returns a scale-less pool is caught just below; both
            # get the same pointer at the contract instead of a bare
            # arity/shape error
            try:
                pool = spec.init_paged_pool(num_blocks, bs, jnp.int8,
                                            self.kv_group_size)
            except TypeError as e:
                raise ValueError(
                    f"model spec '{spec.name}' init_paged_pool does not "
                    f"accept the 4-arg quantized form "
                    f"(num_blocks, block_size, dtype, kv_group_size) — it "
                    f"does not implement the quantized-pool contract "
                    f"(init_paged_kv_pool in models/gpt.py is the "
                    f"reference): {e}") from e
            if not (isinstance(pool, dict) and "k_scale" in pool):
                raise ValueError(
                    f"model spec '{spec.name}' init_paged_pool returned no "
                    f"k_scale/v_scale leaves for dtype int8 — it does not "
                    f"implement the quantized-pool contract "
                    f"(init_paged_kv_pool in models/gpt.py is the reference)")
        else:
            pool = spec.init_paged_pool(num_blocks, bs, jnp.dtype(kvd))
        self.pool = jax.device_put(
            pool, NamedSharding(engine.mesh, PartitionSpec()))
        self.allocator = BlockAllocator(
            num_blocks, policy=str(scfg.prefix_cache_policy or "lru"))
        self.prefix_cache = None
        if scfg.enable_prefix_caching:
            from deepspeed_tpu.inference.prefix_cache import PrefixCache
            self.prefix_cache = PrefixCache(
                self.allocator, bs,
                fingerprint=spec.cache_fingerprint or spec.name)
        self.tables = np.full((self.max_slots, self.nb), TRASH_BLOCK, np.int32)
        self.slots = [_Slot(i) for i in range(self.max_slots)]
        self.queue = collections.deque()

        self._rng = jax.random.PRNGKey(0)
        if self.streamed and self.telemetry.enabled:
            # the staging pool's offload/* metrics (stage-wait, occupancy,
            # in-flight bytes) land in THIS engine's serving registry
            engine.streamer.telemetry = self.telemetry
            engine.store.telemetry = self.telemetry
        self._build_step_fns()

        # drafter AFTER pool/allocator: the draft-model drafter mirrors the
        # pool geometry and shares the block tables (spec_decode.py)
        if draft_spec is not None and scfg.spec_decode.drafter != "model":
            raise ValueError(
                f"draft_spec was passed but spec_decode.drafter is "
                f"{scfg.spec_decode.drafter!r} — only the 'model' drafter "
                f"consumes it (did you mean spec_decode="
                f"{{'drafter': 'model', ...}}?)")
        self.drafter = make_drafter(self, scfg.spec_decode,
                                    draft_spec=draft_spec) \
            if self.spec_on else None

        # HBM memory ledger + OOM forensics (telemetry/memscope.py):
        # per-subsystem byte attribution as mem/* gauges plus the
        # ledger+planner+flight dump on RESOURCE_EXHAUSTED in step().
        # Built AFTER the drafter so the draft mirror is on the ledger;
        # the capacity verdict already ran pre-allocation above (its plan
        # becomes last_plan — the OOM dump's "was this foreseeable" base);
        # disabled default = no object, no gauges, untouched compile_stats
        self.memscope = None
        if self._memscope_on:
            from deepspeed_tpu.telemetry.memscope import ServingMemScope
            self.memscope = ServingMemScope(self)
            self.memscope.last_plan = self._preflight_plan

        # self-healing: pool invariant auditor (inference/audit.py) — pure
        # host-side reads, run every `audit_interval` syncs / on demand /
        # at close(); on violation: flight dump, then repair-or-raise
        self.audit_interval = int(scfg.audit_interval or 0)
        self.audit_action = str(scfg.audit_action or "repair")
        if self.audit_action not in ("repair", "raise"):
            raise ValueError(f"unknown audit_action {self.audit_action!r} "
                             f"(expected 'repair' or 'raise')")
        self._auditor = PoolAuditor(self)
        self.audits_run = 0
        self.audit_violations_total = 0
        self.audit_repairs = 0

        # graceful degradation (serving/degradation.py): disabled default
        # means the controller is never built — the hot path, the compiled
        # programs and compile_stats() are byte-identical without it
        self.pressure = None
        if scfg.degradation.enabled:
            from deepspeed_tpu.serving.degradation import PressureController
            self.pressure = PressureController(self, scfg.degradation)
        self._decode_step_w1 = None   # lazily-built 1-step decode program
                                      # (degradation fallback; also the spec-
                                      # decode-disabled path, whose block
                                      # sizing has no window-rounding tail)
        self._deadlines = False       # any live request carries a deadline

        # observability
        self.steps = 0
        self.decode_steps = 0
        self.prefill_chunks = 0
        self.prefill_chunks_skipped = 0     # chunks the prefix cache elided
        self.prefix_hit_blocks = 0
        self.prefix_hit_tokens = 0
        self.tokens_generated = 0
        self.peak_active = 0
        self.cancelled = 0                  # requests withdrawn via cancel()
        self.deadline_cancelled = 0         # requests retired reason="deadline"
        self.degradation_sheds = 0          # queued requests shed by the
                                            # pressure controller's top rung
        self.handoffs_out = 0               # slots exported to a decode engine
        self.handoffs_in = 0                # slots adopted from a prefill engine
        self.verify_calls = 0               # spec decode: jitted verify steps
        self.verify_slot_steps = 0          # per-slot verify participations —
                                            # the denominator of the per-
                                            # sequence tokens/step multiple
        self.drafted_tokens = 0             # real (non-padding) proposals scored
        self.accepted_tokens = 0            # drafts that matched the target
        self.spec_emitted_tokens = 0        # tokens emitted by verify steps
                                            # (accepted + one bonus each)

        pool_mb = sum(x.size * x.dtype.itemsize
                      for x in jax.tree_util.tree_leaves(self.pool)) / 2**20
        log_dist(f"serving engine: {spec.name} slots={self.max_slots} "
                 f"blocks={num_blocks}x{bs} ({pool_mb:.0f} MB pool, "
                 f"kv={self.kv_cache_dtype}) table_width={self.nb} "
                 f"prefill_chunk={self.chunk} weights={self.weight_quant}",
                 ranks=[0])

    # ------------------------------------------------------------------
    # compiled step programs — built once, shapes pinned for the lifetime
    # ------------------------------------------------------------------

    def _build_step_fns(self):
        if self.streamed:
            self._build_streamed_step_fns()
            return
        spec = self.engine.model_spec
        cfg = self.engine.config
        decode_paged = self.engine._fn_transform(spec.decode_paged_fn)
        prefill_paged = self.engine._fn_transform(spec.prefill_paged_fn)

        def sample(logits, rng):
            return sample_logits(logits, rng, greedy=cfg.greedy,
                                 temperature=cfg.temperature, top_k=cfg.top_k,
                                 top_p=cfg.top_p)

        def make_decode_step(window):
            """Build the decode-WINDOW program: `window` tokens per sync
            inside one lax.scan (multi-step scheduling). One device call +
            one host roundtrip amortize over the whole window — the
            dispatch-latency lever. Returns emitted tokens [S, window]: the
            window of successors of the input token, with the input's k/v
            (and each successor's but the last) written into the pool along
            the way. A builder, not a single closure, because the pressure
            controller's window-shrink rung needs a second, 1-step variant
            of the same program built lazily at degradation time."""

            def decode_step(params, tok, pos, pool, tables, rng):
                if window == 1:  # no scan wrapper: keep the 1-step hot path
                    logits, pool = decode_paged(params, tok, pos, pool,
                                                tables)
                    return sample(logits, rng)[:, None], pool

                def body(carry, _):
                    tok, pos, pool, rng = carry
                    rng, sub = jax.random.split(rng)
                    logits, pool = decode_paged(params, tok, pos, pool,
                                                tables)
                    nxt = sample(logits, sub)
                    return (nxt, pos + 1, pool, rng), nxt

                (_, _, pool, _), toks = jax.lax.scan(
                    body, (tok, pos, pool, rng), None, length=window)
                return jnp.moveaxis(toks, 0, 1), pool

            return decode_step

        self._make_decode_fn = make_decode_step
        decode_step = make_decode_step(self.window)

        def prefill_step(params, toks, start, last_idx, pool, table, rng):
            logits, pool = prefill_paged(params, toks, start, last_idx, pool,
                                         table)
            return sample(logits, rng), pool

        # the pool is donated: the update is in-place in HBM, the old buffer
        # is dead the moment the step returns the new one. The compile
        # watchdog (telemetry/flight_recorder.py) wraps each program when
        # telemetry is on: the serving promise is ONE compile each for the
        # engine's lifetime, and any cache miss after that warmup is
        # recorded (program name, shapes, compile_ms) — with telemetry off,
        # wrap() returns the jitted function untouched.
        wd = self.telemetry.watchdog
        self._decode_step = wd.wrap(
            "decode_step", jax.jit(decode_step, donate_argnums=(3,)))
        self._prefill_step = wd.wrap(
            "prefill_step", jax.jit(prefill_step, donate_argnums=(4,)))

        self._verify_step = None
        if self.spec_on:
            verify_paged = self.engine._fn_transform(spec.verify_paged_fn)
            K1 = self.draft_k + 1

            def verify_step(params, toks, pos, pool, tables, rng):
                """Fixed-shape verify: score the k drafts of every slot in
                ONE call — tokens [S, k+1] (col 0 = last emitted token at
                the cursor, cols 1..k = drafts), positions pos..pos+k per
                row, all k+1 tokens' k/v written through the tables along
                the way. Returns the SAMPLED token per position [S, k+1]:
                under greedy config that is the argmax — the exact-match
                acceptance target; under stochastic sampling it is the
                target model's own draw, so exact-match acceptance is the
                conservative sample-and-match scheme (output distribution
                preserved; the true rejection-sampling upgrade would
                return per-position probabilities here instead)."""
                logits, pool = verify_paged(params, toks, pos, pool, tables)
                S, V = logits.shape[0], logits.shape[-1]
                tgt = sample(logits.reshape(S * K1, V),
                             rng).reshape(S, K1)
                return tgt, pool

            self._verify_step = wd.wrap(
                "verify_step", jax.jit(verify_step, donate_argnums=(3,)))

    def _build_streamed_step_fns(self):
        """Step programs for the offloaded-weights (streamed) mode: the
        whole-model paged programs are replaced by SIX single-signature
        jitted programs — {embed, layer, head} x {prefill, decode} — and a
        host loop that walks the layer program L times per call, weights
        fed by the engine's async staging pool (layer i computes while
        layer i+1's upload and layer i+2's disk read are in flight). The
        layer index is TRACED (the pool's layer axis is dynamic-sliced and
        written back in place via donation), so every layer of the walk
        shares one compile; the serving promise becomes one compile per
        PROGRAM, six programs total, asserted by compile_stats() exactly
        like the resident mode's two."""
        spec = self.engine.model_spec
        cfg = self.engine.config
        L = self.engine.store.num_layers
        streamer = self.engine.streamer

        def sample(logits, rng):
            return sample_logits(logits, rng, greedy=cfg.greedy,
                                 temperature=cfg.temperature, top_k=cfg.top_k,
                                 top_p=cfg.top_p)

        # separate prefill/decode jits per role: each program then has
        # exactly ONE call signature for the engine's lifetime, keeping the
        # compile-watchdog contract as sharp as the resident mode's. The
        # factories mint DISTINCT function objects per phase — jax.jit
        # wrappers over one function share a single compile cache, which
        # would double every program's reported count.

        def make_embed():
            def embed(res, toks, positions):
                return spec.embed_fn(res, toks, positions)
            return embed

        def make_layer():
            def layer(p, x, layer_idx, pool, tables, positions):
                return spec.layer_paged_fn(p, x, layer_idx, pool, tables,
                                           positions)
            return layer

        def make_head():
            def head(res, x, last_idx, rng):
                last = jnp.take_along_axis(x, last_idx[:, None, None],
                                           axis=1)
                logits = spec.final_fn(res, last)[:, 0]
                return sample(logits, rng)
            return head

        wd = self.telemetry.watchdog
        self._embed_prefill = wd.wrap("embed_prefill", jax.jit(make_embed()))
        self._embed_decode = wd.wrap("embed_decode", jax.jit(make_embed()))
        self._layer_prefill = wd.wrap(
            "layer_prefill", jax.jit(make_layer(), donate_argnums=(3,)))
        self._layer_decode = wd.wrap(
            "layer_decode", jax.jit(make_layer(), donate_argnums=(3,)))
        self._head_prefill = wd.wrap("head_prefill", jax.jit(make_head()))
        self._head_decode = wd.wrap("head_decode", jax.jit(make_head()))

        def prefill_step(params, toks, start, last_idx, pool, table, rng):
            B, C = toks.shape
            positions = np.asarray(start, np.int32)[:, None] + \
                np.arange(C, dtype=np.int32)[None]
            x = self._embed_prefill(params, toks, positions)
            for i in range(L):
                x, pool = self._layer_prefill(streamer.layer(i), x,
                                              np.int32(i), pool, table,
                                              positions)
            return self._head_prefill(params, x,
                                      np.asarray(last_idx, np.int32),
                                      rng), pool

        def decode_step(params, tok, pos, pool, tables, rng):
            S = np.shape(tok)[0]
            positions = np.asarray(pos, np.int32)[:, None]
            x = self._embed_decode(params, np.asarray(tok, np.int32)[:, None],
                                   positions)
            for i in range(L):
                x, pool = self._layer_decode(streamer.layer(i), x,
                                             np.int32(i), pool, tables,
                                             positions)
            tok_next = self._head_decode(params, x, np.zeros(S, np.int32),
                                         rng)
            return tok_next[:, None], pool

        self._prefill_step = prefill_step
        self._decode_step = decode_step
        self._verify_step = None

    def _degraded_decode_step(self):
        """The 1-step decode program, built lazily the first time a
        degraded path needs it: the spec-decode-disabled fallback (whose
        block sizing carries a k-draft overhang, not a window-rounding
        tail, so running the K-step window could write past the allocated
        blocks) and the pressure ladder's window-shrink rung. One extra
        warmup compile at first engagement; `compile_stats()` reports it
        as `decode_step_w1` from then on."""
        if self.window == 1:
            return self._decode_step
        if self._decode_step_w1 is None:
            self._decode_step_w1 = self.telemetry.watchdog.wrap(
                "decode_step_w1",
                jax.jit(self._make_decode_fn(1), donate_argnums=(3,)))
        return self._decode_step_w1

    def _next_rng(self):
        if self.config.greedy:
            return self._rng                        # unused by the sampler
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ------------------------------------------------------------------
    # request lifecycle
    # ------------------------------------------------------------------

    def check_admissible(self, prompt_len: int, max_new: int,
                         prefill_only: bool = False, uid: Any = "?",
                         padded_prompt: int = None) -> int:
        """Sizing validation shared by submit() and the serving router's
        replica scoring: raises `InadmissibleRequestError` when the request
        can NEVER fit this engine (max_context table width, whole-pool
        block budget), else returns the blocks it will occupy. A
        `prefill_only` request never decodes here (its slot hands off to a
        decode replica), so only the padded prompt counts — no decode-write
        or window-rounding tail. `padded_prompt` overrides this engine's
        own chunk-grid padding: a handoff TARGET adopts a slot padded on
        the PREFILL replica's grid, so the router validates decode
        replicas against that width, not their own."""
        prompt_len = int(prompt_len)
        max_new = int(max_new)
        padded = (int(padded_prompt) if padded_prompt else
                  -(-prompt_len // self.chunk) * self.chunk)
        if prompt_len < 1:
            raise InadmissibleRequestError(f"request {uid}: empty prompt")
        if max_new < 1:
            raise InadmissibleRequestError(
                f"request {uid}: max_new_tokens < 1")
        eff_new = 1 if prefill_only else max_new
        eff_window = 1 if prefill_only else self.window
        # a verify step always writes its full k-draft overhang, so spec
        # decode sizes past the window math (which it replaces); a
        # prefill-only slot never verifies here
        eff_spec = 0 if prefill_only else self.draft_k
        need = blocks_needed(prompt_len, padded, eff_new, self.block_size,
                             window=eff_window, spec_k=eff_spec)
        if max_written_pos(prompt_len, padded, eff_new, eff_window,
                           eff_spec) >= self.max_context:
            raise InadmissibleRequestError(
                f"request {uid}: prompt {prompt_len} + max_new "
                f"{max_new} (window {eff_window}, draft_k {eff_spec}) "
                f"exceeds max_context {self.max_context} "
                f"(raise serving.max_context)")
        if need > self.allocator.capacity:
            raise InadmissibleRequestError(
                f"request {uid}: needs {need} KV blocks, pool has "
                f"{self.allocator.capacity} (raise serving.num_kv_blocks)")
        return need

    def attach_observability(self, tracer=None, flightrec=None, tid=None):
        """Router injection point: share the POOL's tracer / flight
        recorder (so every replica's spans land in one trace file and one
        black box) and take this engine's Perfetto track id. Standalone
        engines keep their own from the telemetry config."""
        if tracer is not None:
            self.tracer = tracer
        if flightrec is not None:
            self.flightrec = flightrec
        if tid is not None:
            self.trace_tid = int(tid)

    def set_clock(self, clock):
        """Unified clock injection (the router calls this on every replica,
        and again after a restart): TTL at the router, the TTFT/TPOT stamps
        and hard-deadline sweep here, and the watchdog/hedging timers all
        read ONE time source, so a chaos test drives the whole pool's time
        deterministically. Absolute `deadline_at` values stay comparable
        across replicas because every engine shares the router's clock."""
        self._clock = clock

    def submit(self, request: Request, prefill_only: bool = False,
               hashes: Optional[List[bytes]] = None, trace=None,
               deadline_at: Optional[float] = None):
        """Queue a request. Raises `InadmissibleRequestError` if it can
        NEVER be admitted (it exceeds the engine's max_context table width
        or the whole pool); a request that merely doesn't fit *right now*
        waits in the queue (admission backpressure). The prompt copy and
        sizing math happen once, here — the admission loop re-reads the
        precomputed record every step while backpressured.

        `prefill_only=True` is the disaggregated-serving entry: the slot
        runs chunked prefill, samples its first token, then parks in a
        handoff state (`export_handoff` / `adopt_handoff`) instead of
        decoding — the router transplants its blocks into a decode
        replica. `hashes` hands in a precomputed chain (the router hashes
        once per request for affinity scoring; chains are
        fingerprint-identical across a pool, so re-hashing per dispatch —
        and again per failover re-dispatch — would be pure waste).
        `trace` carries the router's `TraceContext`; a standalone engine
        with tracing on mints its own here, so the request's whole life is
        one connected span tree either way. `deadline_at` pins the hard
        deadline ABSOLUTELY (on this engine's clock) — the router passes
        the original submit-time deadline through every re-dispatch so a
        failover rerun or a hedged duplicate never extends the budget;
        without it, `request.deadline_ms` anchors at arrival here."""
        prompt = np.asarray(request.tokens, np.int32).reshape(-1)
        prompt_len = int(prompt.shape[0])
        padded = -(-prompt_len // self.chunk) * self.chunk
        need = self.check_admissible(prompt_len, request.max_new_tokens,
                                     prefill_only=prefill_only,
                                     uid=request.uid)
        # hash once at submit; the admission loop re-matches the chain every
        # step while backpressured (cache contents change between steps)
        if self.prefix_cache is None:
            hashes = None
        elif hashes is None:
            hashes = self.prefix_cache.hash_chain(prompt)
        t_arrive = self._clock()
        if deadline_at is None and request.deadline_ms is not None:
            deadline_at = t_arrive + float(request.deadline_ms) / 1e3
        if deadline_at is not None:
            self._deadlines = True
        if self.tracer.enabled:
            if trace is None:
                # no router above: this engine owns the trace end to end
                trace = self.tracer.start(request.uid, t0=t_arrive,
                                          owner="engine")
            self.tracer.event(trace, "submit", t_arrive, tid=self.trace_tid,
                              attrs={"prompt_len": prompt_len,
                                     "max_new": int(request.max_new_tokens)})
        self.queue.append((request, prompt, prompt_len, padded, need, hashes,
                           t_arrive, prefill_only, trace, deadline_at))

    def _resolve_eos(self, req: Request):
        if not req.stop_on_eos:
            return None
        eos = req.eos_token_id
        if eos is None:
            eos = getattr(self.config, "eos_token_id", None)
        if eos is None:
            eos = self.engine.model_spec.eos_token_id
        return eos

    def _admit(self, finished: List[CompletedRequest]):
        free = [s for s in self.slots if s.state == _FREE]
        while self.queue and free:
            (req, prompt, prompt_len, padded, need, hashes,
             t_arrive, prefill_only, trace, deadline_at) = self.queue[0]
            if deadline_at is not None and self._clock() >= deadline_at:
                # dead on arrival at the slot: don't burn prefill compute
                # on a request whose budget already expired in the queue
                self.queue.popleft()
                finished.append(self._expire_queued(req.uid, prompt_len))
                continue
            hit = []
            if hashes:
                # longest-prefix match, capped so at least the final prompt
                # token is always prefilled — its logits seed the first
                # sampled token, so a 100%-cached prompt still runs one
                # chunk. The hit is then truncated to whole-CHUNK coverage:
                # prefill chunks start on the absolute j*chunk grid, so a
                # partial-chunk hit saves nothing (its chunk re-runs in
                # full) and would overstate every hit counter — and
                # dropping it means no chunk ever overlaps a shared block,
                # so registered blocks are never written again, period.
                # incref BEFORE alloc: the hit blocks may be sitting
                # refcount-0 on the reclaimable list, and our own alloc's
                # eviction must not recycle them out from under the match.
                limit = (prompt_len - 1) // self.block_size
                hit = self.prefix_cache.match(hashes[:limit])
                m = len(hit)
                while m and (m * self.block_size) % self.chunk:
                    m -= 1
                hit = hit[:m]
                for b in hit:
                    self.allocator.incref(b)
            ev0 = self.allocator.evictions
            blocks = self.allocator.alloc(need - len(hit))
            if blocks is None:
                if self.flightrec.enabled:
                    self.flightrec.record(
                        "backpressure", uid=req.uid, need=need - len(hit),
                        available=self.allocator.available,
                        queued=len(self.queue))
                # pool exhausted: FIFO backpressure — the head waits for
                # retirements to free blocks (no reordering: a stream of
                # small requests must not starve a big one). Decref the
                # tentative hit tail-first, like _retire: the chain head
                # must park most-recent so demand eviction trims tails
                # before it strands a whole chain
                if hit:
                    self.allocator.free(hit[::-1])
                break
            blocks = hit + blocks
            self.queue.popleft()
            slot = free.pop()
            slot.state = _PREFILL
            slot.uid = req.uid
            slot.prompt = prompt
            slot.prompt_len = prompt_len
            slot.padded_len = padded
            slot.max_new = int(req.max_new_tokens)
            slot.eos = self._resolve_eos(req)
            slot.blocks = blocks
            # prefill resumes at the cached boundary — exactly on the chunk
            # grid, because the hit was truncated to whole-chunk coverage
            # above. With the default prefill_chunk == kv_block_size every
            # hit block skips a whole chunk.
            slot.cursor = len(hit) * self.block_size
            slot.hashes = hashes
            slot.reg = len(hit)
            slot.cached = len(hit)
            slot.pos = prompt_len
            slot.emitted = []
            slot.prefill_only = prefill_only
            slot.deadline = deadline_at
            slot.t_arrive = t_arrive
            if self.telemetry.enabled:
                slot.t_admit = self._clock()
                self.telemetry.observe("serving/queue_wait_ms",
                                       (slot.t_admit - t_arrive) * 1e3)
            slot.trace = trace
            if self.tracer.enabled and trace is not None:
                # the queue-wait span + an admit mark; flow_end lands the
                # router's dispatch arrow on THIS replica's Perfetto track
                t_adm = slot.t_admit if slot.t_admit is not None \
                    else self._clock()
                self.tracer.flow_end(trace, t_adm, tid=self.trace_tid)
                self.tracer.record(trace, "queued", t_arrive,
                                   max(0.0, t_adm - t_arrive),
                                   tid=self.trace_tid)
                self.tracer.event(trace, "admit", t_adm, tid=self.trace_tid,
                                  attrs={"slot": slot.idx,
                                         "blocks": len(blocks),
                                         "cached_blocks": len(hit)})
            if self.flightrec.enabled:
                # admission decision: the black box's bread and butter
                self.flightrec.record("admit", uid=req.uid, slot=slot.idx,
                                      blocks=len(blocks),
                                      cached_blocks=len(hit),
                                      queued=len(self.queue))
                if self.allocator.evictions > ev0:
                    self.flightrec.record(
                        "eviction", uid=req.uid,
                        evicted=self.allocator.evictions - ev0)
            self.tables[slot.idx, :] = TRASH_BLOCK
            self.tables[slot.idx, :len(blocks)] = blocks
            if hit:
                self.prefix_hit_blocks += len(hit)
                self.prefix_hit_tokens += len(hit) * self.block_size
                self.prefill_chunks_skipped += slot.cursor // self.chunk

    def _retire(self, slot: _Slot, reason: str) -> CompletedRequest:
        # blocks return to the pool the step the sequence finishes — a
        # DECREF: blocks shared through the prefix cache stay live until
        # their last reader retires, and registered refcount-0 blocks park
        # on the reclaimable list instead of the free list. Freed in
        # REVERSE block order so the hash-chain TAIL parks LRU-oldest:
        # demand eviction then trims chains tail-first, and the surviving
        # prefix stays matchable (match walks head-first and stops at the
        # first unregistered hash — evicting a head strands its whole tail)
        self.allocator.free(slot.blocks[::-1])
        self.tables[slot.idx, :] = TRASH_BLOCK
        if self.drafter is not None:
            self.drafter.retire(slot)       # stateful drafters drop slot state
        timing = None
        if self.telemetry.enabled and slot.t_admit is not None:
            t_finish = self._clock()
            self.telemetry.observe("serving/e2e_ms",
                                   (t_finish - slot.t_arrive) * 1e3)
            # TPOT (serving/tpot_ms) is recorded per emission burst in
            # _observe_tpot — per-token interpolation that stays honest
            # when a decode window or an accepted draft emits several
            # tokens in one sync — not as a per-request mean here
            timing = {"arrival": slot.t_arrive, "admit": slot.t_admit,
                      "first_token": slot.t_first, "finish": t_finish}
        if self.tracer.enabled and slot.trace is not None:
            t_end = self._clock()
            self.tracer.event(slot.trace, "retire", t_end,
                              tid=self.trace_tid,
                              attrs={"reason": reason,
                                     "tokens": len(slot.emitted)})
            if slot.trace.owner == "engine":
                # no router above: this engine closes the root (e2e) span
                self.tracer.finish(slot.trace, t_end, tid=self.trace_tid,
                                   attrs={"reason": reason})
        if self.flightrec.enabled:
            self.flightrec.record("retire", uid=slot.uid, reason=reason,
                                  tokens=len(slot.emitted),
                                  freed_blocks=len(slot.blocks))
        done = CompletedRequest(uid=slot.uid, prompt_len=slot.prompt_len,
                                tokens=np.asarray(slot.emitted, np.int32),
                                finish_reason=reason,
                                cached_prefix_tokens=slot.cached
                                * self.block_size,
                                timing=timing)
        slot.reset()
        return done

    def _emit(self, slot: _Slot, tok: int, finished: List[CompletedRequest]):
        slot.emitted.append(int(tok))
        self.tokens_generated += 1
        if self.telemetry.enabled and len(slot.emitted) == 1 \
                and slot.t_arrive is not None:
            slot.t_first = slot.t_prev = self._clock()
            self.telemetry.observe("serving/ttft_ms",
                                   (slot.t_first - slot.t_arrive) * 1e3)
        if slot.eos is not None and int(tok) == slot.eos:
            finished.append(self._retire(slot, "eos"))
        elif len(slot.emitted) >= slot.max_new:
            finished.append(self._retire(slot, "length"))

    def _observe_tpot(self, slot, anchor, j):
        """Per-token TPOT with intra-burst interpolation: a decode sync
        that emits `j` tokens for a slot since `anchor` (the previous
        emission sync) interpolates the j timestamps evenly across the
        interval — j samples of dt/j each — so `serving/tpot_ms` stays
        honest whether a step emits exactly one token, a K-token decode
        window, or 1..k+1 tokens from a verify step's accepted draft. (A
        single per-request mean would hide the burst cadence; dividing
        wall time by steps instead of tokens would overstate it.)"""
        if not self.telemetry.enabled or anchor is None or j <= 0:
            return
        t_now = self._clock()
        per_tok = (t_now - anchor) / j * 1e3
        for _ in range(j):
            self.telemetry.observe("serving/tpot_ms", per_tok)
        if slot.state != _FREE:            # retired slots were reset already
            slot.t_prev = t_now

    # ------------------------------------------------------------------
    # cancellation + queue extraction (router TTL / failover build on these)
    # ------------------------------------------------------------------

    def _expire_queued(self, uid, prompt_len) -> CompletedRequest:
        """Complete a queued request whose hard deadline passed before it
        ever touched a slot."""
        self.deadline_cancelled += 1
        if self.telemetry.enabled:
            self.telemetry.inc("serving/deadline_cancelled")
        if self.flightrec.enabled:
            self.flightrec.record("deadline", uid=uid, queued=True)
        return CompletedRequest(uid=uid, prompt_len=prompt_len,
                                tokens=np.zeros((0,), np.int32),
                                finish_reason="deadline")

    def _sweep_deadlines(self, finished: List[CompletedRequest]):
        """Hard-deadline enforcement at the scheduler sync point: an active
        slot (generating OR parked for handoff) past its budget retires
        with reason "deadline" — blocks freed the same call — and queued
        requests past theirs complete without ever occupying a slot. Gated
        by `_deadlines`, so traffic without deadlines never pays the scan."""
        if not self._deadlines:
            return
        now = self._clock()
        for slot in self.slots:
            if slot.state != _FREE and slot.deadline is not None \
                    and now >= slot.deadline:
                self.deadline_cancelled += 1
                if self.telemetry.enabled:
                    self.telemetry.inc("serving/deadline_cancelled")
                if self.flightrec.enabled:
                    self.flightrec.record("deadline", uid=slot.uid,
                                          tokens=len(slot.emitted))
                finished.append(self._retire(slot, "deadline"))
        if any(rec[9] is not None for rec in self.queue):
            keep = collections.deque()
            for rec in self.queue:
                if rec[9] is not None and now >= rec[9]:
                    finished.append(self._expire_queued(rec[0].uid, rec[2]))
                else:
                    keep.append(rec)
            self.queue = keep

    def cancel(self, uid, queued_only: bool = False,
               reason: str = "cancelled") -> Optional[CompletedRequest]:
        """Withdraw a request wherever it lives. A queued request is removed
        before it ever touches a slot; an active one retires immediately —
        its blocks freed/decref'd the same call, exactly like an EOS
        retirement. Returns a `CompletedRequest` with
        ``finish_reason=reason`` (whatever tokens were already emitted are
        kept), or None when `uid` is unknown — or not cancellable under
        `queued_only=True`, the router-TTL mode that must never kill a
        request already generating. A slot PARKED in the handoff state is
        "not generating" for that purpose and IS cancelled under
        `queued_only` — it holds exported blocks on the source pool while
        waiting for a decode replica, and skipping it would leak them for
        as long as the handoff stays deferred."""
        for i, rec in enumerate(self.queue):
            if rec[0].uid == uid:
                del self.queue[i]
                self.cancelled += 1
                if self.flightrec.enabled:
                    self.flightrec.record("cancel", uid=uid, queued=True,
                                          reason=reason)
                return CompletedRequest(uid=uid, prompt_len=rec[2],
                                        tokens=np.zeros((0,), np.int32),
                                        finish_reason=reason)
        for slot in self.slots:
            if slot.state == _FREE or slot.uid != uid:
                continue
            if queued_only and slot.state != _HANDOFF:
                return None
            self.cancelled += 1
            return self._retire(slot, reason)
        return None

    def drain_queued(self) -> List[Request]:
        """Extract every queued-but-unstarted request, emptying the queue —
        the router's failover path: a quarantined replica's waiting requests
        are re-submitted elsewhere verbatim (they never touched this
        engine's pool, so nothing needs freeing)."""
        out = [rec[0] for rec in self.queue]
        self.queue.clear()
        return out

    def active_uids(self) -> List[Any]:
        """Uids currently occupying slots (prefilling, decoding, or parked
        for handoff) — in-flight work that dies with the engine."""
        return [s.uid for s in self.slots if s.state != _FREE]

    def has_output(self, uid) -> bool:
        """True once the request has emitted its first token here — the
        router's hedging probe: a dispatched request with no output past
        `hedge_after_ms` earns a speculative duplicate elsewhere."""
        for s in self.slots:
            if s.state != _FREE and s.uid == uid:
                return len(s.emitted) > 0
        return False

    def shed_queued_below_priority(self, min_priority: int
                                   ) -> List[CompletedRequest]:
        """Degradation-ladder top rung: complete (reason "cancelled") every
        QUEUED request whose priority is strictly below `min_priority`.
        Active slots are never shed — their compute is already sunk."""
        out: List[CompletedRequest] = []
        keep = collections.deque()
        for rec in self.queue:
            req = rec[0]
            if int(getattr(req, "priority", 0)) < min_priority:
                self.cancelled += 1
                self.degradation_sheds += 1
                if self.telemetry.enabled:
                    self.telemetry.inc("serving/degradation_sheds")
                if self.flightrec.enabled:
                    self.flightrec.record("degrade_shed", uid=req.uid,
                                          priority=int(req.priority))
                out.append(CompletedRequest(uid=req.uid, prompt_len=rec[2],
                                            tokens=np.zeros((0,), np.int32),
                                            finish_reason="cancelled"))
            else:
                keep.append(rec)
        self.queue = keep
        return out

    # ------------------------------------------------------------------
    # pool invariant auditing (inference/audit.py)
    # ------------------------------------------------------------------

    def audit_state(self) -> Dict[str, Any]:
        """Portable JSON snapshot of the pool bookkeeping — what
        `bin/dstpu_audit` consumes, and what a flight dump embeds."""
        return self._auditor.snapshot()

    def audit(self, repair: bool = False):
        """Run the pool invariant auditor now. On violations: dump the
        flight recorder (ring + report + portable state snapshot), then —
        with `repair=True` — rebuild the free list/refcounts/reclaimable
        LRU from the slot tables (ground truth) and re-audit; a repair
        that cannot reach a clean state raises `PoolCorruptionError`.
        Returns the (pre-repair) `AuditReport`."""
        report = self._auditor.audit()
        self.audits_run += 1
        if report.ok:
            return report
        self.audit_violations_total += len(report.violations)
        if self.telemetry.enabled:
            self.telemetry.inc("serving/audit_violations",
                               len(report.violations))
        if self.flightrec.enabled:
            self.flightrec.record("audit_violation",
                                  violations=len(report.violations),
                                  by_kind=report.by_kind())
            try:
                stats = self.stats()
            except Exception as e:                    # a corrupt pool may
                stats = {"error": str(e)}             # break stats() itself
            self.flightrec.dump(
                f"pool audit failed: {report.summary()}",
                state={"audit": report.to_dict(),
                       "audit_state": self._auditor.snapshot(),
                       "stats": stats})
        if repair:
            summary = self._auditor.repair()
            self.audit_repairs += 1
            if self.telemetry.enabled:
                self.telemetry.inc("serving/audit_repairs")
            if self.flightrec.enabled:
                self.flightrec.record("audit_repair", **{
                    k: summary[k] for k in ("violations_before",
                                            "violations_after", "clean")})
            log_dist(f"serving audit: repaired {report.summary()} -> "
                     f"{'clean' if summary['clean'] else 'STILL DIRTY'}",
                     ranks=[0])
            if not summary["clean"]:
                raise PoolCorruptionError(report)
        return report

    def _scheduled_audit(self):
        """The every-N-syncs audit: repair in place or raise so the router
        quarantines this replica, per `serving.audit_action`."""
        report = self.audit(repair=(self.audit_action == "repair"))
        if not report.ok and self.audit_action == "raise":
            raise PoolCorruptionError(report)

    def close(self):
        """Engine shutdown: one final invariant audit (always — leaked
        blocks at teardown are the cheapest possible time to catch) plus a
        telemetry flush. Returns the final `AuditReport`."""
        report = self.audit(repair=(self.audit_action == "repair"))
        self.telemetry.close()
        return report

    # ------------------------------------------------------------------
    # router surface: affinity scoring + load signals
    # ------------------------------------------------------------------

    def hash_chain(self, prompt) -> Optional[List[bytes]]:
        """The prompt's chained block hashes (None when caching is off) —
        computed once by the router and matched against every replica."""
        if self.prefix_cache is None:
            return None
        return self.prefix_cache.hash_chain(
            np.asarray(prompt, np.int32).reshape(-1))

    def prefix_affinity(self, hashes) -> int:
        """Longest registered prefix (in blocks) this engine already holds
        for a prompt's hash chain — the router's affinity score. Read-only:
        no refcounts move, no LRU entry is touched. 0 when caching is off."""
        if self.prefix_cache is None or not hashes:
            return 0
        return self.prefix_cache.match_len(hashes)

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def has_free_slot(self) -> bool:
        return any(s.state == _FREE for s in self.slots)

    # ------------------------------------------------------------------
    # disaggregated prefill/decode: block handoff between engines
    # ------------------------------------------------------------------

    def handoff_ready(self) -> List[Any]:
        """Uids of prefill-only slots whose prefill finished: their blocks
        hold the full prompt KV and their first sampled token is emitted —
        ready for `export_handoff` into a decode engine."""
        return [s.uid for s in self.slots if s.state == _HANDOFF]

    def export_handoff(self, uid) -> Dict[str, Any]:
        """Snapshot a handoff-parked slot for transplant. The blocks stay
        OWNED by this engine (refcounts untouched) until `release_handoff`
        — the copy must complete before the source can be reclaimed, the
        same protocol as the checkpoint saver's tmp->rename commit."""
        slot = self._handoff_slot(uid)
        # blocks the prefill cursor actually wrote: the padded prompt only
        # (a prefill-only slot never decodes here, so no window tail)
        n_used = (slot.padded_len - 1) // self.block_size + 1
        return {"uid": slot.uid, "prompt": slot.prompt,
                "prompt_len": slot.prompt_len, "padded_len": slot.padded_len,
                "max_new": slot.max_new, "eos": slot.eos,
                "emitted": list(slot.emitted), "pos": slot.pos,
                "blocks": list(slot.blocks[:n_used]),
                "cached": slot.cached, "t_arrive": slot.t_arrive,
                "t_admit": slot.t_admit, "t_first": slot.t_first,
                "trace": slot.trace}

    def adopt_handoff(self, state: Dict[str, Any], src_pool) -> bool:
        """Adopt a prefilled slot exported by another engine: allocate the
        full-lifetime blocks here, gather the prompt's KV blocks out of
        `src_pool` into them (`transplant_blocks` — a block-indexed copy,
        axis 1 of the pool layout), and seed a _DECODE slot that continues
        from the first sampled token. Returns False when this engine has no
        free slot or blocks RIGHT NOW (the router retries later — source
        blocks are still held); raises `InadmissibleRequestError` when the
        request can never fit here."""
        need = blocks_needed(state["prompt_len"], state["padded_len"],
                             state["max_new"], self.block_size,
                             window=self.window, spec_k=self.draft_k)
        if max_written_pos(state["prompt_len"], state["padded_len"],
                           state["max_new"], self.window,
                           self.draft_k) >= self.max_context:
            raise InadmissibleRequestError(
                f"request {state['uid']}: handoff target max_context "
                f"{self.max_context} too small (prompt {state['prompt_len']}"
                f" + max_new {state['max_new']}, window {self.window})")
        if need > self.allocator.capacity:
            raise InadmissibleRequestError(
                f"request {state['uid']}: handoff needs {need} KV blocks, "
                f"decode pool has {self.allocator.capacity}")
        free = [s for s in self.slots if s.state == _FREE]
        if not free:
            return False
        blocks = self.allocator.alloc(need)
        if blocks is None:
            return False
        n_src = len(state["blocks"])
        try:
            self.pool = transplant_blocks(src_pool, state["blocks"],
                                          self.pool, blocks[:n_src],
                                          pad_to=self.nb)
        except Exception:
            self.allocator.free(blocks)    # don't leak the reservation
            raise
        slot = free[-1]
        slot.state = _DECODE
        slot.uid = state["uid"]
        slot.prompt = state["prompt"]
        slot.prompt_len = state["prompt_len"]
        slot.padded_len = state["padded_len"]
        slot.max_new = state["max_new"]
        slot.eos = state["eos"]
        slot.blocks = blocks
        slot.cursor = state["padded_len"]
        slot.pos = state["pos"]
        slot.emitted = list(state["emitted"])
        slot.hashes = None          # adopted blocks stay private: this pool
        slot.reg = 0                # never registers them (the prefill
        slot.cached = state["cached"]  # replica's cache owns the prefix)
        # carry the PREFILL replica's stamps: TTFT/TPOT must measure from
        # the real first token, not from adoption time (a parked slot would
        # otherwise report an inflated, decode-attributed TTFT)
        slot.t_arrive = state["t_arrive"]
        slot.t_admit = state.get("t_admit")
        slot.t_first = state.get("t_first")
        slot.t_prev = slot.t_first         # TPOT interpolation re-anchors here
        slot.trace = state.get("trace")    # decode spans continue the trace
        self.tables[slot.idx, :] = TRASH_BLOCK
        self.tables[slot.idx, :len(blocks)] = blocks
        self.handoffs_in += 1
        return True

    def release_handoff(self, uid):
        """Free the source side of a completed transplant: decref the
        slot's blocks (registered prefix blocks park reclaimable and stay
        matchable for affinity) and recycle the slot."""
        slot = self._handoff_slot(uid)
        self.allocator.free(slot.blocks[::-1])
        self.tables[slot.idx, :] = TRASH_BLOCK
        if self.drafter is not None:
            self.drafter.retire(slot)
        slot.reset()
        self.handoffs_out += 1

    def _handoff_slot(self, uid) -> _Slot:
        for s in self.slots:
            if s.state == _HANDOFF and s.uid == uid:
                return s
        raise KeyError(f"no handoff-ready slot for request {uid!r}")

    # ------------------------------------------------------------------
    # speculative decoding: draft -> one fixed-shape verify -> accept+rewind
    # ------------------------------------------------------------------

    def _verify_decode(self, dec, tok, pos, tables, finished):
        """Draft+verify replacing the decode step: the drafter proposes up
        to `draft_k` tokens per slot, ONE jitted verify call scores drafts
        for ALL slots (writing their k/v at pos..pos+k through the tables),
        and each slot emits its longest agreeing prefix plus the bonus
        token from the first disagreeing row — 1..k+1 tokens per model
        step. Rejection is the O(1) rollback the paged layout buys: the
        cursor advances only past accepted tokens, the rejected tokens'
        k/v sits beyond it (overwritten by the next verify's writes, never
        attended — the causal mask stops at the cursor), and the slot's
        blocks and table rows do not move."""
        tr_on = self.tracer.enabled
        with self.telemetry.span("serving/draft", tid=self.trace_tid):
            drafts, dlens = self.drafter.propose(dec, tok, pos, tables)
        if self.pressure is not None and self.pressure.draft_cap is not None:
            # ladder rung 1: cap the ACCEPTED draft length only — the
            # verify program keeps its compiled [S, k+1] shape, drafts past
            # the cap score as padding and land past the cursor (dead)
            dlens = np.minimum(dlens, self.pressure.draft_cap)
        toks = np.concatenate([tok[:, None], drafts], axis=1)
        t0 = self._clock() if tr_on else 0.0
        with self.telemetry.span("serving/verify", tid=self.trace_tid):
            tgt, self.pool = self._verify_step(self.engine.params, toks,
                                               pos, self.pool, tables,
                                               self._next_rng())
            # dstpu: ignore[DT001]: THE one host roundtrip per verify step — acceptance runs host-side, amortized over k+1 tokens x all slots
            tgt = np.asarray(jax.device_get(tgt))       # [S, draft_k+1]
        t1 = self._clock() if tr_on else 0.0
        self.verify_calls += 1
        self.decode_steps += 1
        for s in dec:
            dlen = int(dlens[s.idx])
            ctx, uid = s.trace, s.uid         # _retire resets the slot
            n, emitted = accept_greedy(drafts[s.idx], tgt[s.idx], dlen)
            # O(1) rollback/advance: the cursor moves past the accepted
            # prefix + bonus only; everything else written this step is
            # dead weight the next verify overwrites
            s.pos += n + 1
            self.verify_slot_steps += 1
            self.drafted_tokens += dlen
            self.accepted_tokens += n
            if self.telemetry.enabled:
                if dlen:
                    self.telemetry.observe("serving/spec_accept_rate",
                                           n / dlen)
                self.telemetry.inc("serving/spec_accepted_tokens", n)
                self.telemetry.inc("serving/spec_drafted_tokens", dlen)
            anchor, j = s.t_prev, 0
            for t in emitted:
                # EOS inside an accepted draft retires the slot right here,
                # at the EOS position — the accepted tail past it (and the
                # bonus) is discarded exactly like a window tail
                self._emit(s, t, finished)
                j += 1
                if s.state == _FREE:
                    break
            # j, not len(emitted): an EOS or max_new retirement mid-burst
            # truncates the accepted tail — only tokens that actually
            # reached the output count toward the tokens/step multiple
            self.spec_emitted_tokens += j
            self._observe_tpot(s, anchor, j)
            if tr_on and ctx is not None:
                self.tracer.record(ctx, "verify", t0, t1 - t0,
                                   tid=self.trace_tid,
                                   attrs={"drafted": dlen, "accepted": n,
                                          "emitted": j})
            if self.flightrec.enabled and n < dlen:
                # spec-decode rollback: the cursor rewound past dlen-n
                # rejected draft tokens — O(1), but worth the black box
                self.flightrec.record("rollback", uid=uid,
                                      rejected=dlen - n, accepted=n)
        if self.telemetry.enabled:
            self.telemetry.inc("serving/spec_verify_steps")

    # ------------------------------------------------------------------
    # the engine step: admit -> prefill chunk(s) -> decode all slots
    # ------------------------------------------------------------------

    def step(self) -> List[CompletedRequest]:
        """One scheduler iteration. Returns the requests that finished.

        The try/except is the OOM-forensics dispatch boundary: a
        RESOURCE_EXHAUSTED escaping the compiled calls dumps the memory
        ledger + planner delta + flight-recorder ring (memscope enabled)
        before re-raising — the error itself is never swallowed."""
        try:
            return self._step_impl()
        except Exception as e:
            if self.memscope is not None:
                self.memscope.on_step_error(e)
            raise

    def _step_impl(self) -> List[CompletedRequest]:
        finished: List[CompletedRequest] = []
        self.steps += 1
        params = self.engine.params

        with self.telemetry.span("serving/admit", tid=self.trace_tid):
            self._admit(finished)

        # chunked prefill, bounded per step so arriving prompts cannot stall
        # the running batch for more than prefill_budget chunk-times
        budget = self.prefill_budget
        for slot in self.slots:
            if budget <= 0:
                break
            while slot.state == _PREFILL and budget > 0:
                start = slot.cursor
                chunk = np.zeros((1, self.chunk), np.int32)
                seg = slot.prompt[start:start + self.chunk]
                chunk[0, :len(seg)] = seg
                final = start + self.chunk >= slot.padded_len
                last = (slot.prompt_len - 1 - start) if final else self.chunk - 1
                tr_on = self.tracer.enabled and slot.trace is not None
                t0 = self._clock() if tr_on else 0.0
                with self.telemetry.span("serving/prefill_chunk",
                                         tid=self.trace_tid):
                    tok, self.pool = self._prefill_step(
                        params, chunk, np.asarray([start], np.int32),
                        np.asarray([last], np.int32), self.pool,
                        self.tables[slot.idx][None], self._next_rng())
                if tr_on:
                    t1 = self._clock()
                    self.tracer.record(slot.trace, "prefill_chunk", t0,
                                       t1 - t0, tid=self.trace_tid,
                                       attrs={"start": start,
                                              "chunk": self.chunk})
                if self.drafter is not None:
                    # a stateful drafter (the draft model) shadows the chunk
                    # into its own pool through the same table — the draft
                    # cache is warm the moment this slot starts verifying
                    self.drafter.prefill_chunk(
                        slot, chunk, np.asarray([start], np.int32),
                        np.asarray([last], np.int32),
                        self.tables[slot.idx][None])
                slot.cursor = start + self.chunk
                budget -= 1
                self.prefill_chunks += 1
                if self.prefix_cache is not None and slot.hashes:
                    # register blocks the cursor just finished writing —
                    # full blocks strictly below prompt_len only (the
                    # padded tail and decode-written blocks stay private,
                    # so shared blocks are immutable by construction). A
                    # block becomes matchable only here, AFTER its content
                    # exists in the pool: registering at admission would
                    # let a same-step sibling map garbage.
                    hi = min(slot.cursor, slot.prompt_len) // self.block_size
                    for i in range(slot.reg, hi):
                        self.prefix_cache.register(slot.hashes[i],
                                                   slot.blocks[i])
                    slot.reg = max(slot.reg, hi)
                if final:
                    # a prefill-only slot parks for handoff instead of
                    # decoding; _emit may still retire it right here when
                    # the first sampled token is EOS or max_new == 1 — the
                    # router then sees a normal completion from this engine
                    slot.state = _HANDOFF if slot.prefill_only else _DECODE
                    # dstpu: ignore[DT001]: first-token readback at prefill completion — one scalar per prompt, the TTFT emission point
                    self._emit(slot, int(np.asarray(tok)[0]), finished)

        # decode: ONE fixed-shape call for every slot; non-decoding slots
        # ride along against the trash block. With window > 1 the call
        # emits a whole window per slot; a slot finishing mid-window
        # discards the tail (already written to its own blocks — the
        # blocks_needed window padding covers it). With spec decode on,
        # the verify step replaces this call entirely.
        dec = [s for s in self.slots if s.state == _DECODE]
        if dec:
            self.peak_active = max(self.peak_active, len(dec))
            tok = np.zeros((self.max_slots,), np.int32)
            pos = np.zeros((self.max_slots,), np.int32)
            tables = np.full_like(self.tables, TRASH_BLOCK)
            for s in dec:
                tok[s.idx] = s.emitted[-1]
                pos[s.idx] = s.pos
                tables[s.idx] = self.tables[s.idx]
            spec_active = self.spec_on and not (
                self.pressure is not None and self.pressure.spec_disabled)
            if spec_active:
                self._verify_decode(dec, tok, pos, tables, finished)
            else:
                # the degraded paths run the 1-STEP decode program: with
                # spec decode pressure-disabled the blocks were sized for
                # the k-draft overhang (no window-rounding tail, so a K-step
                # window could write past them), and the ladder's window-
                # shrink rung trades dispatch amortization for K-times finer
                # retirement/admission granularity under pool pressure
                use_w1 = self.spec_on or (
                    self.pressure is not None
                    and self.pressure.force_window_1)
                step_fn = self._degraded_decode_step() if use_w1 \
                    else self._decode_step
                win = 1 if use_w1 else self.window
                tr_on = self.tracer.enabled
                t0 = self._clock() if tr_on else 0.0
                with self.telemetry.span("serving/decode_window",
                                         tid=self.trace_tid):
                    nxt, self.pool = step_fn(params, tok, pos,
                                             self.pool, tables,
                                             self._next_rng())
                    # dstpu: ignore[DT001]: THE one host roundtrip per decode window — EOS/retirement decisions are host-side, amortized over `win` tokens
                    nxt = np.asarray(jax.device_get(nxt))   # [S, win]
                t1 = self._clock() if tr_on else 0.0
                self.decode_steps += 1
                for s in dec:
                    s.pos += win
                    ctx = s.trace             # _retire resets the slot
                    anchor, j = s.t_prev, 0
                    for t in nxt[s.idx]:
                        self._emit(s, int(t), finished)
                        j += 1
                        if s.state == _FREE:            # retired mid-window
                            break
                    self._observe_tpot(s, anchor, j)
                    if tr_on and ctx is not None:
                        self.tracer.record(ctx, "decode_window", t0, t1 - t0,
                                           tid=self.trace_tid,
                                           attrs={"emitted": j})

        # sync-point housekeeping: hard deadlines, the pressure ladder, and
        # the scheduled pool audit all run here — between compiled calls,
        # on host state only
        self._sweep_deadlines(finished)
        if self.pressure is not None:
            self.pressure.update(finished)
        if self.audit_interval and self.steps % self.audit_interval == 0:
            self._scheduled_audit()

        if self.telemetry.enabled:
            self.telemetry.set_gauge("serving/queue_depth", len(self.queue))
            self.telemetry.set_gauge("serving/active_slots", self.num_active)
            self.telemetry.set_gauge("serving/free_blocks",
                                     self.allocator.available)
            if self.memscope is not None:
                # mem/* ledger gauges; the first publish also runs the lazy
                # per-program memory_analysis pass (AOT — no jit-cache hit)
                self.memscope.publish()
            self.telemetry.maybe_export(self.steps)

        return finished

    # ------------------------------------------------------------------
    # batch front-end + introspection
    # ------------------------------------------------------------------

    @property
    def num_active(self):
        return sum(1 for s in self.slots if s.state != _FREE)

    def run(self, requests: Sequence[Request]) -> Dict[Any, CompletedRequest]:
        """Submit a batch of requests and drain the engine."""
        for r in requests:
            self.submit(r)
        out: Dict[Any, CompletedRequest] = {}
        while self.queue or self.num_active:
            before = (self.prefill_chunks, self.decode_steps, len(self.queue))
            for done in self.step():
                out[done.uid] = done
            after = (self.prefill_chunks, self.decode_steps, len(self.queue))
            if after == before:                     # defensive: cannot happen
                raise RuntimeError(
                    f"serving scheduler made no progress: queue="
                    f"{len(self.queue)} active={self.num_active} "
                    f"free_blocks={self.allocator.num_free}")
        # drained: flush the tail of the trace into the exporters (a run
        # shorter than export_interval would otherwise leave no files)
        if self.telemetry.enabled:
            self.telemetry.export(self.steps)
        return out

    def compile_stats(self) -> Dict[str, int]:
        """Compiled-program counts of the persistent step functions — the
        serving promise is that these stay at 1 each for the engine's
        lifetime, across any mix of request shapes (the verify and draft
        programs appear, and join the promise, when spec decode is on; the
        streamed mode's six per-phase programs replace the resident two,
        each still pinned at one)."""
        if self.streamed:
            return {name: int(fn._cache_size()) for name, fn in (
                ("embed_prefill", self._embed_prefill),
                ("layer_prefill", self._layer_prefill),
                ("head_prefill", self._head_prefill),
                ("embed_decode", self._embed_decode),
                ("layer_decode", self._layer_decode),
                ("head_decode", self._head_decode))}
        out = {"decode_step": int(self._decode_step._cache_size()),
               "prefill_step": int(self._prefill_step._cache_size())}
        if self.spec_on:
            out["verify_step"] = int(self._verify_step._cache_size())
            out.update(self.drafter.compile_stats())
        if self._decode_step_w1 is not None:
            # appears only once the degradation ladder (or the spec-decode
            # fallback) actually built it — absent means never engaged
            out["decode_step_w1"] = int(self._decode_step_w1._cache_size())
        return out

    def stats(self) -> Dict[str, Any]:
        out = {"steps": self.steps, "decode_steps": self.decode_steps,
               "prefill_chunks": self.prefill_chunks,
               "tokens_generated": self.tokens_generated,
               "peak_active": self.peak_active,
               "cancelled": self.cancelled,
               "deadline_cancelled": self.deadline_cancelled,
               "handoffs_in": self.handoffs_in,
               "handoffs_out": self.handoffs_out,
               "queued": len(self.queue), "active": self.num_active,
               "free_blocks": self.allocator.num_free,
               "reclaimable_blocks": self.allocator.num_reclaimable,
               "available_blocks": self.allocator.available,
               "compiles": self.compile_stats()}
        if self.spec_on:
            out["spec_decode"] = {
                "drafter": self.drafter.name,
                "draft_k": self.draft_k,
                "verify_steps": self.verify_calls,
                "drafted_tokens": self.drafted_tokens,
                "accepted_tokens": self.accepted_tokens,
                "emitted_tokens": self.spec_emitted_tokens,
                # accepted/proposed (the drafter's hit rate) and tokens
                # emitted per SEQUENCE per model step (the throughput
                # multiple: 1.0 = spec decode is pure overhead, draft_k+1
                # is the ceiling; the denominator is per-slot verify
                # participations, so batching doesn't inflate it)
                "acceptance_rate": (self.accepted_tokens /
                                    max(1, self.drafted_tokens)),
                "accepted_tokens_per_step": (self.spec_emitted_tokens /
                                             max(1, self.verify_slot_steps))}
        if self.kv_quant or self.weight_quant != "off":
            q = {"kv_cache_dtype": self.kv_cache_dtype,
                 "weights": self.weight_quant}
            if self.kv_quant:
                g = self.pool["k_scale"].shape[-1]
                q["kv_group_size"] = int(self.pool["k"].shape[-1] // g)
            if self.weight_quant_stats is not None:
                # the pytree-wide WOQ ratio (bytes_before/bytes_after incl.
                # scales) — the weight-memory saving actually realized
                q["weight_quant"] = dict(self.weight_quant_stats)
            out["quantization"] = q
        if self.audits_run:
            out["audit"] = {"runs": self.audits_run,
                            "violations": self.audit_violations_total,
                            "repairs": self.audit_repairs}
        if self.pressure is not None:
            out["degradation"] = self.pressure.stats()
        if self.prefix_cache is not None:
            out["prefix_cache"] = {
                "hit_blocks": self.prefix_hit_blocks,
                "hit_tokens": self.prefix_hit_tokens,
                "prefill_chunks_skipped": self.prefill_chunks_skipped,
                "cached_blocks": self.prefix_cache.num_cached,
                "evictions": self.allocator.evictions}
        if self.streamed:
            # staging-pool overlap counters (device-ward hits/stalls +
            # write-back accounting) — the streamed mode's "is the overlap
            # real" readout, available with telemetry off
            from deepspeed_tpu.telemetry.memscope import tree_bytes
            out["offload"] = {
                "staging": self.engine.streamer.stats(),
                "layer_bytes": self.engine.store.layer_bytes,
                "host_param_bytes": self.engine.store.host_bytes,
                # peak HBM of the streamed-layer staging window — distinct
                # from the always-resident (embed/norm/head) tree below
                "staged_peak_bytes": self.engine.peak_param_hbm_bytes,
                "resident_param_bytes": tree_bytes(self.engine.params)}
        if self.memscope is not None:
            out["memory"] = self.memscope.snapshot()
        if self.telemetry.enabled:
            out["latency"] = self.latency_snapshot()
            # compile watchdog: ONE warmup compile per program is the
            # contract; any recompile after that is named here (and in the
            # flight recorder, with the triggering shapes)
            out["watchdog"] = self.telemetry.watchdog.summary()
        return out

    def latency_snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-request latency histogram snapshots (ttft_ms / tpot_ms /
        queue_wait_ms / e2e_ms -> count/mean/p50/p90/p99/min/max). Empty
        when telemetry is disabled."""
        if not self.telemetry.enabled:
            return {}
        snap = self.telemetry.registry.snapshot()
        return {name.split("/", 1)[1]: m for name, m in snap.items()
                if m.get("type") == "histogram" and name.startswith("serving/")}

    def write_monitor_events(self, monitor):
        """Serving cache/pool observability through the experiment monitor
        (same guarded best-effort contract as the PR 2 recovery events):
        Serving/prefix_hit_tokens, Serving/prefix_evictions,
        Serving/pool_free_blocks, stepped by the scheduler iteration."""
        from deepspeed_tpu.monitor.monitor import write_serving_events
        write_serving_events(monitor, [
            ("Serving/prefix_hit_tokens", self.prefix_hit_tokens, self.steps),
            ("Serving/prefix_evictions", self.allocator.evictions, self.steps),
            ("Serving/pool_free_blocks", self.allocator.available, self.steps),
        ])
