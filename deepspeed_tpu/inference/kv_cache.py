"""Paged KV-cache pool — the serving engine's memory system.

vLLM's PagedAttention insight mapped onto the existing blocked cache layout
(`TpuInferenceConfig.kv_block_size`): instead of one contiguous
[B, Hkv, M, hd] slab per generate() call, the engine owns a SINGLE pool of
physical [block, hd] KV blocks allocated once at init —
``k/v: [L, num_blocks, Hkv, block, hd]`` — and each serving slot holds a
block TABLE mapping its logical blocks to physical pool blocks. The decode
kernel (`ops/pallas/decode_attention.paged_decode_attention`) walks a row's
logical blocks and resolves them through the scalar-prefetched table, so:

  * no per-request cache allocation, ever — admission is a free-list pop;
  * a sequence's memory is freed the step it emits EOS (continuous batching
    can admit a queued request into the freed blocks immediately);
  * fragmentation is bounded to < one block per sequence.

Block 0 is RESERVED as the trash block: inactive slots point every table
entry at it, so the fixed-shape decode step can run over all slots — the
writes of dead slots land in the trash block and their reads produce garbage
the scheduler never looks at. This is what keeps the decode program's shape
(and therefore its compile) constant for the lifetime of the engine.

The allocator is deliberately host-side and stdlib-only: block alloc/free
happens at request admission/retirement (a few times per second), not in the
per-token hot loop, which stays a single fixed-shape jitted call.
"""

from typing import List, Optional

import jax.numpy as jnp

TRASH_BLOCK = 0  # physical block 0: write sink for inactive slots


class BlockAllocator:
    """Free-list over the physical blocks of a paged KV pool.

    Block 0 (TRASH_BLOCK) is never handed out. alloc() is all-or-nothing:
    a request either gets every block it needs or stays queued — partial
    allocation would deadlock two half-admitted requests against each other.
    """

    def __init__(self, num_blocks: int):
        assert num_blocks >= 2, "pool needs >= 1 usable block past the trash block"
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() yields low ids first

    @property
    def capacity(self) -> int:
        """Usable blocks (the trash block is not allocatable)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop n blocks, or None (and no state change) if fewer are free."""
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        return got

    def free(self, blocks: List[int]):
        for b in blocks:
            assert b != TRASH_BLOCK, "freeing the trash block"
            assert b not in self._free, f"double free of block {b}"
            self._free.append(b)


def max_written_pos(prompt_len: int, padded_prompt: int, max_new: int,
                    window: int = 1) -> int:
    """Highest cache position a request ever WRITES — the single source of
    truth for pool sizing (blocks_needed) AND admission validation (the
    scheduler's table-width check); two copies of this math drifting apart
    would let a request scribble past its allocated blocks.

    Chunked prefill writes the padded prompt's tail (masked garbage,
    overwritten by decode as it advances), and decode writes token i's k/v
    at prompt_len + i for i in [0, max_new-1) — the final sampled token is
    emitted without a decode step, so it never lands in the cache. With a
    decode window (`decode_steps_per_sync` > 1) the device runs whole
    windows blindly, so the max_new-1 decode writes round UP to a window
    multiple (the tail of the last window is garbage the scheduler
    discards — but it was written).
    """
    decode_writes = max_new - 1
    if window > 1 and decode_writes > 0:
        decode_writes = -(-decode_writes // window) * window
    return max(padded_prompt - 1, prompt_len - 1 + decode_writes)


def blocks_needed(prompt_len: int, padded_prompt: int, max_new: int,
                  block_size: int, window: int = 1) -> int:
    """Physical blocks a request occupies for its whole lifetime (see
    max_written_pos for the write-extent reasoning)."""
    return max_written_pos(prompt_len, padded_prompt, max_new,
                           window) // block_size + 1


def gather_block_kv(pool_k_l, pool_v_l, block_tables):
    """Materialize each row's logical KV as contiguous [B, Hkv, nb*block, hd].

    The XLA fallback path for paged attention (short contexts / CPU harness /
    alibi + sliding-window archs): one gather per layer per step. The Pallas
    kernel exists precisely to NOT pay this — it resolves the table inside
    the block index map — but the gathered form keeps a dense oracle for
    numerics and covers every arch flag.

    pool_[kv]_l: [N, Hkv, block, hd] (one layer's pool); block_tables: [B, nb].
    """
    B, nb = block_tables.shape
    N, Hkv, bm, hd = pool_k_l.shape
    k = jnp.moveaxis(pool_k_l[block_tables], 2, 1).reshape(B, Hkv, nb * bm, hd)
    v = jnp.moveaxis(pool_v_l[block_tables], 2, 1).reshape(B, Hkv, nb * bm, hd)
    return k, v
