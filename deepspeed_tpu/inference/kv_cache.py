"""Paged KV-cache pool — the serving engine's memory system.

vLLM's PagedAttention insight mapped onto the existing blocked cache layout
(`TpuInferenceConfig.kv_block_size`): instead of one contiguous
[B, Hkv, M, hd] slab per generate() call, the engine owns a SINGLE pool of
physical [block, hd] KV blocks allocated once at init —
``k/v: [L, num_blocks, Hkv, block, hd]`` — and each serving slot holds a
block TABLE mapping its logical blocks to physical pool blocks. The decode
kernel (`ops/pallas/decode_attention.paged_decode_attention`) walks a row's
logical blocks and resolves them through the scalar-prefetched table, so:

  * no per-request cache allocation, ever — admission is a free-list pop;
  * a sequence's memory is freed the step it emits EOS (continuous batching
    can admit a queued request into the freed blocks immediately);
  * fragmentation is bounded to < one block per sequence.

Block 0 is RESERVED as the trash block: inactive slots point every table
entry at it, so the fixed-shape decode step can run over all slots — the
writes of dead slots land in the trash block and their reads produce garbage
the scheduler never looks at. This is what keeps the decode program's shape
(and therefore its compile) constant for the lifetime of the engine.

The allocator is deliberately host-side and stdlib-only: block alloc/free
happens at request admission/retirement (a few times per second), not in the
per-token hot loop, which stays a single fixed-shape jitted call.

Prefix caching (`inference/prefix_cache.py`) layers on the allocator's
REFERENCE COUNTS: a physical block shared by several sequences (same prompt
prefix) is freed only when its last reader retires, and a refcount-0 block
whose content is still registered in the prefix cache parks on a
"reclaimable" LRU list instead of the free list — its KV stays resurrectable
for future hits, but `alloc()` treats it as available and evicts it (via the
`on_evict` hook, which unregisters the hash) the moment a fresh allocation
would otherwise fail. Caching therefore never reduces usable capacity.
"""

import collections
from typing import List, Optional

import jax
import jax.numpy as jnp

TRASH_BLOCK = 0  # physical block 0: write sink for inactive slots


class BlockAllocator:
    """Ref-counted free-list over the physical blocks of a paged KV pool.

    Block 0 (TRASH_BLOCK) is never handed out. alloc() is all-or-nothing:
    a request either gets every block it needs or stays queued — partial
    allocation would deadlock two half-admitted requests against each other.

    Every allocated block carries a refcount (1 at alloc). `incref` adds a
    reader (a prefix-cache hit mapping the block into another slot's table);
    `free` is a DECREF — the block returns to circulation only at zero. A
    zero-refcount block that `is_cached` claims (its content hash is still
    registered) moves to the reclaimable LRU instead of the free list; it is
    recycled lazily, oldest first, only when alloc() finds the free list
    short, calling `on_evict(block)` so the cache unregisters the hash
    before the block's KV can be overwritten.

    The free list is a list (deterministic pop order: low ids first) + a
    shadow set, so the double-free guard is O(1) per freed block instead of
    an O(n) list scan.
    """

    def __init__(self, num_blocks: int, policy: str = "lru"):
        assert num_blocks >= 2, "pool needs >= 1 usable block past the trash block"
        assert policy in ("lru", "none"), \
            f"unknown reclaim policy {policy!r} (expected 'lru' or 'none')"
        self.num_blocks = num_blocks
        self.policy = policy
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() yields low ids first
        self._free_set = set(self._free)
        self._refs = {}                     # block -> refcount (0 = reclaimable)
        self._reclaimable = collections.OrderedDict()  # LRU: oldest first
        self.is_cached = None               # hook: block -> bool (prefix cache)
        self.on_evict = None                # hook: block evicted -> unregister
        self.evictions = 0

    @property
    def capacity(self) -> int:
        """Usable blocks (the trash block is not allocatable)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_reclaimable(self) -> int:
        return len(self._reclaimable)

    @property
    def available(self) -> int:
        """Blocks an alloc() can actually obtain: free + reclaimable. This,
        not num_free, is the admission-backpressure quantity — cached
        refcount-0 blocks are usable capacity, merely lazily recycled."""
        return len(self._free) + len(self._reclaimable)

    def refcount(self, b: int) -> int:
        return self._refs.get(b, 0)

    def _push_free(self, b: int):
        self._free.append(b)
        self._free_set.add(b)

    def _evict_one(self):
        """Recycle the least-recently-parked reclaimable block: unregister
        its cached content (on_evict) and hand it to the free list."""
        b, _ = self._reclaimable.popitem(last=False)
        del self._refs[b]
        self.evictions += 1
        if self.on_evict is not None:
            self.on_evict(b)
        self._push_free(b)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop n blocks, or None (and no state change) if fewer are
        available. Reclaimable cached blocks are evicted LRU-first, but only
        as many as the free list is short — eviction never runs ahead of
        demand."""
        if n > self.available:
            return None
        while len(self._free) < n:
            self._evict_one()
        got = []
        for _ in range(n):
            b = self._free.pop()
            self._free_set.discard(b)
            self._refs[b] = 1
            got.append(b)
        return got

    def incref(self, b: int) -> int:
        """Add a reader to an allocated or reclaimable block (prefix-cache
        hit). A reclaimable block is resurrected: it leaves the LRU and its
        KV content becomes live again without a copy."""
        assert b != TRASH_BLOCK, "incref of the trash block"
        assert b in self._refs and b not in self._free_set, \
            f"incref of unallocated block {b}"
        self._refs[b] += 1
        if b in self._reclaimable:
            del self._reclaimable[b]
        return self._refs[b]

    def flush_reclaimable(self, keep: int = 0) -> int:
        """Demand-independent reclaim (the degradation ladder's "aggressive
        prefix-cache reclaim" rung): evict parked refcount-0 cached blocks
        NOW — oldest first, down to `keep` survivors — instead of lazily at
        the next failing alloc. Trades future prefix-cache hits for
        immediately-free blocks under pool pressure. Returns the number of
        blocks evicted."""
        n = 0
        while len(self._reclaimable) > max(0, int(keep)):
            self._evict_one()
            n += 1
        return n

    def free(self, blocks: List[int]):
        """Decref each block. At zero: cached blocks (per `is_cached`) park
        on the reclaimable LRU (policy 'lru'); everything else — and all
        blocks under policy 'none' — returns to the free list, cached
        content unregistered on the spot."""
        for b in blocks:
            assert b != TRASH_BLOCK, "freeing the trash block"
            assert b not in self._free_set, f"double free of block {b}"
            assert self._refs.get(b, 0) > 0, f"free of unallocated block {b}"
            self._refs[b] -= 1
            if self._refs[b] > 0:
                continue
            cached = self.is_cached is not None and self.is_cached(b)
            if cached and self.policy == "lru":
                self._reclaimable[b] = None     # most-recently-parked end
            else:
                # policy "none" unregisters on the spot but does NOT count
                # as an eviction: `evictions` means demand-driven reclaim
                # (pool pressure), not routine retirement
                if cached and self.on_evict is not None:
                    self.on_evict(b)
                del self._refs[b]
                self._push_free(b)


def max_written_pos(prompt_len: int, padded_prompt: int, max_new: int,
                    window: int = 1, spec_k: int = 0) -> int:
    """Highest cache position a request ever WRITES — the single source of
    truth for pool sizing (blocks_needed) AND admission validation (the
    scheduler's table-width check); two copies of this math drifting apart
    would let a request scribble past its allocated blocks.

    Chunked prefill writes the padded prompt's tail (masked garbage,
    overwritten by decode as it advances), and decode writes token i's k/v
    at prompt_len + i for i in [0, max_new-1) — the final sampled token is
    emitted without a decode step, so it never lands in the cache. With a
    decode window (`decode_steps_per_sync` > 1) the device runs whole
    windows blindly, so the max_new-1 decode writes round UP to a window
    multiple (the tail of the last window is garbage the scheduler
    discards — but it was written).

    Speculative decoding (`spec_k` > 0 draft tokens per verify step —
    replaces the decode window): every verify call writes the k/v of its
    input token AND all k drafts, positions pos..pos+k, and a slot still
    verifies while one token short of its budget, so the write extent grows
    by the k-token draft overhang past the last real decode write. A max_new=1
    request never verifies (its only token comes from prefill logits), so
    the overhang only applies when there are decode writes at all.
    """
    decode_writes = max_new - 1
    if spec_k > 0 and decode_writes > 0:
        decode_writes += spec_k
    elif window > 1 and decode_writes > 0:
        decode_writes = -(-decode_writes // window) * window
    return max(padded_prompt - 1, prompt_len - 1 + decode_writes)


def blocks_needed(prompt_len: int, padded_prompt: int, max_new: int,
                  block_size: int, window: int = 1, spec_k: int = 0) -> int:
    """Physical blocks a request occupies for its whole lifetime (see
    max_written_pos for the write-extent reasoning)."""
    return max_written_pos(prompt_len, padded_prompt, max_new,
                           window, spec_k) // block_size + 1


def _transplant_jit(src_pool, src_idx, dst_pool, dst_idx):
    def copy_leaf(dst_leaf, src_leaf):
        return dst_leaf.at[:, dst_idx].set(
            jnp.take(src_leaf, src_idx, axis=1))
    return jax.tree_util.tree_map(copy_leaf, dst_pool, src_pool)


# destination donated: XLA aliases the scatter in place instead of copying
# the whole (potentially multi-GB) pool per handoff; the caller re-binds
# `engine.pool` to the result, exactly like the serving step programs
_transplant_jit = jax.jit(_transplant_jit, donate_argnums=(2,))


def transplant_blocks(src_pool, src_blocks, dst_pool, dst_blocks,
                      pad_to: Optional[int] = None):
    """Copy physical KV blocks across two pools — the prefill->decode
    handoff primitive (`deepspeed_tpu/serving/`): a slot prefilled on one
    engine replica moves into another replica's pool by copying just its
    blocks and rebuilding the block table there.

    The paged layout makes this a block-indexed gather: every pool leaf is
    ``[L, num_blocks, ...]`` (axis 1 is the physical-block axis — the
    `init_paged_kv_pool` contract), so the copy is one `take` along axis 1
    per leaf scattered into the destination's block slots, jitted with the
    destination DONATED so the update aliases in place. `pad_to` pins the
    index width (pad entries copy trash->trash, whose content is garbage
    by contract): pass the destination's table width so every handoff
    shares ONE compiled copy program instead of one per block count.

    Returns the updated destination pool (the caller re-binds
    `engine.pool`; the old buffer is donated/dead). Both pools must share
    leaf structure, block size, and dtype; the trash block is never a
    legal source or destination for REAL entries.
    """
    assert len(src_blocks) == len(dst_blocks), \
        f"transplant width mismatch: {len(src_blocks)} vs {len(dst_blocks)}"
    assert TRASH_BLOCK not in src_blocks and TRASH_BLOCK not in dst_blocks, \
        "transplant of the trash block"
    for d, s in zip(jax.tree_util.tree_leaves(dst_pool),
                    jax.tree_util.tree_leaves(src_pool)):
        if d.dtype != s.dtype:
            raise ValueError(f"pool dtype mismatch: {d.dtype} vs {s.dtype}")
    if not src_blocks:
        return dst_pool
    src_blocks, dst_blocks = list(src_blocks), list(dst_blocks)
    if pad_to is not None and pad_to > len(src_blocks):
        pad = pad_to - len(src_blocks)
        src_blocks += [TRASH_BLOCK] * pad
        dst_blocks += [TRASH_BLOCK] * pad
    return _transplant_jit(src_pool, jnp.asarray(src_blocks, jnp.int32),
                           dst_pool, jnp.asarray(dst_blocks, jnp.int32))


def gather_block_kv(pool_k_l, pool_v_l, block_tables):
    """Materialize each row's logical KV as contiguous [B, Hkv, nb*block, hd].

    The XLA fallback path for paged attention (short contexts / CPU harness /
    alibi + sliding-window archs): one gather per layer per step. The Pallas
    kernel exists precisely to NOT pay this — it resolves the table inside
    the block index map — but the gathered form keeps a dense oracle for
    numerics and covers every arch flag.

    pool_[kv]_l: [N, Hkv, block, hd] (one layer's pool); block_tables: [B, nb].
    """
    B, nb = block_tables.shape
    N, Hkv, bm, hd = pool_k_l.shape
    k = jnp.moveaxis(pool_k_l[block_tables], 2, 1).reshape(B, Hkv, nb * bm, hd)
    v = jnp.moveaxis(pool_v_l[block_tables], 2, 1).reshape(B, Hkv, nb * bm, hd)
    return k, v


def gather_block_kv_dequant(pool_l, block_tables, dtype):
    """Dequantizing gather for an INT8 paged pool layer — the quantized
    path's XLA fallback AND the quantized kernel's parity oracle, in one
    definition (the same role `gather_block_kv` plays for the fp pool).

    `pool_l` is one layer's quantized pool slice: ``k``/``v`` int8
    [N, Hkv, block, hd] plus ``k_scale``/``v_scale`` f32
    [N, Hkv, block, hd//g] (the `init_paged_kv_pool` int8 layout — scales
    ride the SAME physical-block axis as the payload, which is what lets
    `transplant_blocks` move a block's scales with its bytes for free).
    Gathers payload and scales through the table with the ordinary block
    gather, then dequantizes via `quantization.dequantize_kv` — int8 × f32
    scale, narrowed to `dtype` last, exactly the in-kernel ordering."""
    from deepspeed_tpu.inference.quantization import dequantize_kv
    k, v = gather_block_kv(pool_l["k"], pool_l["v"], block_tables)
    ks, vs = gather_block_kv(pool_l["k_scale"], pool_l["v_scale"],
                             block_tables)
    return dequantize_kv(k, ks, dtype), dequantize_kv(v, vs, dtype)
