"""Speculative decoding on the paged KV pool — drafters + the verify math.

Decode is dispatch-latency- and HBM-bound at small batch: every model step
reads the whole weight set and the live KV prefix to emit ONE token per
slot. Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding") turns that step into k+1 tokens'
worth of work whose *acceptance* decides the payout: a cheap DRAFTER
proposes k tokens per slot, one fixed-shape jitted VERIFY call scores all
of them for every slot at once (the chunked-prefill machinery at positions
pos..pos+k — `_paged_attend` already builds causal masks from absolute
positions), and the scheduler accepts the longest agreeing prefix plus one
bonus token from the first disagreeing logit row. Greedy output is
token-identical to non-speculative serving by construction: a draft is
accepted only when it equals the target model's own (greedy) choice.

The paged layout is what makes rejection FREE: a rejected draft just
doesn't advance the slot's length cursor. Its k/v was written past the
cursor, later steps overwrite those positions, and the causal mask (k_pos
<= q_pos) guarantees nothing ever attends beyond the cursor — no cache
copy, no block free/realloc, block table untouched. That O(1) rollback is
the invariant tests/test_spec_decode.py pins.

Two drafters, one interface (`Drafter`):

  * `NgramDrafter` — model-free prompt lookup: match the newest generated
    tokens against the slot's OWN prompt+output history and propose the
    continuation. Zero extra device work; shines exactly on the
    cache-heavy, template/shared-prefix workloads the prefix cache serves
    (summarize/extract/multi-turn — output copies input).
  * `DraftModelDrafter` — a second, smaller `DecodeModelSpec` (the paged
    contract required) runs k greedy decode steps per verify inside one
    jitted lax.scan. Its pool mirrors the target's block geometry and is
    indexed by the SAME block tables, so slot lifecycle, prefix sharing
    and the cursor-rewind rollback all transfer verbatim; its prefill
    shadows the target's chunked prefill chunk for chunk.

Acceptance is greedy exact-match against the verify step's sampled row
(under greedy sampling, the argmax). For stochastic sampling the same
exact-match rule is the conservative "sample-and-match" scheme — the
emitted token at each position is always the target model's own sample, so
the output distribution is preserved; upgrading the acceptance test to
true rejection sampling (accept with prob p_target/p_draft) only needs the
verify step to return probabilities instead of samples, which is the one
documented extension point (`ServingEngine._build_verify_fn`).
"""

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Drafter:
    """Drafter interface the serving scheduler drives.

    `propose` is the only required method: given the active decode slots
    and the fixed-shape step arrays the scheduler already built (last
    emitted token, cursor position and block table per slot row), return
    `(drafts [max_slots, k] int32, lens [max_slots] int32)` — `lens[i]`
    counts the REAL proposals in row i (the rest is padding the verify
    step scores but acceptance ignores; proposing fewer than k costs
    nothing but the padded compute). `prefill_chunk` lets a stateful
    drafter shadow the target's chunked prefill; `retire` announces a
    slot recycle."""

    name = "none"

    def prefill_chunk(self, slot, chunk, start, last_idx, table):
        pass

    def propose(self, dec_slots, tok0, pos, tables
                ) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def retire(self, slot):
        pass

    def compile_stats(self):
        return {}


# ----------------------------------------------------------------------
# n-gram / prompt-lookup drafter
# ----------------------------------------------------------------------


def ngram_propose(history: np.ndarray, k: int, max_n: int = 4,
                  min_n: int = 1) -> np.ndarray:
    """Prompt-lookup proposal (Saxena's prompt-lookup decoding, the
    model-free n-gram drafter): find the MOST RECENT earlier occurrence of
    the history's trailing n-gram (longest n first) and propose the up-to-k
    tokens that followed it. Returns [<=k] int32 — empty when no n-gram of
    any tried length recurs.

    Host-side and allocation-light: one sliding-window equality per tried
    n over an int32 history that is at most max_context long."""
    L = int(history.shape[0])
    for n in range(min(max_n, L - 1), max(min_n, 1) - 1, -1):
        pat = history[L - n:]
        # windows[i] == history[i:i+n]; candidates exclude the pattern's
        # own position (i == L - n)
        windows = np.lib.stride_tricks.sliding_window_view(history, n)
        hits = np.nonzero((windows == pat).all(axis=1))[0]
        hits = hits[hits < L - n]
        if hits.size:
            # most recent occurrence wins — but prefer one with a FULL
            # k-token continuation: the hit nearest the end of history is
            # usually the freshest context, yet a hit whose continuation
            # runs off the end can propose almost nothing (on a cycling
            # history the latest hit is only `period` tokens from the
            # end — a structurally short draft every single step)
            full = hits[hits + n + k <= L]
            start = int(full[-1] if full.size else hits[-1]) + n
            cont = history[start:start + k]
            if cont.size:
                return cont.astype(np.int32)
    return np.zeros((0,), np.int32)


class NgramDrafter(Drafter):
    """Model-free drafter: each slot's own prompt+output history is the
    draft model. No device state, no extra compiles — `propose` is pure
    host work against arrays the scheduler already holds."""

    name = "ngram"

    def __init__(self, draft_k: int, max_n: int = 4, min_n: int = 1):
        self.k = int(draft_k)
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def propose(self, dec_slots, tok0, pos, tables):
        S = tok0.shape[0]
        drafts = np.zeros((S, self.k), np.int32)
        lens = np.zeros((S,), np.int32)
        for s in dec_slots:
            # history ends at the slot's last emitted token — the verify
            # input — so the proposal is its continuation
            hist = np.concatenate(
                [s.prompt, np.asarray(s.emitted, np.int32)])
            cont = ngram_propose(hist, self.k, self.max_n, self.min_n)
            drafts[s.idx, :cont.shape[0]] = cont
            lens[s.idx] = cont.shape[0]
        return drafts, lens


# ----------------------------------------------------------------------
# draft-model drafter
# ----------------------------------------------------------------------


def build_draft_program(decode_paged_fn, draft_k: int):
    """K-step greedy draft loop as ONE jitted program (the draft-model
    analog of the scheduler's decode window): feed each slot's last token,
    scan `draft_k` paged decode steps with argmax feedback, return the
    drafts [S, k] and the (donated) draft pool. Factored out of
    `DraftModelDrafter` so other draft-model consumers — the RLHF rollout
    in `runtime/hybrid_engine.py` is the natural one — can reuse the exact
    program instead of growing a second drafting loop."""

    def draft_steps(params, tok, pos, pool, tables):
        def body(carry, _):
            tok, pos, pool = carry
            logits, pool = decode_paged_fn(params, tok, pos, pool, tables)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, pos + 1, pool), nxt

        (_, _, pool), toks = jax.lax.scan(
            body, (tok, pos, pool), None, length=draft_k)
        return jnp.moveaxis(toks, 0, 1), pool

    return jax.jit(draft_steps, donate_argnums=(3,))


class DraftModelDrafter(Drafter):
    """Drafter driven by a second, smaller `DecodeModelSpec`.

    The draft model owns a paged pool with the TARGET's block geometry
    (same num_blocks, same block_size, its own layer/head shapes) indexed
    by the scheduler's own block tables — physical block b holds the
    target's KV for some token span in the target pool and the draft
    model's KV for the SAME span in the draft pool. Admission, retirement,
    prefix sharing and cursor-rewind rollback therefore need no drafter
    bookkeeping at all: the tables are the bookkeeping. Drafting runs k
    greedy decode steps for ALL slots in one jitted scan; prefill shadows
    the target's chunked prefill chunk-for-chunk (same [1, chunk] slices,
    same tables), so the draft cache is warm the moment a slot starts
    decoding. Cost per verify: k draft-model steps — size the draft model
    so that is small next to one target step.

    Caveat (documented, correctness-neutral): a slot ADOPTED via the
    disaggregated prefill/decode handoff transplants only the target
    pool's blocks, so the draft pool has no KV for its prompt — drafts for
    such a slot are garbage until enough accepted tokens rebuild context,
    and the verify step simply rejects them (output stays exact)."""

    name = "model"

    def __init__(self, serving, draft_spec, draft_k: int):
        from jax.sharding import NamedSharding, PartitionSpec
        from deepspeed_tpu.utils.tree import tree_cast

        missing = [n for n in ("decode_paged_fn", "prefill_paged_fn",
                               "init_paged_pool")
                   if getattr(draft_spec, n, None) is None]
        if missing:
            raise ValueError(
                f"draft model spec '{getattr(draft_spec, 'name', '?')}' has "
                f"no paged serving contract (missing {missing}); build it "
                f"with make_gpt_decode_model")
        self.spec = draft_spec
        self.k = int(draft_k)
        engine = serving.engine
        sharding = NamedSharding(engine.mesh, PartitionSpec())
        self.params = jax.device_put(
            tree_cast(draft_spec.params, engine.dtype), sharding)
        # mirror the target pool's placement story (scheduler __init__):
        # committed sharding up front so the first call of each program has
        # the same arg signature as every later call — no phantom compile.
        # The mirror takes the serving engine's EFFECTIVE kv dtype (the
        # quantization block may have picked int8 over the engine config),
        # so a quantized target gets an equally-quantized draft mirror —
        # the draft model's resident bytes halve along with the target's
        if serving.kv_quant:
            # same contract story as the scheduler's own pool build: a
            # legacy 3-arg draft init_paged_pool (or one that returns a
            # scale-less tree) gets the quantized-pool-contract pointer
            # instead of a bare arity/shape error
            try:
                pool = draft_spec.init_paged_pool(
                    serving.allocator.num_blocks, serving.block_size,
                    jnp.int8, serving.kv_group_size)
            except TypeError as e:
                raise ValueError(
                    f"draft model spec '{getattr(draft_spec, 'name', '?')}'"
                    f" init_paged_pool does not accept the 4-arg quantized "
                    f"form (num_blocks, block_size, dtype, kv_group_size) "
                    f"— it does not implement the quantized-pool contract "
                    f"(init_paged_kv_pool in models/gpt.py is the "
                    f"reference): {e}") from e
            if not (isinstance(pool, dict) and "k_scale" in pool):
                raise ValueError(
                    f"draft model spec '{getattr(draft_spec, 'name', '?')}'"
                    f" init_paged_pool returned no k_scale/v_scale leaves "
                    f"for dtype int8 — it does not implement the "
                    f"quantized-pool contract")
        else:
            pool = draft_spec.init_paged_pool(
                serving.allocator.num_blocks, serving.block_size,
                jnp.dtype(serving.kv_cache_dtype))
        self.pool = jax.device_put(pool, sharding)
        self._draft_steps = build_draft_program(draft_spec.decode_paged_fn,
                                                self.k)

        def prefill(params, toks, start, last_idx, pool, table):
            _, pool = draft_spec.prefill_paged_fn(params, toks, start,
                                                  last_idx, pool, table)
            return pool

        self._prefill = jax.jit(prefill, donate_argnums=(4,))

    def prefill_chunk(self, slot, chunk, start, last_idx, table):
        # shadow the target's chunk: same tokens, same cursor, same table —
        # the draft logits are discarded (the TARGET's prefill logits seed
        # the first token; the draft model only ever needs its cache warm)
        self.pool = self._prefill(self.params, chunk, start, last_idx,
                                  self.pool, table)

    def propose(self, dec_slots, tok0, pos, tables):
        drafts, self.pool = self._draft_steps(self.params, jnp.asarray(tok0),
                                              jnp.asarray(pos), self.pool,
                                              jnp.asarray(tables))
        # dstpu: ignore[DT001]: drafts are consumed host-side by accept_greedy — one readback per verify, amortized over k drafts x all slots
        drafts = np.asarray(jax.device_get(drafts))
        lens = np.zeros((tok0.shape[0],), np.int32)
        for s in dec_slots:
            lens[s.idx] = self.k
        return drafts, lens

    def compile_stats(self):
        return {"draft_prefill": int(self._prefill._cache_size()),
                "draft_steps": int(self._draft_steps._cache_size())}


def make_drafter(serving, cfg, draft_spec=None) -> Optional[Drafter]:
    """Build the configured drafter for a ServingEngine (None = spec decode
    off). `cfg` is the `ServingConfig.spec_decode` block."""
    kind = str(cfg.drafter or "off")
    if kind == "off":
        return None
    if int(cfg.draft_k) < 1:
        raise ValueError(f"spec_decode.draft_k must be >= 1 when the "
                         f"drafter is on (got {cfg.draft_k})")
    if kind == "ngram":
        return NgramDrafter(cfg.draft_k, max_n=cfg.ngram_max,
                            min_n=cfg.ngram_min)
    if kind == "model":
        if draft_spec is None:
            raise ValueError(
                "spec_decode.drafter='model' needs a draft DecodeModelSpec: "
                "engine.serving(draft_spec=make_gpt_decode_model(...))")
        return DraftModelDrafter(serving, draft_spec, cfg.draft_k)
    raise ValueError(f"unknown spec_decode.drafter {kind!r} "
                     f"(expected 'off', 'ngram' or 'model')")


def accept_greedy(draft_row: np.ndarray, target_row: np.ndarray,
                  draft_len: int) -> Tuple[int, List[int]]:
    """Longest-agreeing-prefix acceptance for one slot.

    `draft_row` [k]: the proposed tokens; `target_row` [k+1]: the verify
    step's sampled token per position (row i is the target's choice AFTER
    draft i — under greedy sampling, the argmax); `draft_len`: how many
    proposals are real. Returns `(n_accepted, emitted)` where emitted =
    the accepted drafts plus the bonus token from the first disagreeing
    row — always 1..k+1 tokens, so even a zero-length draft degrades to
    exactly the plain decode step (one target-sampled token)."""
    n = 0
    while n < draft_len and int(draft_row[n]) == int(target_row[n]):
        n += 1
    return n, [int(t) for t in draft_row[:n]] + [int(target_row[n])]
