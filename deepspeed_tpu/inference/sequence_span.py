"""Sequence-spanning serving — one monster-context request across chips.

The serving tier's paged pool (`inference/kv_cache.py`) caps a request's
context at what ONE chip's HBM holds. This module removes that wall for the
128k+ tier: the pool's physical-block axis is sharded over the `sequence`
mesh axis, a request's block table is SPLIT into per-shard tables (shard s
owns the contiguous logical-block range [s·nb_s, (s+1)·nb_s) — i.e. the
contiguous token range [s·nb_s·bs, (s+1)·nb_s·bs), ring order), and the
attention of every serving step runs as a shard_map over the sequence axis:

  * WRITE — chunked prefill "walks the ring": each incoming chunk's tokens
    scatter into the shard that owns their positions (non-owned positions
    land in that shard's trash block), so the prefill cursor advances
    through shard 0's blocks, then shard 1's, ... exactly like the ring's
    token order;
  * READ — each shard gathers only ITS table's blocks ([B, Hkv, nb_s·bs,
    hd] — 1/sp of the context), computes an online-softmax PARTIAL
    (m, l, o) against absolute positions, and the partials merge across
    the axis with the same (m, l) combination the ring kernel uses
    (pmax + weighted psum), leaving every chip with the full output.

Per-chip KV residency is therefore ~1/sp of the request's total KV bytes —
`memscope.plan_serving(..., sequence_parallel=sp)` prices exactly this, and
`SpanKVPool.per_chip_bytes()` is the live-ledger view. Block accounting is
per shard: `span_blocks_needed` prices a request's occupancy on EACH shard
(shard 0 binds for long prompts), and `SpanKVPool` runs one `BlockAllocator`
per shard with all-or-nothing admission across all of them.

Trash-block convention: LOCAL physical block 0 of EVERY shard is that
shard's trash block (the global pool reserves sp blocks total) — table
entries and non-owned writes point there, so the fixed-shape span step
never branches on ownership.

Scope: bf16/fp32 pools, plain causal archs (no alibi/sliding-window — the
same contract as the paged Pallas kernel). The int8 pool composes naturally
(scales ride the same sharded block axis) but is not wired here yet.
"""

import math
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.comm.mesh import SEQ_AXIS
from deepspeed_tpu.inference.kv_cache import (BlockAllocator, blocks_needed,
                                              gather_block_kv)
from deepspeed_tpu.utils.jax_compat import shard_map

SPAN_TRASH = 0   # LOCAL physical block 0 of every shard: that shard's trash


# ----------------------------------------------------------------------
# per-shard block accounting (the planner/admission math)
# ----------------------------------------------------------------------


def span_table_width(max_context: int, block_size: int, sp: int) -> int:
    """Per-shard logical table width nb_s: the global table rounds up to
    sp equal shard ranges so every shard's table (and therefore the span
    step's shape) is identical."""
    nb = -(-int(max_context) // int(block_size))
    return -(-nb // int(sp))


def span_blocks_needed(prompt_len: int, padded_prompt: int, max_new: int,
                       block_size: int, sp: int, nb_s: int,
                       window: int = 1, spec_k: int = 0) -> List[int]:
    """Physical blocks a request occupies ON EACH SHARD for its lifetime.

    The blocks-from-write-extent math is the flat pool's single source of
    truth (`kv_cache.blocks_needed` over `max_written_pos`) — this only
    SPLITS it: the contiguous logical-block range [0, used) maps onto
    shard s as its slice of [s·nb_s, (s+1)·nb_s). Shard 0 is the binding
    shard for long prompts; later shards taper. A request whose extent
    overflows sp·nb_s can never be admitted — `SpanKVPool.admit` raises
    on it (the span analog of the scheduler's table-width check)."""
    used = blocks_needed(prompt_len, padded_prompt, max_new, block_size,
                         window=window, spec_k=spec_k)
    return [max(0, min(nb_s, used - s * nb_s)) for s in range(sp)]


# ----------------------------------------------------------------------
# the span attention step (inside shard_map over the sequence axis)
# ----------------------------------------------------------------------


def _span_partial_attend(q, k_ctx, v_ctx, q_pos, k_offset, scale):
    """One shard's unnormalized online-softmax partial against ABSOLUTE
    positions. q: [B, C, H, hd]; k_ctx/v_ctx: [B, Hkv, S, hd] (this shard's
    gathered blocks, S = nb_s·bs, key i sits at absolute position
    k_offset + i); q_pos: [B, C]. GQA contracts grouped, like
    `_paged_attend`. Returns (m [B,Hkv,G,C], l [B,Hkv,G,C],
    o [B,C,Hkv,G,hd]) — fp32."""
    B, C, H, hd = q.shape
    Hkv, S = k_ctx.shape[1], k_ctx.shape[2]
    G = H // Hkv
    k_pos = k_offset + jnp.arange(S, dtype=jnp.int32)
    valid = k_pos[None, None, :] <= q_pos[:, :, None]          # [B, C, S]
    qg = q.reshape(B, C, Hkv, G, hd)
    s = jnp.einsum("bckgd,bksd->bkgcs", qg.astype(jnp.float32),
                   k_ctx.astype(jnp.float32)) * scale
    s = jnp.where(valid[:, None, None, :, :], s, -1e30)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    # all-masked rows (a shard holding only FUTURE keys for this query):
    # m == the -1e30 mask sentinel (finite!), p == exp(0) == 1 everywhere —
    # zero the row so its (l, o) partial is empty rather than trash-block
    # mass. (The cross-shard merge would also kill it — exp(m - m_g)
    # underflows to exactly 0 — but partials should be sane on their own.)
    live = (m > -5e29)[..., None]
    p = jnp.where(live, p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bkgcs,bksd->bckgd", p, v_ctx.astype(jnp.float32))
    return m, l, o


def _span_attn_local(q, k_new, v_new, pool_k, pool_v, tbl, positions, *,
                     axis_name, bs, scale):
    """Per-shard write + partial attend + cross-shard merge. Local shapes:
    q [B,C,H,hd]; k_new/v_new [B,C,Hkv,hd]; pool_k/v [N_s,Hkv,bs,hd] (this
    shard's physical blocks); tbl [B,1,nb_s] (this shard's table slice,
    LOCAL physical ids, 0 = local trash); positions [B,C] absolute."""
    B, C, H, hd = q.shape
    nb_s = tbl.shape[-1]
    s_idx = jax.lax.axis_index(axis_name)
    tbl = tbl[:, 0]

    # write: this shard owns logical blocks [s·nb_s, (s+1)·nb_s) — tokens
    # outside that range scatter into the LOCAL trash block, so the chunk
    # walk needs no ownership branch (the ring-walk write)
    lb = positions // bs
    own = (lb >= s_idx * nb_s) & (lb < (s_idx + 1) * nb_s)
    lb_local = jnp.clip(lb - s_idx * nb_s, 0, nb_s - 1)
    blk = jnp.where(own, jnp.take_along_axis(tbl, lb_local, axis=1),
                    SPAN_TRASH)
    off = positions % bs
    pool_k = pool_k.at[blk, :, off, :].set(k_new.astype(pool_k.dtype))
    pool_v = pool_v.at[blk, :, off, :].set(v_new.astype(pool_v.dtype))

    # read: gather ONLY this shard's blocks (1/sp of the context), partial
    # online-softmax at the shard's absolute key offset, merge over the axis
    k_ctx, v_ctx = gather_block_kv(pool_k, pool_v, tbl)
    m, l, o = _span_partial_attend(q, k_ctx, v_ctx, positions,
                                   s_idx * nb_s * bs, scale)
    m_g = jax.lax.pmax(m, axis_name)
    safe = jnp.where(jnp.isfinite(m_g), m_g, 0.0)
    coef = jnp.where(jnp.isfinite(m), jnp.exp(m - safe), 0.0)  # [B,Hkv,G,C]
    l_g = jax.lax.psum(l * coef, axis_name)
    o_g = jax.lax.psum(o * coef.transpose(0, 3, 1, 2)[..., None], axis_name)
    out = o_g / jnp.maximum(l_g.transpose(0, 3, 1, 2)[..., None], 1e-30)
    return out.reshape(B, C, H * hd).astype(q.dtype), pool_k, pool_v


def make_span_gpt_fns(cfg, mesh=None, axis_name=SEQ_AXIS):
    """(prefill_chunk_fn, decode_fn) for a GPT config over a sequence-
    sharded paged pool — the span analogs of the serving engine's two
    programs, same shapes-never-change contract:

      prefill_chunk_fn(params, tokens [B,C], start_pos [B], pool,
                       span_tables [B,sp,nb_s]) -> (logits [B,C,V], pool)
      decode_fn(params, token [B], pos [B], pool, span_tables)
                       -> (logits [B,V], pool)

    `pool` is the `init_paged_kv_pool` tree with leaves placed
    P(None, `sequence`, ...) (the physical-block axis sharded — see
    `SpanKVPool`); `span_tables` hold LOCAL physical ids per shard. Layers
    scan exactly like `_scan_paged`, so depth stays out of compile time."""
    from deepspeed_tpu.models.gpt import (_decode_qkv, _embed, _lm_head,
                                          _residual_mlp)
    mesh = mesh or mesh_mod.get_mesh()
    if cfg.use_alibi or cfg.sliding_window:
        raise ValueError(
            "sequence-spanning serving carries the plain-causal kernel "
            "contract: alibi / sliding-window archs are not supported")
    scale = 1.0 / math.sqrt(cfg.head_dim) if cfg.scale_attn else 1.0

    rep = P(*([None] * 4))
    # one LAYER's pool slice [N, Hkv, block, hd]: block axis sharded
    pool_spec = P(axis_name, None, None, None)

    def _span_half(x, p, pool_l, positions, span_tables):
        bs = pool_l["k"].shape[2]
        q, k, v = _decode_qkv(x, p, positions, cfg)
        fn = shard_map(
            partial(_span_attn_local, axis_name=axis_name, bs=bs,
                    scale=scale),
            mesh=mesh,
            in_specs=(rep, rep, rep, pool_spec, pool_spec,
                      P(None, axis_name, None), P(None, None)),
            out_specs=(P(None, None, None), pool_spec, pool_spec),
            check_vma=False)
        attn, pk, pv = fn(q, k, v, pool_l["k"], pool_l["v"], span_tables,
                          positions)
        pool_l = dict(pool_l, k=pk, v=pv)
        attn_out = attn @ p["attn_out_w"] + p["attn_out_b"]
        return attn_out, pool_l

    def _scan_span(params, x, pool, span_tables, positions):
        def body(x, inputs):
            p, pool_l = inputs
            attn_out, pool_l = _span_half(x, p, pool_l, positions,
                                          span_tables)
            x = _residual_mlp(x, attn_out, p, cfg, constrain=False)
            return x, pool_l

        return jax.lax.scan(body, x, (params["blocks"], pool))

    def prefill_chunk_fn(params, tokens, start_pos, pool, span_tables):
        B, C = tokens.shape
        positions = start_pos[:, None] + jnp.arange(C, dtype=jnp.int32)[None]
        x = _embed(params, tokens, positions, cfg)
        x, pool = _scan_span(params, x, pool, span_tables, positions)
        return _lm_head(params, x, cfg), pool

    def decode_fn(params, token, pos, pool, span_tables):
        x = _embed(params, token[:, None], pos[:, None], cfg)
        x, pool = _scan_span(params, x, pool, span_tables, pos[:, None])
        return _lm_head(params, x, cfg)[:, 0], pool

    return prefill_chunk_fn, decode_fn


# ----------------------------------------------------------------------
# the host-side span pool manager
# ----------------------------------------------------------------------


class SpanKVPool:
    """A paged KV pool whose physical-block axis spans the `sequence` mesh
    axis, plus the per-shard allocators and table builder.

    Allocation is per shard (one ref-counted `BlockAllocator` each, LOCAL
    block 0 reserved as that shard's trash) and ALL-OR-NOTHING across
    shards — a request either gets its priced occupancy on every shard
    (`span_blocks_needed`) or admits nothing, the flat pool's deadlock rule
    lifted to the span. Per-chip KV bytes are `per_chip_bytes()` —
    1/sp of the global pool, the number `plan_serving(...,
    sequence_parallel=sp)` predicts.

    Ledger contract: a serving engine built OVER a span pool mirrors
    `span_shards` (`serving.span_shards = pool.span_shards`) so
    `ServingMemScope` divides its `mem/kv_pool_per_chip_bytes` gauge —
    that attribute is the ONE wire between the span pool and the ledger
    (flat engines default to 1 and the gauge equals `mem/kv_pool_bytes`)."""

    def __init__(self, cfg, blocks_per_shard, block_size, mesh=None,
                 dtype=jnp.bfloat16, axis_name=SEQ_AXIS):
        from deepspeed_tpu.models.gpt import init_paged_kv_pool
        self.mesh = mesh or mesh_mod.get_mesh()
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        self.sp = sizes.get(axis_name, 1)
        self.blocks_per_shard = int(blocks_per_shard)
        self.block_size = int(block_size)
        if jnp.dtype(dtype) == jnp.int8:
            raise ValueError("SpanKVPool: the int8 quantized pool is not "
                             "wired through the span step yet")
        pool = init_paged_kv_pool(cfg, self.sp * self.blocks_per_shard,
                                  block_size, dtype)
        sharding = NamedSharding(self.mesh, P(None, axis_name, None, None,
                                              None))
        self.pool = jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, sharding), pool)
        self.allocators = [BlockAllocator(self.blocks_per_shard)
                           for _ in range(self.sp)]
        # the ledger wire (see class docstring): engines mirror this
        self.span_shards = self.sp

    def per_chip_bytes(self) -> int:
        """MEASURED addressable KV bytes per sequence shard — computed
        from each leaf's actual shard shape under its sharding (not
        total/sp arithmetic), so a silently-dropped placement would show
        up as full-pool residency here, not be papered over. This is the
        live number the planner's `sequence_parallel` pricing predicts."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.pool):
            shape = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shape)) * jnp.dtype(leaf.dtype).itemsize
        return total

    def admit(self, prompt_len: int, max_new: int, nb_s: int,
              padded_prompt: Optional[int] = None,
              window: int = 1, spec_k: int = 0) -> Optional[np.ndarray]:
        """Allocate one request's span tables: [sp, nb_s] int32 LOCAL
        physical ids (trash-filled past each shard's occupancy). None —
        and no state change on ANY shard — when a shard cannot serve its
        slice RIGHT NOW (backpressure); raises ValueError when the
        request can NEVER fit — its write extent overflows the sp·nb_s
        table (the span analog of the scheduler's table-width check —
        without it, out-of-table positions would scatter into trash and
        decode would silently read truncated context), or a shard's need
        exceeds that shard's whole allocator capacity."""
        padded = int(padded_prompt) if padded_prompt else prompt_len
        used = blocks_needed(prompt_len, padded, max_new, self.block_size,
                             window=window, spec_k=spec_k)
        if used > self.sp * nb_s:
            raise ValueError(
                f"span request needs {used} logical blocks but the span "
                f"table holds {self.sp} x {nb_s} = {self.sp * nb_s} — "
                f"prompt {prompt_len} (+{max_new} new) exceeds the pool's "
                f"max context {self.sp * nb_s * self.block_size}; raise "
                f"nb_s / blocks_per_shard or the sequence-axis size")
        needs = span_blocks_needed(prompt_len, padded, max_new,
                                   self.block_size, self.sp, nb_s,
                                   window=window, spec_k=spec_k)
        for s, (alloc, need) in enumerate(zip(self.allocators, needs)):
            if need > alloc.capacity:
                # permanent, not backpressure: a retry loop treating None
                # as try-again would starve this request forever
                raise ValueError(
                    f"span request needs {need} blocks on shard {s} but "
                    f"the shard's allocator holds {alloc.capacity} usable "
                    f"blocks — it can never be admitted; raise "
                    f"blocks_per_shard")
        got, tables = [], np.full((self.sp, nb_s), SPAN_TRASH, np.int32)
        for s, (alloc, need) in enumerate(zip(self.allocators, needs)):
            blocks = alloc.alloc(need) if need else []
            if need and blocks is None:
                for a, b in zip(self.allocators, got):     # roll back
                    a.free(b)
                return None
            got.append(blocks)
            tables[s, :len(blocks)] = blocks
        return tables

    def free(self, tables: np.ndarray):
        """Retire a request: decref every real block on every shard."""
        for s, alloc in enumerate(self.allocators):
            real = [int(b) for b in tables[s] if b != SPAN_TRASH]
            if real:
                alloc.free(real)
