"""ZeRO-Inference: serve models whose weights exceed HBM.

Reference: `docs/_posts/2022-09-10-zero-inference.md:35` ("15T-param model
inference on 1 GPU") — ZeRO-3's `AsyncPartitionedParameterSwapper`
(`runtime/swap_tensor/partitioned_param_swapper.py:36`) keeps the weights on
host RAM or NVMe and fetches each layer's partition right before use.

TPU-native design: the transformer stack is homogeneous, so ONE jitted
per-layer function serves every layer with the layer's weights as arguments.
`runtime/param_swap.LayerStreamer` double-buffers host->HBM uploads (and
NVMe->host reads below them) while the current layer computes. HBM holds:
resident leaves (embeddings/norms/head) + `lookahead+1` layer blocks + the
KV cache — independent of model depth, which is the whole point.

Cost model (same as the reference's): every forward streams all weights
through HBM once, so throughput is bounded by the host link — batch as large
as the KV cache allows to amortize. The reference makes the identical
recommendation (zero-inference.md "efficiency" section).
"""

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.inference.config import TpuInferenceConfig
from deepspeed_tpu.inference.engine import sample_logits
from deepspeed_tpu.runtime.param_swap import LayerParamStore, LayerStreamer
from deepspeed_tpu.utils.logging import log_dist
from deepspeed_tpu.utils.tree import tree_cast


@dataclasses.dataclass
class LayeredModelSpec:
    """A decode model factored into per-layer pieces (see
    `models/gpt.py::make_gpt_layered_model`)."""
    embed_fn: Callable        # (resident, tokens[B,T], positions[B,T]) -> x[B,T,D]
    layer_prefill_fn: Callable  # (layer_p, x, ck, cv, positions) -> (x, ck, cv)
    layer_decode_fn: Callable   # (layer_p, x[B,1,D], ck, cv, pos[B]) -> (x, ck, cv)
    final_fn: Callable        # (resident, x[B,T,D]) -> logits[B,T,V]
    resident: Any             # always-in-HBM params (embed/norms/head)
    blocks: Any               # stacked per-layer params (leading dim L)
    num_layers: int
    init_layer_cache: Callable  # (B, max_len, dtype) -> (ck, cv) one layer
    resident_specs: Any = None  # PartitionSpecs for TP sharding of resident
    block_specs: Any = None     # per-LAYER PartitionSpecs (no leading L dim)
    # training-side spill (runtime/infinity.py):
    layer_train_fn: Optional[Callable] = None  # (layer_p, x, positions) -> x
    train_loss_fn: Optional[Callable] = None   # (resident, x, labels) -> loss
    eos_token_id: Optional[int] = None
    name: str = "model"
    # streamed paged-serving contract (inference/scheduler.py offloaded-
    # weights mode): ONE jitted per-layer program reused for every layer,
    # weights streamed by the staging pool while the paged pool stays
    # device-resident and is updated in place (donated) layer by layer.
    #   layer_paged_fn(layer_p, x[B,C,D], layer_idx, pool, block_tables,
    #                  positions[B,C]) -> (x, pool)
    #     layer_idx is a TRACED scalar — the pool's layer axis is sliced /
    #     updated with dynamic_index/update, so L layers share one compile
    #   init_paged_pool(num_blocks, block_size, dtype[, kv_group_size])
    #     -> pool pytree (the same [L, N, Hkv, block, hd] layout as
    #     DecodeModelSpec's)
    layer_paged_fn: Optional[Callable] = None
    init_paged_pool: Optional[Callable] = None
    # cache-identity fingerprint (prefix cache hash chain; falls back to
    # `name`) — same contract as DecodeModelSpec.cache_fingerprint
    cache_fingerprint: Optional[str] = None


class ZeroInferenceEngine:
    """Inference engine with the parameter spill tier.

    `offload_device`: "cpu" (host RAM) or "nvme" (disk via the AIO library,
    O_DIRECT). `lookahead`: how many layers of weights to keep in flight
    ahead of compute (1 = classic double buffering)."""

    def __init__(self, model: LayeredModelSpec, config: TpuInferenceConfig,
                 offload_device="cpu", nvme_path=None, lookahead=1,
                 staging=3):
        self.model_spec = model
        self.config = config
        dtype = jnp.dtype(config.dtype) if config.dtype != "float" else jnp.float32
        self.dtype = dtype

        if not mesh_mod.has_mesh():
            from deepspeed_tpu import comm
            from deepspeed_tpu.config.core import MeshConfig
            tp = config.tensor_parallel.tp_size
            comm.init_distributed(mesh_config=MeshConfig(data=-1, tensor=tp))
        self.mesh = mesh_mod.get_mesh()

        from jax.sharding import NamedSharding
        tp = config.tensor_parallel.tp_size
        if tp > 1 and model.block_specs is None:
            raise ValueError(
                f"tensor_parallel.tp_size={tp} with parameter spill needs a "
                "LayeredModelSpec carrying block_specs/resident_specs (the "
                "GPT zoo's make_gpt_layered_model provides them); refusing "
                "to silently serve unsharded layers")
        if model.resident_specs is not None:
            res_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), model.resident_specs)
            self.resident = jax.device_put(tree_cast(model.resident, dtype),
                                           res_sh)
        else:
            self.resident = jax.device_put(tree_cast(model.resident, dtype))
        from deepspeed_tpu.telemetry import Telemetry
        self.telemetry = Telemetry(getattr(config, "telemetry", None),
                                   subsystem="zero_inference")
        self.store = LayerParamStore(
            tree_cast(model.blocks, dtype), device=offload_device,
            swap_folder=nvme_path, staging=staging)
        self.store.telemetry = self.telemetry
        layer_sh = None
        if model.block_specs is not None:
            layer_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), model.block_specs)
        # cyclic: decode walks layers 0..L-1 over and over — pinning the
        # look-ahead to that scan order keeps layer 0 staged while L-1
        # computes, so the wrap between steps never restarts cold
        self.streamer = LayerStreamer(self.store, shardings=layer_sh,
                                      lookahead=lookahead, cyclic=True,
                                      telemetry=self.telemetry)
        self.total_param_bytes = (
            self.store.layer_bytes * self.store.num_layers)

        # one compiled function per role, reused for every layer
        self._embed = jax.jit(model.embed_fn)
        self._layer_prefill = jax.jit(model.layer_prefill_fn,
                                      donate_argnums=(1, 2, 3))
        self._layer_decode = jax.jit(model.layer_decode_fn,
                                     donate_argnums=(1, 2, 3))
        self._final = jax.jit(model.final_fn)
        # scheduler-facing surface (serving() streamed mode): resident
        # params ARE the device-resident tree; no dequant transform here
        self._fn_transform = lambda fn: fn
        # engine-owned cache template (PR 3 satellite pattern): generate()
        # reuses the previous request's cache buffers when (B, max_len,
        # dtype) matches instead of re-allocating (and re-zeroing) a fresh
        # per-layer cache every call. Safe WITHOUT re-zeroing: decode masks
        # attention to k_pos <= pos and prefill never reads the cache, so
        # stale content past the written prefix is provably unattended.
        # The layer programs donate their cache arguments, so the retained
        # entry is always the most recently RETURNED buffers.
        self._cache_entry = None       # ((B, max_len, dtype), caches)
        self._cache_hits = 0
        log_dist(
            f"zero-inference engine: {model.name} dtype={dtype} "
            f"offload={offload_device} layers={self.store.num_layers} "
            f"layer_mb={self.store.layer_bytes / 1e6:.1f} "
            f"resident+{lookahead + 1} layers in HBM", ranks=[0])

    @property
    def params(self):
        """The device-RESIDENT param tree (embeddings/norms/head) — what
        the serving scheduler passes to the embed/head programs; the
        streamed blocks never appear here."""
        return self.resident

    def enable_weight_quant(self, bits=8, group_size=64):
        raise ValueError(
            "weight-only quantization is a resident-engine feature "
            "(InferenceEngine.enable_weight_quant): the spill tier streams "
            "bit16 layers from the host store — quantize the HOST copies "
            "instead by building the store at a narrower dtype, or serve "
            "resident with serving.quantization.weights")

    # ---- forward ----

    def _init_caches(self, B, max_len):
        dt = jnp.dtype(self.config.kv_cache_dtype)
        return [self.model_spec.init_layer_cache(B, max_len, dt)
                for _ in range(self.store.num_layers)]

    def _own_caches(self, B, max_len):
        """Engine-owned per-layer cache buffers for generate(): reused on a
        shape match (ONE retained entry — a multi-shape store would pin
        several full caches in HBM). The entry is checked out here and
        checked back in by generate() AFTER the decode loop — donation
        rotates the underlying buffers, so the retained reference must be
        whatever the programs last returned."""
        key = (int(B), int(max_len), str(self.config.kv_cache_dtype))
        if self._cache_entry is not None and self._cache_entry[0] == key:
            self._cache_hits += 1
            caches = self._cache_entry[1]
        else:
            caches = self._init_caches(B, max_len)
        self._cache_entry = None       # checked out (buffers will be donated)
        return key, caches

    def forward(self, tokens, caches=None, max_len=None):
        """Prefill: logits [B,T,V] + per-layer caches, streaming the weights."""
        tokens = jnp.asarray(tokens, jnp.int32)
        B, T = tokens.shape
        if caches is None:
            caches = self._init_caches(B, max_len or self.config.max_out_tokens)
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
        x = self._embed(self.resident, tokens, positions)
        for i in range(self.store.num_layers):
            p = self.streamer.layer(i)
            x, ck, cv = self._layer_prefill(p, x, caches[i][0], caches[i][1],
                                            positions)
            caches[i] = (ck, cv)
        logits = self._final(self.resident, x)
        return logits, caches

    __call__ = forward

    def _decode_step(self, token, pos, caches):
        x = self._embed(self.resident, token[:, None], pos[:, None])
        for i in range(self.store.num_layers):
            p = self.streamer.layer(i)
            x, ck, cv = self._layer_decode(p, x, caches[i][0], caches[i][1], pos)
            caches[i] = (ck, cv)
        logits = self._final(self.resident, x)[:, 0]
        return logits, caches

    def _sample(self, logits, rng):
        """Config-driven sampling — the SAME rule as the resident engine."""
        return sample_logits(logits, rng, greedy=self.config.greedy,
                             temperature=self.config.temperature,
                             top_k=self.config.top_k,
                             top_p=self.config.top_p)

    def generate(self, tokens, max_new_tokens=16, eos_token_id=None,
                 pad_token_id=0, rng=None):
        """Generation (greedy, or sampled per the config's
        temperature/top_k when greedy=False and an rng is given). Each
        emitted token streams the full weight set through HBM — the
        ZeRO-Inference cost model; batch wide to amortize."""
        tokens = jnp.asarray(tokens, jnp.int32)
        B, T = tokens.shape
        if rng is None and not self.config.greedy:
            rng = jax.random.PRNGKey(0)
        cache_key, caches = self._own_caches(B, T + max_new_tokens)
        logits, caches = self.forward(tokens, caches)
        if rng is not None:
            rng, sub = jax.random.split(rng)
        else:
            sub = None
        tok = self._sample(logits[:, -1, :], sub)
        pos = jnp.full((B,), T, jnp.int32)
        eos = self.model_spec.eos_token_id if eos_token_id is None else eos_token_id
        out = []
        done = np.zeros((B,), bool)
        for step in range(max_new_tokens):
            emitted = np.where(done, pad_token_id, np.asarray(tok))
            out.append(emitted)
            if eos is not None:
                done |= emitted == eos
            # only pay a decode pass (a full weight stream through HBM) when
            # another token will actually be emitted
            if step == max_new_tokens - 1 or done.all():
                break
            logits, caches = self._decode_step(tok, pos, caches)
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            tok = self._sample(logits, sub)
            pos = pos + 1
        # check the (donation-rotated) cache buffers back in for the next
        # shape-matching request
        self._cache_entry = (cache_key, caches)
        return np.stack(out, axis=1)

    # ---- serving -------------------------------------------------------

    def serving(self, **overrides):
        """Continuous-batching serving over STREAMED weights: the paged KV
        pool and scheduler (inference/scheduler.py) with this engine's
        staging pool feeding one jitted per-layer program — the
        router/scheduler stack serves a model bigger than HBM. Constraints
        of the streamed mode (enforced loudly by the scheduler): decode
        window 1, no speculative decoding, no weight-only quant."""
        from deepspeed_tpu.inference.scheduler import ServingEngine
        return ServingEngine(self, **overrides)

    # ---- accounting (for tests and `see_memory_usage`-style reporting) ----

    @property
    def peak_param_hbm_bytes(self):
        """High-water mark of device-resident spilled-parameter bytes."""
        return self.streamer.peak_live_layers * self.store.layer_bytes

    def release(self):
        self.telemetry.close()
        self.store.release()
