"""Weight-only quantization for inference (ZeroQuant-style WOQ).

Reference: `deepspeed/inference/quantization/` (`quantization.py`, `layers.py`)
— int8/int4 groupwise weight quantization with dequant-on-use linear layers.
TPU-native realization: quantize the param pytree once at engine build (int8, or
int4 packed two-per-byte); the model functions run against a dequantizing view
inside jit, so XLA fuses dequant into the consuming matmul and the HBM-resident
weights stay quantized — 2x/4x weight-memory saving, which is what lets a chip
hold a model 2-4x over its bf16 capacity (ZeRO-Inference direction,
`docs/_posts/2022-09-10-zero-inference.md`).

Groupwise symmetric: scale = max|x|/qmax per `group_size` elements of the last
dim (same scheme as `csrc/quantization/quantize.cu`).
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """int8/int4-packed weight + groupwise scales; a pytree leaf pair."""
    q: Any                 # int8 payload ([..., D] for 8-bit, [..., D//2] packed for 4-bit)
    scale: Any             # f32 [..., D//group_size]
    bits: int = 8
    group_size: int = 64
    shape: tuple = ()      # original shape
    dtype: Any = jnp.bfloat16

    def tree_flatten(self):
        return (self.q, self.scale), (self.bits, self.group_size, self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        bits, group_size, shape, dtype = aux
        return cls(q=q, scale=scale, bits=bits, group_size=group_size,
                   shape=shape, dtype=dtype)

    def dequantize(self):
        return dequantize_tensor(self)


def quantize_tensor(x, bits=8, group_size=64):
    """x: [..., D] float → QuantizedTensor. Symmetric per-group."""
    assert bits in (4, 8)
    orig_shape = tuple(x.shape)
    D = orig_shape[-1]
    assert D % group_size == 0
    qmax = 127.0 if bits == 8 else 7.0
    xg = x.astype(jnp.float32).reshape(-1, D // group_size, group_size)
    amax = jnp.max(jnp.abs(xg), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.clip(jnp.round(xg / scale[..., None]), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(orig_shape)
    scale = scale.reshape(orig_shape[:-1] + (D // group_size,))
    if bits == 4:
        # pack two int4 values per byte: bias to [1,15] unsigned nibbles
        qu = (q.astype(jnp.int32) + 8).astype(jnp.uint8)
        packed = (qu[..., 0::2] | (qu[..., 1::2] << 4)).astype(jnp.uint8)
        q = jax.lax.bitcast_convert_type(packed, jnp.int8)
    return QuantizedTensor(q=q, scale=scale, bits=bits, group_size=group_size,
                           shape=orig_shape, dtype=x.dtype)


def dequantize_tensor(t: QuantizedTensor):
    D = t.shape[-1]
    if t.bits == 4:
        packed = jax.lax.bitcast_convert_type(t.q, jnp.uint8)
        lo = (packed & 0xF).astype(jnp.int32) - 8
        hi = (packed >> 4).astype(jnp.int32) - 8
        q = jnp.stack([lo, hi], axis=-1).reshape(t.shape[:-1] + (D,))
    else:
        q = t.q.astype(jnp.int32)
    qf = q.astype(jnp.float32).reshape(-1, D // t.group_size, t.group_size)
    x = qf * t.scale.reshape(-1, D // t.group_size)[..., None]
    return x.reshape(t.shape).astype(t.dtype)


def quantize_param_tree(params, bits=8, group_size=64, min_size=4096,
                        exclude_keys=("scale", "bias", "ln", "norm")):
    """Quantize every large float matrix leaf; small/1-D/norm params stay dense.

    Returns (qtree, stats). `exclude_keys`: substring match on the leaf path —
    norm scales and biases are precision-critical and tiny (reference
    `layers.py` quantizes Linear/Embedding weights only).
    """
    n_q, n_dense = [0], [0]
    bytes_before, bytes_after = [0], [0]

    def leaf(path, x):
        key = "/".join(str(getattr(k, "key", k)) for k in path).lower()
        is_float = jnp.issubdtype(x.dtype, jnp.floating)
        quantizable = (is_float and x.ndim >= 2 and x.size >= min_size
                       and x.shape[-1] % group_size == 0
                       and (bits == 8 or x.shape[-1] % 2 == 0)
                       and not any(e in key for e in exclude_keys))
        bytes_before[0] += x.size * x.dtype.itemsize
        if not quantizable:
            n_dense[0] += 1
            bytes_after[0] += x.size * x.dtype.itemsize
            return x
        t = quantize_tensor(x, bits=bits, group_size=group_size)
        n_q[0] += 1
        bytes_after[0] += t.q.size + t.scale.size * 4
        return t

    qtree = jax.tree_util.tree_map_with_path(leaf, params)
    stats = {"quantized": n_q[0], "dense": n_dense[0],
             "bytes_before": bytes_before[0], "bytes_after": bytes_after[0],
             "ratio": bytes_before[0] / max(bytes_after[0], 1)}
    logger.info(f"WOQ int{bits}: {n_q[0]} tensors quantized, {n_dense[0]} dense, "
                f"{stats['ratio']:.2f}x weight-memory saving")
    return qtree, stats


def dequantize_param_tree(qtree):
    """Inverse (call inside jit: XLA fuses dequant into consumers)."""
    return jax.tree_util.tree_map(
        lambda x: x.dequantize() if isinstance(x, QuantizedTensor) else x,
        qtree, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def wrap_fn_dequant(fn):
    """fn(params, ...) → fn'(qparams, ...): dequantizes params first."""
    def wrapped(qparams, *args, **kw):
        return fn(dequantize_param_tree(qparams), *args, **kw)
    return wrapped
