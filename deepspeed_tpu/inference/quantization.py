"""Weight-only quantization for inference (ZeroQuant-style WOQ).

Reference: `deepspeed/inference/quantization/` (`quantization.py`, `layers.py`)
— int8/int4 groupwise weight quantization with dequant-on-use linear layers.
TPU-native realization: quantize the param pytree once at engine build (int8, or
int4 packed two-per-byte); the model functions run against a dequantizing view
inside jit, so XLA fuses dequant into the consuming matmul and the HBM-resident
weights stay quantized — 2x/4x weight-memory saving, which is what lets a chip
hold a model 2-4x over its bf16 capacity (ZeRO-Inference direction,
`docs/_posts/2022-09-10-zero-inference.md`).

Groupwise symmetric: scale = max|x|/qmax per `group_size` elements of the last
dim (same scheme as `csrc/quantization/quantize.cu`).
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import logger


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """int8/int4-packed weight + groupwise scales; a pytree leaf pair."""
    q: Any                 # int8 payload ([..., D] for 8-bit, [..., D//2] packed for 4-bit)
    scale: Any             # f32 [..., D//group_size]
    bits: int = 8
    group_size: int = 64
    shape: tuple = ()      # original shape
    dtype: Any = jnp.bfloat16

    def tree_flatten(self):
        return (self.q, self.scale), (self.bits, self.group_size, self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        bits, group_size, shape, dtype = aux
        return cls(q=q, scale=scale, bits=bits, group_size=group_size,
                   shape=shape, dtype=dtype)

    def dequantize(self):
        return dequantize_tensor(self)


def quantize_tensor(x, bits=8, group_size=64):
    """x: [..., D] float → QuantizedTensor. Symmetric per-group.

    Raises `ValueError` (not a bare assert) on inadmissible geometry so a
    config typo surfaces as a clear message at quantize time instead of an
    opaque traceback inside a reshape: the last dim must tile into whole
    `group_size` groups (each group shares one scale — a ragged tail would
    need a second scale grid), and the int4 path packs two values per byte,
    so D must additionally be even."""
    if bits not in (4, 8):
        raise ValueError(f"quantize_tensor: bits must be 4 or 8 (got {bits})")
    orig_shape = tuple(x.shape)
    D = orig_shape[-1]
    if group_size < 1 or D % group_size != 0:
        raise ValueError(
            f"quantize_tensor: last dim {D} does not tile into groups of "
            f"{group_size} (shape {orig_shape}) — pick a group_size that "
            f"divides it, or leave this tensor dense "
            f"(quantize_param_tree skips non-tiling leaves automatically)")
    if bits == 4 and D % 2 != 0:
        raise ValueError(
            f"quantize_tensor: int4 packs two values per byte — last dim "
            f"{D} must be even (shape {orig_shape})")
    qmax = 127.0 if bits == 8 else 7.0
    xg = x.astype(jnp.float32).reshape(-1, D // group_size, group_size)
    amax = jnp.max(jnp.abs(xg), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / qmax
    q = jnp.clip(jnp.round(xg / scale[..., None]), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(orig_shape)
    scale = scale.reshape(orig_shape[:-1] + (D // group_size,))
    if bits == 4:
        # pack two int4 values per byte: bias to [1,15] unsigned nibbles
        qu = (q.astype(jnp.int32) + 8).astype(jnp.uint8)
        packed = (qu[..., 0::2] | (qu[..., 1::2] << 4)).astype(jnp.uint8)
        q = jax.lax.bitcast_convert_type(packed, jnp.int8)
    return QuantizedTensor(q=q, scale=scale, bits=bits, group_size=group_size,
                           shape=orig_shape, dtype=x.dtype)


def dequantize_tensor(t: QuantizedTensor):
    D = t.shape[-1]
    if t.bits == 4:
        packed = jax.lax.bitcast_convert_type(t.q, jnp.uint8)
        lo = (packed & 0xF).astype(jnp.int32) - 8
        hi = (packed >> 4).astype(jnp.int32) - 8
        q = jnp.stack([lo, hi], axis=-1).reshape(t.shape[:-1] + (D,))
    else:
        q = t.q.astype(jnp.int32)
    qf = q.astype(jnp.float32).reshape(-1, D // t.group_size, t.group_size)
    x = qf * t.scale.reshape(-1, D // t.group_size)[..., None]
    return x.reshape(t.shape).astype(t.dtype)


def quantize_param_tree(params, bits=8, group_size=64, min_size=4096,
                        exclude_keys=("scale", "bias", "ln", "norm")):
    """Quantize every large float matrix leaf; small/1-D/norm params stay dense.

    Returns (qtree, stats). `exclude_keys`: substring match on the leaf path —
    norm scales and biases are precision-critical and tiny (reference
    `layers.py` quantizes Linear/Embedding weights only).
    """
    n_q, n_dense = [0], [0]
    bytes_before, bytes_after = [0], [0]

    def leaf(path, x):
        key = "/".join(str(getattr(k, "key", k)) for k in path).lower()
        is_float = jnp.issubdtype(x.dtype, jnp.floating)
        quantizable = (is_float and x.ndim >= 2 and x.size >= min_size
                       and x.shape[-1] % group_size == 0
                       and (bits == 8 or x.shape[-1] % 2 == 0)
                       and not any(e in key for e in exclude_keys))
        bytes_before[0] += x.size * x.dtype.itemsize
        if not quantizable:
            n_dense[0] += 1
            bytes_after[0] += x.size * x.dtype.itemsize
            return x
        t = quantize_tensor(x, bits=bits, group_size=group_size)
        n_q[0] += 1
        bytes_after[0] += t.q.size + t.scale.size * 4
        return t

    qtree = jax.tree_util.tree_map_with_path(leaf, params)
    stats = {"quantized": n_q[0], "dense": n_dense[0],
             "bytes_before": bytes_before[0], "bytes_after": bytes_after[0],
             "ratio": bytes_before[0] / max(bytes_after[0], 1)}
    logger.info(f"WOQ int{bits}: {n_q[0]} tensors quantized, {n_dense[0]} dense, "
                f"{stats['ratio']:.2f}x weight-memory saving")
    return qtree, stats


def dequantize_param_tree(qtree):
    """Inverse (call inside jit: XLA fuses dequant into consumers)."""
    return jax.tree_util.tree_map(
        lambda x: x.dequantize() if isinstance(x, QuantizedTensor) else x,
        qtree, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def wrap_fn_dequant(fn):
    """fn(params, ...) → fn'(qparams, ...): dequantizes params first."""
    def wrapped(qparams, *args, **kw):
        return fn(dequantize_param_tree(qparams), *args, **kw)
    return wrapped


# ----------------------------------------------------------------------
# int8 KV-cache quantization (the paged pool's write/read primitives)
# ----------------------------------------------------------------------
#
# The serving pool stores K/V as int8 with per-group fp32 scales along the
# head dim (`models/gpt.init_paged_kv_pool` grows `k_scale`/`v_scale` leaves
# [L, N, Hkv, block, hd//g] beside the payload). These two functions are the
# SINGLE definition of that scheme's numerics, shared by the cache-write
# scatter inside the jitted prefill/decode/verify programs, the dequantizing
# gather oracle (`kv_cache.gather_block_kv_dequant`), and the parity tests
# against the Pallas kernels (`ops/pallas/quant.py` uses the same
# scale = max|x|/127, clip ±127 rule — tests pin the two against each other
# so the schemes cannot drift).


def quantize_kv(x, group_size):
    """x: [..., D] float → (q int8 [..., D], scale f32 [..., D//group_size]).

    Symmetric per-group int8, identical semantics to
    `ops/pallas/quant.quantize_int8` and `quantize_tensor(bits=8)`:
    scale = max(|x|, eps)/127 per group, round-half-even, clip at ±127."""
    D = x.shape[-1]
    if group_size < 1 or D % group_size != 0:
        raise ValueError(f"quantize_kv: last dim {D} does not tile into "
                         f"groups of {group_size}")
    g = D // group_size
    xg = x.astype(jnp.float32).reshape(x.shape[:-1] + (g, group_size))
    amax = jnp.max(jnp.abs(xg), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xg / scale[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale


def dequantize_kv(q, scale, dtype=jnp.bfloat16):
    """Inverse of `quantize_kv`: int8 payload × fp32 group scale → `dtype`.

    q: [..., D] int8; scale: [..., D//g] f32. The fp32 product is narrowed
    to `dtype` LAST — the in-kernel dequant in
    `ops/pallas/decode_attention.paged_decode_attention_quant` applies the
    exact same ordering, so the kernel and this oracle see bit-identical
    K/V tiles."""
    D = q.shape[-1]
    g = scale.shape[-1]
    xf = q.astype(jnp.float32).reshape(q.shape[:-1] + (g, D // g)) \
        * scale[..., None]
    return xf.reshape(q.shape).astype(dtype)
