"""Automatic prefix caching: content-addressed KV-block reuse across requests.

Real serving traffic is dominated by shared prefixes — system prompts,
few-shot templates, multi-turn histories — yet the continuous-batching
engine (PR 3) prefills every prompt from scratch. vLLM's automatic prefix
caching (Kwon et al. 2023) and SGLang's RadixAttention (Zheng et al. 2023)
showed that FULL KV blocks are reusable verbatim across requests at zero
accuracy cost: a block's KV content is a pure function of (the tokens in and
before it, the model). The paged pool is exactly the substrate this needs —
sharing a prefix is just mapping the same physical blocks into several
slots' block tables.

Design, layered over `inference/kv_cache.BlockAllocator`:

  * every FULL prompt block gets a CHAINED content hash —
    ``h_i = sha256(h_{i-1} || tokens[i*bs:(i+1)*bs])`` seeded with the
    model's cache-identity fingerprint (`DecodeModelSpec.cache_fingerprint`)
    — so a hash names the whole prefix through that block, not the block's
    tokens alone;
  * a hash -> physical-block map serves longest-prefix match at admission:
    the scheduler maps the hit blocks straight into the new slot's table,
    bumps their refcounts, and starts the chunked-prefill cursor at the
    cached boundary;
  * a block is registered only once its content is FULLY WRITTEN (the
    prefill cursor passed it) and only if it lies strictly below
    ``prompt_len`` — the padded tail and every decode-written block stay
    private, so shared blocks are immutable by construction;
  * refcount-0 registered blocks park on the allocator's reclaimable LRU;
    eviction (hash unregistration, via the allocator's `on_evict` hook)
    happens only when a fresh allocation would otherwise fail, so caching
    never reduces usable pool capacity.

Nothing here touches the compiled step programs: a hit changes only host-
side table contents and the prefill start cursor — same shapes, zero new
compiles (`ServingEngine.compile_stats()` stays at one per program).
"""

import hashlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.inference.kv_cache import BlockAllocator


class PrefixCache:
    """Hash-chain -> physical-block map over a `BlockAllocator`.

    The cache owns no blocks and moves no data: the allocator's refcounts
    and reclaimable list carry the lifetime story, and this class installs
    itself as the allocator's `is_cached` / `on_evict` hooks so eviction
    and hash unregistration can never drift apart.
    """

    def __init__(self, allocator: BlockAllocator, block_size: int,
                 fingerprint: Optional[str] = None):
        self.allocator = allocator
        self.block_size = int(block_size)
        # the chain root commits every hash to this model's cache identity:
        # two archs (or two checkpoints someone names differently) can never
        # serve each other's KV even if their token streams collide
        self._root = hashlib.sha256(
            b"dstpu-prefix-cache:" + (fingerprint or "").encode()).digest()
        self._by_hash: Dict[bytes, int] = {}
        self._by_block: Dict[int, bytes] = {}
        allocator.is_cached = self._by_block.__contains__
        allocator.on_evict = self._unregister_block

    # ------------------------------------------------------------------
    # hashing + lookup
    # ------------------------------------------------------------------

    def hash_chain(self, prompt: Sequence[int]) -> List[bytes]:
        """Chained hashes of the prompt's full blocks (one per block the
        prompt completely fills). Computed once per request at submit."""
        arr = np.ascontiguousarray(np.asarray(prompt, np.int32).reshape(-1))
        bs = self.block_size
        out, h = [], self._root
        for i in range(len(arr) // bs):
            h = hashlib.sha256(h + arr[i * bs:(i + 1) * bs].tobytes()).digest()
            out.append(h)
        return out

    def match(self, hashes: Sequence[bytes]) -> List[int]:
        """Longest-prefix match: physical blocks for the leading run of
        registered hashes. Pure lookup — the caller increfs winners (and
        only then is the hit protected from eviction)."""
        blocks = []
        for h in hashes:
            b = self._by_hash.get(h)
            if b is None:
                break
            blocks.append(b)
        return blocks

    def match_len(self, hashes: Sequence[bytes]) -> int:
        """Read-only longest-prefix LENGTH (in blocks) — the router's
        affinity score (`deepspeed_tpu/serving/router.py`). Unlike `match`
        it builds no block list and, like `match`, touches no refcounts and
        moves nothing on the reclaimable LRU, so scoring N replicas per
        request is free of side effects on every cache it probes."""
        n = 0
        for h in hashes:
            if h not in self._by_hash:
                break
            n += 1
        return n

    # ------------------------------------------------------------------
    # registration / eviction
    # ------------------------------------------------------------------

    def register(self, h: bytes, block: int) -> bool:
        """Announce that `block` now holds the fully written KV content
        named by `h`. First writer wins: if another block already carries
        this hash (two requests with the same prefix admitted before either
        registered), the newcomer stays uncached and frees normally."""
        if h in self._by_hash or block in self._by_block:
            return False
        self._by_hash[h] = block
        self._by_block[block] = h
        return True

    def _unregister_block(self, block: int):
        h = self._by_block.pop(block, None)
        if h is not None:
            self._by_hash.pop(h, None)

    @property
    def num_cached(self) -> int:
        """Registered blocks (live shared + reclaimable)."""
        return len(self._by_block)

    # ------------------------------------------------------------------
    # audit surface (inference/audit.py, bin/dstpu_audit)
    # ------------------------------------------------------------------

    def snapshot(self) -> Dict[str, int]:
        """Portable hex-keyed copy of the forward registration map
        (hash -> physical block) — the audit-state interchange form the
        pool auditor checks I3 (hash-chain liveness + bijection) against.
        All-JSON types, so a flight dump embeds it directly."""
        return {h.hex(): int(b) for h, b in self._by_hash.items()}

    def reverse_snapshot(self) -> Dict[int, str]:
        """Portable copy of the reverse map (block -> hash hex). The
        auditor cross-checks it against `snapshot()`: the two maps must be
        inverse bijections, or a future hit would serve another prefix's
        KV content."""
        return {int(b): h.hex() for b, h in self._by_block.items()}
