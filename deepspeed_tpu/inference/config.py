"""Inference config — analog of `DeepSpeedInferenceConfig` (`inference/config.py`)."""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from deepspeed_tpu.config.core import ConfigModel, TelemetryConfig


@dataclass
class QuantConfig(ConfigModel):
    enabled: bool = False
    bits: int = 8
    group_size: int = 64


@dataclass
class TensorParallelConfig(ConfigModel):
    tp_size: int = 1
    enabled: bool = True


@dataclass
class SpecDecodeConfig(ConfigModel):
    """Speculative decoding over the paged pool (`inference/spec_decode.py`).

    When enabled, the serving scheduler replaces the per-token decode step
    (and the decode window) with a draft+verify loop: a DRAFTER proposes
    `draft_k` tokens per active slot, one fixed-shape jitted VERIFY call
    scores all of them for all `max_slots` at once (the chunked-prefill
    machinery at positions pos..pos+k), and the longest agreeing prefix is
    accepted plus one bonus token from the first disagreeing logit row —
    1..k+1 tokens per model step instead of exactly 1. Rejection is an O(1)
    rewind of the slot's length cursor: blocks past it are overwritten by
    later writes, never freed or reallocated, and the block table is
    untouched. Greedy output is token-identical to non-speculative serving.
    """
    drafter: str = "off"          # "off" | "ngram" | "model". "ngram" is the
                                  # model-free prompt-lookup drafter (match
                                  # the newest generated tokens against the
                                  # slot's own prompt+output history, propose
                                  # the continuation — ideal for the cache-
                                  # heavy shared-prefix workloads prefix
                                  # caching serves); "model" drives a second,
                                  # smaller DecodeModelSpec passed to
                                  # `engine.serving(draft_spec=...)`
    draft_k: int = 4              # draft tokens proposed+verified per step —
                                  # a compile-stability knob: pins the verify
                                  # program's [max_slots, draft_k+1] shape.
                                  # Size against the measured acceptance
                                  # rate: the verify step always pays k+1
                                  # positions of compute, accepted or not
    ngram_max: int = 4            # longest suffix n-gram the prompt-lookup
    ngram_min: int = 1            # drafter tries to match (tried max..min)


@dataclass
class ServingQuantizationConfig(ConfigModel):
    """Quantized serving (`inference/quantization.py`, the int8 paged pool).

    Decode is HBM-bandwidth-bound at serving batch sizes: every step reads
    the whole weight set plus the live KV prefix. Quantizing the RESIDENT
    bytes therefore buys two things at once — capacity (an int8 pool holds
    ~2x the blocks per HBM byte: more concurrent users, a bigger prefix
    cache; int8/int4 weights let one chip hold a 2-4x-over-bf16 model, the
    ZeRO-Inference direction) and tokens/s (the decode step streams half
    the bytes). Both knobs change ONLY what is stored: K/V quantize at
    cache-write time and dequantize inside the paged kernel's KV-grid walk
    (or the gather fallback), weights dequantize inside the jitted step
    where XLA fuses the dequant into the consuming matmul — program shapes,
    and therefore the one-compile-per-program contract, are untouched.
    """
    kv_cache_dtype: str = ""      # "" = inherit the engine's kv_cache_dtype;
                                  # "bf16"/"bfloat16" | "int8". int8 stores
                                  # the pool as symmetric per-group int8 with
                                  # f32 scales riding the same physical-block
                                  # axis (scales travel with blocks through
                                  # prefix sharing / handoff / transplant)
    kv_group_size: int = 0        # elements per K/V scale group along
                                  # head_dim; 0 = head_dim (one scale per
                                  # written vector per head). Must divide
                                  # head_dim; smaller = tighter quant, more
                                  # scale overhead (4/g bytes per element)
    weights: str = "off"          # "off" | "int8" | "int4": pytree-wide
                                  # weight-only quantization at serving-
                                  # engine build (dequantize-on-use view;
                                  # int4 packs two values per byte). Applies
                                  # to the ENGINE's resident params — the
                                  # dense copy is dropped, generate() serves
                                  # the quantized tree too
    weight_group_size: int = 64   # elements per weight scale group (last
                                  # dim); leaves it does not tile stay dense


@dataclass
class DegradationConfig(ConfigModel):
    """Graceful-degradation ladder (`serving/degradation.py`).

    When enabled, a `PressureController` evaluates pool pressure every
    `eval_interval` scheduler syncs — free-block fraction, queue depth,
    and (when telemetry is on) TTFT p99 — and walks an ORDERED ladder of
    service-degrading levels, one rung per evaluation, escalating while
    any signal is over its high watermark and de-escalating one rung only
    after `hold_steps` consecutive calm evaluations (hysteresis: separate
    high/low watermarks + the hold count prevent flapping):

      0 normal · 1 cap draft_k to 1 (spec decode keeps its compiled shape,
      the drafter just proposes less) · 2 disable spec decode (fall back
      to a single-step decode program) · 3 force the 1-step decode window
      (finer retirement granularity frees blocks sooner) · 4 aggressively
      flush the reclaimable prefix-cache blocks (zeroes the replica's
      prefix-affinity pull so the router routes shared-prefix traffic
      elsewhere, and moves demand-eviction work off the admission path) ·
      5 shed queued requests whose priority is below `shed_below_priority`.

    Disabled (default) the controller is never constructed: the hot path,
    the compiled programs, and `compile_stats()` are untouched.
    """
    enabled: bool = False
    eval_interval: int = 4        # scheduler syncs between evaluations
    free_block_low: float = 0.10  # available/capacity below this => pressure
    free_block_high: float = 0.30 # ...and above this counts as calm
    queue_high: int = 16          # engine queue depth over this => pressure
    queue_low: int = 2            # ...and at/below this counts as calm
    ttft_p99_ms: float = 0.0      # TTFT p99 over this => pressure (0 = off;
                                  # needs telemetry for the histogram)
    hold_steps: int = 3           # consecutive calm evals per de-escalation
    shed_below_priority: int = 0  # level 5 sheds queued requests with
                                  # Request.priority strictly below this
    headroom_low: float = 0.0     # mem/headroom_frac (telemetry/memscope.py
                                  # ledger) below this => pressure (0 = off;
                                  # needs telemetry.memscope + a known HBM
                                  # capacity — the signal is omitted when
                                  # either is missing)
    headroom_high: float = 0.0    # ...and at/above this counts as calm
                                  # (clamped up to headroom_low)


@dataclass
class ServingConfig(ConfigModel):
    """Continuous-batching serving engine (`inference/scheduler.py`).

    The serving layer runs a FIXED-shape decode step over `max_slots`
    sequence slots against one engine-owned paged KV pool; requests are
    admitted into freed slots every step and retire (freeing their blocks)
    the moment they emit EOS. All shape knobs here are compile-stability
    knobs: each one pins a jitted program's shape for the engine's lifetime.
    """
    max_slots: int = 8            # decode batch slots — THE decode step shape
    max_context: int = 0          # per-sequence cap (prompt + generated);
                                  # 0 = the engine's max_out_tokens. Sets the
                                  # block-table width nb = ceil(max_context /
                                  # kv_block_size)
    num_kv_blocks: int = 0        # physical pool blocks (incl. the reserved
                                  # trash block 0); 0 = worst case:
                                  # max_slots * nb + 1 (no admission can ever
                                  # starve); smaller values oversubscribe the
                                  # pool and lean on admission backpressure
    prefill_chunk: int = 0        # chunked-prefill bucket: prompts process in
                                  # fixed [1, chunk] slices (one compile
                                  # total); 0 = kv_block_size
    prefill_chunks_per_step: int = 1  # prefill work interleaved per decode
                                  # step — bounds how long an arriving prompt
                                  # can stall the running batch
    decode_steps_per_sync: int = 1  # decode WINDOW: tokens decoded per
                                  # scheduler sync, inside one jitted
                                  # lax.scan (vLLM's multi-step scheduling).
                                  # >1 amortizes per-call dispatch + the
                                  # host roundtrip over K tokens — the lever
                                  # on dispatch-latency-bound backends — at
                                  # the cost of K-step retirement/admission
                                  # granularity (a sequence finishing
                                  # mid-window wastes the window's tail)
    enable_prefix_caching: bool = False  # automatic prefix caching
                                  # (inference/prefix_cache.py): full prompt
                                  # blocks are content-hashed and reused
                                  # across requests — a shared system prompt
                                  # prefills once. Token-identical greedy
                                  # output, zero new compiles; costs only
                                  # host-side hashing at submit
    spec_decode: SpecDecodeConfig = field(default_factory=SpecDecodeConfig)
                                  # speculative decoding (drafter/draft_k —
                                  # see SpecDecodeConfig); replaces the
                                  # decode window when on
    audit_interval: int = 0       # run the KV-pool invariant auditor
                                  # (inference/audit.py) every N scheduler
                                  # syncs (0 = on-demand/shutdown only).
                                  # Host-side reads only — never touches the
                                  # compiled programs
    audit_action: str = "repair"  # on a failed audit, after the flight-
                                  # recorder dump: "repair" rebuilds the
                                  # free list/refcounts from the slot tables
                                  # (ground truth) and keeps serving;
                                  # "raise" raises PoolCorruptionError out
                                  # of step() so the serving router
                                  # quarantines the replica (PR 6 failover)
    degradation: DegradationConfig = field(default_factory=DegradationConfig)
                                  # graceful-degradation ladder under
                                  # sustained pressure (see
                                  # DegradationConfig); off by default
    quantization: ServingQuantizationConfig = field(
        default_factory=ServingQuantizationConfig)
                                  # quantized serving: int8 KV pool +
                                  # weight-only int8/int4 (see
                                  # ServingQuantizationConfig); off by
                                  # default — bf16 pool, dense weights
    prefix_cache_policy: str = "lru"  # what happens to a cached block when
                                  # its last reader retires: "lru" parks it
                                  # on the reclaimable list (evicted oldest-
                                  # first only when an alloc would fail —
                                  # caching never reduces usable capacity);
                                  # "none" frees + unregisters immediately
                                  # (only concurrently-active sharing)


@dataclass
class TpuInferenceConfig(ConfigModel):
    dtype: str = "bfloat16"
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    max_out_tokens: int = 1024
    max_tokens: Optional[int] = None
    min_out_tokens: int = 1
    replace_with_kernel_inject: bool = True   # on TPU: use pallas decode kernels
    quant: QuantConfig = field(default_factory=QuantConfig)
    checkpoint: Optional[str] = None
    max_batch_size: int = 8
    # decoding
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    greedy: bool = True
    eos_token_id: Optional[int] = None
    # moe inference
    moe: Dict[str, Any] = field(default_factory=dict)
    # kv cache
    kv_cache_dtype: str = "bfloat16"
    # blocked KV-cache layout: cache length is rounded up to a whole number
    # of kv_block_size-token blocks, the unit the streaming decode kernel
    # (`ops/pallas/decode_attention.py`) DMAs from HBM — per decode step it
    # touches only the blocks covering each row's live prefix, so serving
    # contexts are bounded by HBM, not VMEM. 512 is the measured
    # bandwidth-floor block on v5e; 0 disables the rounding (legacy exact-
    # length caches; the kernel then pays a runtime pad-to-block copy).
    kv_block_size: int = 512
    # continuous-batching serving engine knobs (InferenceEngine.serving())
    serving: ServingConfig = field(default_factory=ServingConfig)
    # unified telemetry (deepspeed_tpu/telemetry/): TTFT/TPOT/queue-wait
    # histograms + pool gauges on the serving scheduler; disabled by default
    # (zero overhead, no files written)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    # ZeRO-Inference parameter spill (reference ds_config "zero_optimization"
    # with stage-3 param offload): {"offload_param": {"device": "cpu"|"nvme",
    # "nvme_path": ..., "lookahead": 1, "staging": 3}}
    zero: Dict[str, Any] = field(default_factory=dict)

    _LEGACY_DTYPES = {"fp16": "float16", "half": "float16", "bf16": "bfloat16",
                      "fp32": "float32", "float": "float32",
                      "torch.float16": "float16", "torch.bfloat16": "bfloat16",
                      "torch.float32": "float32"}

    @classmethod
    def from_dict(cls, d, path=""):
        """Accept the reference's legacy kwargs (`inference/config.py`
        validators): `mp_size` is the deprecated tensor_parallel degree —
        silently ignoring it would serve tp=1 — plus torch-style dtype
        spellings and the retired `replace_method` knob."""
        from deepspeed_tpu.config.core import maybe_unwrap_tuned
        d = dict(maybe_unwrap_tuned(d or {}))
        if "mp_size" in d:
            tp = d.pop("mp_size")
            tpc = d.setdefault("tensor_parallel", {})
            if isinstance(tpc, dict):
                tpc.setdefault("tp_size", int(tp))
        d.pop("replace_method", None)  # deprecated no-op in the reference too
        dt = d.get("dtype")
        if dt is not None and not isinstance(dt, str):
            dt = str(dt)
        if isinstance(dt, str):
            d["dtype"] = cls._LEGACY_DTYPES.get(dt, dt)
        return super().from_dict(d, path=path)
