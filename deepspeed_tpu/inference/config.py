"""Inference config — analog of `DeepSpeedInferenceConfig` (`inference/config.py`)."""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from deepspeed_tpu.config.core import ConfigModel


@dataclass
class QuantConfig(ConfigModel):
    enabled: bool = False
    bits: int = 8
    group_size: int = 64


@dataclass
class TensorParallelConfig(ConfigModel):
    tp_size: int = 1
    enabled: bool = True


@dataclass
class TpuInferenceConfig(ConfigModel):
    dtype: str = "bfloat16"
    tensor_parallel: TensorParallelConfig = field(default_factory=TensorParallelConfig)
    max_out_tokens: int = 1024
    max_tokens: Optional[int] = None
    min_out_tokens: int = 1
    replace_with_kernel_inject: bool = True   # on TPU: use pallas decode kernels
    quant: QuantConfig = field(default_factory=QuantConfig)
    checkpoint: Optional[str] = None
    max_batch_size: int = 8
    # decoding
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    greedy: bool = True
    eos_token_id: Optional[int] = None
    # moe inference
    moe: Dict[str, Any] = field(default_factory=dict)
    # kv cache
    kv_cache_dtype: str = "bfloat16"
    # blocked KV-cache layout: cache length is rounded up to a whole number
    # of kv_block_size-token blocks, the unit the streaming decode kernel
    # (`ops/pallas/decode_attention.py`) DMAs from HBM — per decode step it
    # touches only the blocks covering each row's live prefix, so serving
    # contexts are bounded by HBM, not VMEM. 512 is the measured
    # bandwidth-floor block on v5e; 0 disables the rounding (legacy exact-
    # length caches; the kernel then pays a runtime pad-to-block copy).
    kv_block_size: int = 512
    # ZeRO-Inference parameter spill (reference ds_config "zero_optimization"
    # with stage-3 param offload): {"offload_param": {"device": "cpu"|"nvme",
    # "nvme_path": ..., "lookahead": 1, "staging": 3}}
    zero: Dict[str, Any] = field(default_factory=dict)

    _LEGACY_DTYPES = {"fp16": "float16", "half": "float16", "bf16": "bfloat16",
                      "fp32": "float32", "float": "float32",
                      "torch.float16": "float16", "torch.bfloat16": "bfloat16",
                      "torch.float32": "float32"}

    @classmethod
    def from_dict(cls, d, path=""):
        """Accept the reference's legacy kwargs (`inference/config.py`
        validators): `mp_size` is the deprecated tensor_parallel degree —
        silently ignoring it would serve tp=1 — plus torch-style dtype
        spellings and the retired `replace_method` knob."""
        d = dict(d or {})
        if "mp_size" in d:
            tp = d.pop("mp_size")
            tpc = d.setdefault("tensor_parallel", {})
            if isinstance(tpc, dict):
                tpc.setdefault("tp_size", int(tp))
        d.pop("replace_method", None)  # deprecated no-op in the reference too
        dt = d.get("dtype")
        if dt is not None and not isinstance(dt, str):
            dt = str(dt)
        if isinstance(dt, str):
            d["dtype"] = cls._LEGACY_DTYPES.get(dt, dt)
        return super().from_dict(d, path=path)
