"""HF checkpoint adapters — the reference's injection-container role.

Reference: `module_inject/containers/*` (gpt2.py, llama.py, llama2.py, opt.py…)
map HuggingFace module trees onto fused inference blocks, transposing/fusing
weights per architecture; `module_inject/load_checkpoint.py` does the state-dict
walking. Here the same job is a pure weight-layout transform: HF state dict →
our stacked-block pytree (models/gpt.py layout), after which the whole zoo
(training engine, inference engine, TP specs, Pallas kernels) applies unchanged.

Covered: GPT-2 (Conv1D [in,out] weights, learned positions, fused c_attn) and
LLaMA 1/2/3 (Linear [out,in] weights → transpose; separate q/k/v → fused;
HF "rotate-half" RoPE row order → interleaved, the inverse of the permutation in
HF's `convert_llama_weights_to_hf.py`). Each adapter returns (GPTConfig, params)
so callers can build either a training ModelSpec or a DecodeModelSpec.
"""

import dataclasses

import numpy as np
import jax.numpy as jnp

from deepspeed_tpu.models.gpt import GPTConfig
from deepspeed_tpu.utils.logging import logger


def _t(x):
    """torch tensor / numpy → numpy fp32."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, np.float32)


def _state_dict(model_or_sd):
    if hasattr(model_or_sd, "state_dict"):
        return {k: _t(v) for k, v in model_or_sd.state_dict().items()}
    return {k: _t(v) for k, v in model_or_sd.items()}


def _stack(layers):
    """list of per-layer dicts → stacked dict with leading layer dim."""
    out = {}
    for key in layers[0]:
        out[key] = jnp.asarray(np.stack([l[key] for l in layers]))
    return out


# ----------------------------------------------------------------------
# GPT-2
# ----------------------------------------------------------------------


def from_hf_gpt2(model_or_sd, hf_config=None, dtype=jnp.float32):
    """GPT2LMHeadModel → (GPTConfig, params). Conv1D stores [in, out] — our
    convention already; no transposes (reference container: `containers/gpt2.py`)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    pre = "transformer." if "transformer.wte.weight" in sd else ""

    n_layer = hf_config.n_layer if hf_config else \
        1 + max(int(k.split(".")[1 if not pre else 2]) for k in sd if ".h." in "." + k)
    cfg = GPTConfig(
        vocab_size=sd[f"{pre}wte.weight"].shape[0],
        n_layer=n_layer,
        n_head=hf_config.n_head if hf_config else 12,
        d_model=sd[f"{pre}wte.weight"].shape[1],
        max_seq_len=sd[f"{pre}wpe.weight"].shape[0],
        norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5) or 1e-5),
        use_rotary=False, use_swiglu=False, use_rmsnorm=False,
        tie_embeddings=True, dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"{pre}h.{i}."
        layers.append({
            "ln1_scale": sd[b + "ln_1.weight"],
            "ln1_bias": sd[b + "ln_1.bias"],
            "attn_qkv_w": sd[b + "attn.c_attn.weight"],     # [D, 3D], Conv1D
            "attn_qkv_b": sd[b + "attn.c_attn.bias"],
            "attn_out_w": sd[b + "attn.c_proj.weight"],
            "attn_out_b": sd[b + "attn.c_proj.bias"],
            "ln2_scale": sd[b + "ln_2.weight"],
            "ln2_bias": sd[b + "ln_2.bias"],
            "mlp_up_w": sd[b + "mlp.c_fc.weight"],
            "mlp_up_b": sd[b + "mlp.c_fc.bias"],
            "mlp_down_w": sd[b + "mlp.c_proj.weight"],
            "mlp_out_b": sd[b + "mlp.c_proj.bias"],
        })
    params = {
        "wte": jnp.asarray(sd[f"{pre}wte.weight"], dtype),
        "wpe": jnp.asarray(sd[f"{pre}wpe.weight"], dtype),
        "blocks": {k: v.astype(dtype) for k, v in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd[f"{pre}ln_f.weight"], dtype),
        "lnf_bias": jnp.asarray(sd[f"{pre}ln_f.bias"], dtype),
    }
    logger.info(f"adapted HF GPT-2: {cfg.n_layer}L d={cfg.d_model} vocab={cfg.vocab_size}")
    return cfg, params


# ----------------------------------------------------------------------
# LLaMA
# ----------------------------------------------------------------------


def _unpermute_rope_rows(w, n_heads, head_dim, rotary_dims=None):
    """HF rotate-half row order → interleaved order, per head.

    HF applies RoPE as rotate_half over contiguous halves of the (first
    `rotary_dims` of the) head dim; our `_rope` (models/gpt.py) rotates
    interleaved pairs. Reorder the rows so pair (i, i+rd/2) becomes (2i, 2i+1);
    rows past `rotary_dims` (NeoX rotary_pct < 1) stay in place.
    w: [n_heads*head_dim, in_dim] (torch Linear layout).
    """
    H, hd = n_heads, head_dim
    rd = rotary_dims if rotary_dims is not None else hd
    w = w.reshape(H, hd, -1)
    rot, keep = w[:, :rd], w[:, rd:]
    rot = rot.reshape(H, 2, rd // 2, -1)     # [H, {half0,half1}, rd/2, in]
    rot = np.transpose(rot, (0, 2, 1, 3))    # interleave the halves
    rot = rot.reshape(H, rd, -1)
    return np.concatenate([rot, keep], axis=1).reshape(H * hd, -1)


def _split_fused_qkv_per_head(w, n_heads, head_dim):
    """BLOOM/NeoX fused query_key_value stores [H, (q,k,v), hd] interleaved per
    head — split into contiguous q, k, v of [H*hd, in_dim]."""
    in_dim = w.shape[-1] if w.ndim == 2 else 1
    w = w.reshape(n_heads, 3, head_dim, -1)
    q, k, v = w[:, 0], w[:, 1], w[:, 2]
    out = lambda t: t.reshape(n_heads * head_dim, in_dim) if in_dim > 1 \
        else t.reshape(n_heads * head_dim)
    return out(q), out(k), out(v)


def from_hf_llama(model_or_sd, hf_config=None, dtype=jnp.float32):
    """LlamaForCausalLM → (GPTConfig, params). Transposes Linear [out,in]→[in,out],
    fuses q/k/v, un-permutes RoPE rows (reference container: `containers/llama.py`)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None, "from_hf_llama needs the HF config (head counts)"

    H = hf_config.num_attention_heads
    Hkv = getattr(hf_config, "num_key_value_heads", H) or H
    D = hf_config.hidden_size
    hd = D // H
    cfg = GPTConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=H, n_kv_head=Hkv, d_model=D,
        d_ff=hf_config.intermediate_size,
        max_seq_len=getattr(hf_config, "max_position_embeddings", 4096),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(getattr(hf_config, "rms_norm_eps", 1e-6)),
        use_rotary=True, use_swiglu=True, use_rmsnorm=True,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"model.layers.{i}."
        q = _unpermute_rope_rows(sd[b + "self_attn.q_proj.weight"], H, hd)
        k = _unpermute_rope_rows(sd[b + "self_attn.k_proj.weight"], Hkv, hd)
        v = sd[b + "self_attn.v_proj.weight"]
        qkv = np.concatenate([q, k, v], axis=0).T          # [D, (H+2Hkv)*hd]
        # attention biases: InternLM / LlamaConfig(attention_bias=True); the
        # q/k biases get the same per-head row un-permutation as the weights
        if b + "self_attn.q_proj.bias" in sd:
            qb = _unpermute_rope_rows(sd[b + "self_attn.q_proj.bias"], H, hd).ravel()
            kb = _unpermute_rope_rows(sd[b + "self_attn.k_proj.bias"], Hkv, hd).ravel()
            vb = sd[b + "self_attn.v_proj.bias"]
            qkv_b = np.concatenate([qb, kb, vb])
        else:
            qkv_b = np.zeros(qkv.shape[1], np.float32)
        out_b = sd.get(b + "self_attn.o_proj.bias", np.zeros(D, np.float32))
        layers.append({
            "ln1_scale": sd[b + "input_layernorm.weight"],
            "attn_qkv_w": qkv,
            "attn_qkv_b": qkv_b,
            "attn_out_w": sd[b + "self_attn.o_proj.weight"].T,
            "attn_out_b": out_b,
            "ln2_scale": sd[b + "post_attention_layernorm.weight"],
            "mlp_gate_w": sd[b + "mlp.gate_proj.weight"].T,
            "mlp_up_w": sd[b + "mlp.up_proj.weight"].T,
            "mlp_down_w": sd[b + "mlp.down_proj.weight"].T,
            "mlp_out_b": np.zeros(D, np.float32),
        })
    params = {
        "wte": jnp.asarray(sd["model.embed_tokens.weight"], dtype),
        "blocks": {k: v.astype(dtype) for k, v in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd["model.norm.weight"], dtype),
    }
    if not cfg.tie_embeddings:
        head = sd.get("lm_head.weight", sd["model.embed_tokens.weight"])
        params["lm_head"] = jnp.asarray(head, dtype)
    logger.info(f"adapted HF LLaMA: {cfg.n_layer}L d={cfg.d_model} "
                f"H={H}/{Hkv} vocab={cfg.vocab_size}")
    return cfg, params


# ----------------------------------------------------------------------
# OPT
# ----------------------------------------------------------------------


def from_hf_opt(model_or_sd, hf_config=None, dtype=jnp.float32):
    """OPTForCausalLM → (GPTConfig, params). Pre-LN decoder with ReLU MLP and
    learned positions at a +2 offset — the offset is absorbed by trimming the
    first two position rows (reference container: `containers/opt.py`,
    `fusedqkv_utils.py`)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None
    assert getattr(hf_config, "do_layer_norm_before", True), \
        "post-LN OPT variants (350m) are not supported"
    D = hf_config.hidden_size
    assert getattr(hf_config, "word_embed_proj_dim", D) == D, \
        "OPT word_embed_proj_dim != hidden_size not supported"

    cfg = GPTConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        d_model=D,
        d_ff=hf_config.ffn_dim,
        max_seq_len=hf_config.max_position_embeddings,
        activation="relu",
        use_rotary=False, use_swiglu=False, use_rmsnorm=False,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", True)),
        dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"model.decoder.layers.{i}."
        q = sd[b + "self_attn.q_proj.weight"]
        k = sd[b + "self_attn.k_proj.weight"]
        v = sd[b + "self_attn.v_proj.weight"]
        layers.append({
            "ln1_scale": sd[b + "self_attn_layer_norm.weight"],
            "ln1_bias": sd[b + "self_attn_layer_norm.bias"],
            "attn_qkv_w": np.concatenate([q, k, v], axis=0).T,
            "attn_qkv_b": np.concatenate([sd[b + "self_attn.q_proj.bias"],
                                          sd[b + "self_attn.k_proj.bias"],
                                          sd[b + "self_attn.v_proj.bias"]]),
            "attn_out_w": sd[b + "self_attn.out_proj.weight"].T,
            "attn_out_b": sd[b + "self_attn.out_proj.bias"],
            "ln2_scale": sd[b + "final_layer_norm.weight"],
            "ln2_bias": sd[b + "final_layer_norm.bias"],
            "mlp_up_w": sd[b + "fc1.weight"].T,
            "mlp_up_b": sd[b + "fc1.bias"],
            "mlp_down_w": sd[b + "fc2.weight"].T,
            "mlp_out_b": sd[b + "fc2.bias"],
        })
    params = {
        "wte": jnp.asarray(sd["model.decoder.embed_tokens.weight"], dtype),
        # OPTLearnedPositionalEmbedding indexes at position+2
        "wpe": jnp.asarray(sd["model.decoder.embed_positions.weight"][2:], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd["model.decoder.final_layer_norm.weight"], dtype),
        "lnf_bias": jnp.asarray(sd["model.decoder.final_layer_norm.bias"], dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(sd["lm_head.weight"], dtype)
    logger.info(f"adapted HF OPT: {cfg.n_layer}L d={cfg.d_model} vocab={cfg.vocab_size}")
    return cfg, params


# ----------------------------------------------------------------------
# BLOOM
# ----------------------------------------------------------------------


def from_hf_bloom(model_or_sd, hf_config=None, dtype=jnp.float32):
    """BloomForCausalLM → (GPTConfig, params). Alibi attention (no position
    embedding), word-embedding LayerNorm, per-head-interleaved fused qkv
    (reference container: `containers/bloom.py`)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None
    H = hf_config.n_head
    D = hf_config.hidden_size
    hd = D // H

    cfg = GPTConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.n_layer,
        n_head=H, d_model=D, d_ff=4 * D,
        max_seq_len=getattr(hf_config, "seq_length", 2048) or 2048,
        norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
        use_alibi=True, use_emb_ln=True,
        use_rotary=False, use_swiglu=False, use_rmsnorm=False,
        tie_embeddings=True, dtype=dtype, remat=False)

    pre = "transformer." if "transformer.word_embeddings.weight" in sd else ""
    layers = []
    for i in range(cfg.n_layer):
        b = f"{pre}h.{i}."
        qw, kw, vw = _split_fused_qkv_per_head(
            sd[b + "self_attention.query_key_value.weight"], H, hd)
        qb, kb, vb = _split_fused_qkv_per_head(
            sd[b + "self_attention.query_key_value.bias"], H, hd)
        layers.append({
            "ln1_scale": sd[b + "input_layernorm.weight"],
            "ln1_bias": sd[b + "input_layernorm.bias"],
            "attn_qkv_w": np.concatenate([qw, kw, vw], axis=0).T,
            "attn_qkv_b": np.concatenate([qb, kb, vb]),
            "attn_out_w": sd[b + "self_attention.dense.weight"].T,
            "attn_out_b": sd[b + "self_attention.dense.bias"],
            "ln2_scale": sd[b + "post_attention_layernorm.weight"],
            "ln2_bias": sd[b + "post_attention_layernorm.bias"],
            "mlp_up_w": sd[b + "mlp.dense_h_to_4h.weight"].T,
            "mlp_up_b": sd[b + "mlp.dense_h_to_4h.bias"],
            "mlp_down_w": sd[b + "mlp.dense_4h_to_h.weight"].T,
            "mlp_out_b": sd[b + "mlp.dense_4h_to_h.bias"],
        })
    params = {
        "wte": jnp.asarray(sd[f"{pre}word_embeddings.weight"], dtype),
        "emb_ln_scale": jnp.asarray(sd[f"{pre}word_embeddings_layernorm.weight"], dtype),
        "emb_ln_bias": jnp.asarray(sd[f"{pre}word_embeddings_layernorm.bias"], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd[f"{pre}ln_f.weight"], dtype),
        "lnf_bias": jnp.asarray(sd[f"{pre}ln_f.bias"], dtype),
    }
    logger.info(f"adapted HF BLOOM: {cfg.n_layer}L d={cfg.d_model} alibi "
                f"vocab={cfg.vocab_size}")
    return cfg, params


# ----------------------------------------------------------------------
# GPT-NeoX / GPT-J
# ----------------------------------------------------------------------


def from_hf_gpt_neox(model_or_sd, hf_config=None, dtype=jnp.float32):
    """GPTNeoXForCausalLM → (GPTConfig, params). Partial rotary (rotary_pct),
    parallel residual, per-head-interleaved fused qkv, untied embed_out
    (reference container: `containers/gptneox.py`)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None
    H = hf_config.num_attention_heads
    D = hf_config.hidden_size
    hd = D // H
    rd = int(hf_config.rotary_pct * hd) // 2 * 2

    cfg = GPTConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=H, d_model=D, d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        norm_eps=float(getattr(hf_config, "layer_norm_eps", 1e-5)),
        use_rotary=True, rotary_pct=float(hf_config.rotary_pct),
        rope_theta=float(getattr(hf_config, "rotary_emb_base", 10000.0)),
        parallel_residual=bool(getattr(hf_config, "use_parallel_residual", True)),
        use_swiglu=False, use_rmsnorm=False,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"gpt_neox.layers.{i}."
        qw, kw, vw = _split_fused_qkv_per_head(
            sd[b + "attention.query_key_value.weight"], H, hd)
        qb, kb, vb = _split_fused_qkv_per_head(
            sd[b + "attention.query_key_value.bias"], H, hd)
        qw = _unpermute_rope_rows(qw, H, hd, rd)
        kw = _unpermute_rope_rows(kw, H, hd, rd)
        qb = _unpermute_rope_rows(qb[:, None], H, hd, rd)[:, 0]
        kb = _unpermute_rope_rows(kb[:, None], H, hd, rd)[:, 0]
        layers.append({
            "ln1_scale": sd[b + "input_layernorm.weight"],
            "ln1_bias": sd[b + "input_layernorm.bias"],
            "attn_qkv_w": np.concatenate([qw, kw, vw], axis=0).T,
            "attn_qkv_b": np.concatenate([qb, kb, vb]),
            "attn_out_w": sd[b + "attention.dense.weight"].T,
            "attn_out_b": sd[b + "attention.dense.bias"],
            "ln2_scale": sd[b + "post_attention_layernorm.weight"],
            "ln2_bias": sd[b + "post_attention_layernorm.bias"],
            "mlp_up_w": sd[b + "mlp.dense_h_to_4h.weight"].T,
            "mlp_up_b": sd[b + "mlp.dense_h_to_4h.bias"],
            "mlp_down_w": sd[b + "mlp.dense_4h_to_h.weight"].T,
            "mlp_out_b": sd[b + "mlp.dense_4h_to_h.bias"],
        })
    params = {
        "wte": jnp.asarray(sd["gpt_neox.embed_in.weight"], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd["gpt_neox.final_layer_norm.weight"], dtype),
        "lnf_bias": jnp.asarray(sd["gpt_neox.final_layer_norm.bias"], dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(sd["embed_out.weight"], dtype)
    logger.info(f"adapted HF GPT-NeoX: {cfg.n_layer}L d={cfg.d_model} "
                f"rot%={cfg.rotary_pct} vocab={cfg.vocab_size}")
    return cfg, params


def from_hf_gptj(model_or_sd, hf_config=None, dtype=jnp.float32):
    """GPTJForCausalLM → (GPTConfig, params). Natively-interleaved rotary over
    `rotary_dim`, single-LN parallel residual (ln2 := copy of ln1), biasless
    attention projections, biased LM head (reference container:
    `containers/gptj.py`)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None
    H = hf_config.n_head
    D = hf_config.n_embd
    hd = D // H
    rd = int(getattr(hf_config, "rotary_dim", hd) or hd)

    cfg = GPTConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.n_layer,
        n_head=H, d_model=D,
        d_ff=getattr(hf_config, "n_inner", None) or 4 * D,
        max_seq_len=hf_config.n_positions,
        norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
        use_rotary=True, rotary_pct=rd / hd,
        parallel_residual=True,
        use_swiglu=False, use_rmsnorm=False,
        tie_embeddings=False, dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"transformer.h.{i}."
        q = sd[b + "attn.q_proj.weight"]   # GPT-J rope is already interleaved
        k = sd[b + "attn.k_proj.weight"]
        v = sd[b + "attn.v_proj.weight"]
        ln_s, ln_b = sd[b + "ln_1.weight"], sd[b + "ln_1.bias"]
        layers.append({
            "ln1_scale": ln_s,
            "ln1_bias": ln_b,
            # single-LN parallel residual: mlp reads the SAME normed input
            "ln2_scale": ln_s.copy(),
            "ln2_bias": ln_b.copy(),
            "attn_qkv_w": np.concatenate([q, k, v], axis=0).T,
            "attn_qkv_b": np.zeros(3 * D, np.float32),
            "attn_out_w": sd[b + "attn.out_proj.weight"].T,
            "attn_out_b": np.zeros(D, np.float32),
            "mlp_up_w": sd[b + "mlp.fc_in.weight"].T,
            "mlp_up_b": sd[b + "mlp.fc_in.bias"],
            "mlp_down_w": sd[b + "mlp.fc_out.weight"].T,
            "mlp_out_b": sd[b + "mlp.fc_out.bias"],
        })
    params = {
        "wte": jnp.asarray(sd["transformer.wte.weight"], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"], dtype),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"], dtype),
        "lm_head": jnp.asarray(sd["lm_head.weight"], dtype),
    }
    if "lm_head.bias" in sd:
        params["lm_head_bias"] = jnp.asarray(sd["lm_head.bias"], dtype)
    logger.info(f"adapted HF GPT-J: {cfg.n_layer}L d={cfg.d_model} rd={rd}")
    return cfg, params


def from_hf_gpt_neo(model_or_sd, hf_config=None, dtype=jnp.float32):
    """GPTNeoForCausalLM → (GPTConfig, params). Alternating global/local
    attention (window 256), UNSCALED attention scores, biasless q/k/v Linear
    layers, learned positions (reference container: `containers/gptneo.py`)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None
    D = hf_config.hidden_size
    H = hf_config.num_heads
    layer_types = tuple(hf_config.attention_layers)  # expanded per-layer list

    cfg = GPTConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.num_layers,
        n_head=H, d_model=D,
        d_ff=getattr(hf_config, "intermediate_size", None) or 4 * D,
        max_seq_len=hf_config.max_position_embeddings,
        norm_eps=float(getattr(hf_config, "layer_norm_epsilon", 1e-5)),
        sliding_window=int(getattr(hf_config, "window_size", 256)),
        attn_layer_types=layer_types,
        scale_attn=False,                  # GPT-Neo does not scale by 1/sqrt(hd)
        use_rotary=False, use_swiglu=False, use_rmsnorm=False,
        tie_embeddings=True, dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"transformer.h.{i}."
        q = sd[b + "attn.attention.q_proj.weight"]
        k = sd[b + "attn.attention.k_proj.weight"]
        v = sd[b + "attn.attention.v_proj.weight"]
        layers.append({
            "ln1_scale": sd[b + "ln_1.weight"],
            "ln1_bias": sd[b + "ln_1.bias"],
            "attn_qkv_w": np.concatenate([q, k, v], axis=0).T,
            "attn_qkv_b": np.zeros(3 * D, np.float32),  # q/k/v are biasless
            "attn_out_w": sd[b + "attn.attention.out_proj.weight"].T,
            "attn_out_b": sd[b + "attn.attention.out_proj.bias"],
            "ln2_scale": sd[b + "ln_2.weight"],
            "ln2_bias": sd[b + "ln_2.bias"],
            "mlp_up_w": sd[b + "mlp.c_fc.weight"].T,
            "mlp_up_b": sd[b + "mlp.c_fc.bias"],
            "mlp_down_w": sd[b + "mlp.c_proj.weight"].T,
            "mlp_out_b": sd[b + "mlp.c_proj.bias"],
        })
    params = {
        "wte": jnp.asarray(sd["transformer.wte.weight"], dtype),
        "wpe": jnp.asarray(sd["transformer.wpe.weight"], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd["transformer.ln_f.weight"], dtype),
        "lnf_bias": jnp.asarray(sd["transformer.ln_f.bias"], dtype),
    }
    logger.info(f"adapted HF GPT-Neo: {cfg.n_layer}L d={D} "
                f"types={layer_types[:4]}... window={cfg.sliding_window}")
    return cfg, params


# ----------------------------------------------------------------------
# Mistral
# ----------------------------------------------------------------------


def from_hf_mistral(model_or_sd, hf_config=None, dtype=jnp.float32):
    """MistralForCausalLM → (GPTConfig, params). LLaMA layout + sliding-window
    attention (reference AutoTP serves mistral via the llama shard plan)."""
    import dataclasses as _dc
    cfg, params = from_hf_llama(model_or_sd, hf_config, dtype=dtype)
    hf_config = hf_config or getattr(model_or_sd, "config", None)
    window = getattr(hf_config, "sliding_window", None)
    if window:
        cfg = _dc.replace(cfg, sliding_window=int(window))
    return cfg, params


# ----------------------------------------------------------------------
# BERT
# ----------------------------------------------------------------------


def from_hf_bert(model_or_sd, hf_config=None, dtype=jnp.float32):
    """BertForMaskedLM → (BertConfig, params) for models/bert.py
    (reference container: `containers/bert.py`). Linear [out,in] → transpose;
    q/k/v fused; post-LN layout."""
    from deepspeed_tpu.models.bert import BertConfig
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None

    D = hf_config.hidden_size
    cfg = BertConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.num_hidden_layers,
        n_head=hf_config.num_attention_heads,
        d_model=D,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        norm_eps=float(hf_config.layer_norm_eps),
        pre_layer_norm=False, dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"bert.encoder.layer.{i}."
        q = sd[b + "attention.self.query.weight"]
        k = sd[b + "attention.self.key.weight"]
        v = sd[b + "attention.self.value.weight"]
        qb = sd[b + "attention.self.query.bias"]
        kb = sd[b + "attention.self.key.bias"]
        vb = sd[b + "attention.self.value.bias"]
        layers.append({
            "attn_qkv_w": np.concatenate([q, k, v], axis=0).T,
            "attn_qkv_b": np.concatenate([qb, kb, vb]),
            "attn_out_w": sd[b + "attention.output.dense.weight"].T,
            "attn_out_b": sd[b + "attention.output.dense.bias"],
            "ln1_scale": sd[b + "attention.output.LayerNorm.weight"],
            "ln1_bias": sd[b + "attention.output.LayerNorm.bias"],
            "mlp_up_w": sd[b + "intermediate.dense.weight"].T,
            "mlp_up_b": sd[b + "intermediate.dense.bias"],
            "mlp_down_w": sd[b + "output.dense.weight"].T,
            "mlp_down_b": sd[b + "output.dense.bias"],
            "ln2_scale": sd[b + "output.LayerNorm.weight"],
            "ln2_bias": sd[b + "output.LayerNorm.bias"],
        })
    V = cfg.vocab_size
    params = {
        "word_emb": jnp.asarray(sd["bert.embeddings.word_embeddings.weight"], dtype),
        "pos_emb": jnp.asarray(sd["bert.embeddings.position_embeddings.weight"], dtype),
        "type_emb": jnp.asarray(sd["bert.embeddings.token_type_embeddings.weight"], dtype),
        "emb_ln_scale": jnp.asarray(sd["bert.embeddings.LayerNorm.weight"], dtype),
        "emb_ln_bias": jnp.asarray(sd["bert.embeddings.LayerNorm.bias"], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "mlm_dense_w": jnp.asarray(sd["cls.predictions.transform.dense.weight"].T, dtype),
        "mlm_dense_b": jnp.asarray(sd["cls.predictions.transform.dense.bias"], dtype),
        "mlm_ln_scale": jnp.asarray(sd["cls.predictions.transform.LayerNorm.weight"], dtype),
        "mlm_ln_bias": jnp.asarray(sd["cls.predictions.transform.LayerNorm.bias"], dtype),
        "mlm_bias": jnp.asarray(sd.get("cls.predictions.bias", np.zeros(V)), dtype),
        "pooler_w": jnp.asarray(sd.get("bert.pooler.dense.weight",
                                       np.zeros((D, D))).T, dtype),
        "pooler_b": jnp.asarray(sd.get("bert.pooler.dense.bias", np.zeros(D)), dtype),
    }
    logger.info(f"adapted HF BERT: {cfg.n_layer}L d={cfg.d_model} vocab={V}")
    return cfg, params


def from_hf_internlm(model_or_sd, hf_config=None, dtype=jnp.float32):
    """InternLMForCausalLM → (GPTConfig, params) (reference container:
    `containers/internlm.py`). InternLM is the LLaMA layout with attention
    biases — same key naming (`model.layers.N.self_attn.*`), handled by the
    bias-aware LLaMA conversion."""
    return from_hf_llama(model_or_sd, hf_config=hf_config, dtype=dtype)


def from_hf_distilbert(model_or_sd, hf_config=None, dtype=jnp.float32):
    """DistilBertForMaskedLM → (BertConfig, params) (reference container:
    `containers/distil_bert.py`). Post-LN encoder, no token-type embeddings,
    MLM head tied to the word embeddings."""
    from deepspeed_tpu.models.bert import BertConfig
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None

    D = hf_config.dim
    cfg = BertConfig(
        vocab_size=hf_config.vocab_size,
        n_layer=hf_config.n_layers,
        n_head=hf_config.n_heads,
        d_model=D,
        d_ff=hf_config.hidden_dim,
        max_seq_len=hf_config.max_position_embeddings,
        type_vocab_size=1,                      # distilbert has no segments
        norm_eps=1e-12,
        pre_layer_norm=False, dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"distilbert.transformer.layer.{i}."
        q, k, v = (sd[b + f"attention.{n}_lin.weight"] for n in ("q", "k", "v"))
        qb, kb, vb = (sd[b + f"attention.{n}_lin.bias"] for n in ("q", "k", "v"))
        layers.append({
            "attn_qkv_w": np.concatenate([q, k, v], axis=0).T,
            "attn_qkv_b": np.concatenate([qb, kb, vb]),
            "attn_out_w": sd[b + "attention.out_lin.weight"].T,
            "attn_out_b": sd[b + "attention.out_lin.bias"],
            "ln1_scale": sd[b + "sa_layer_norm.weight"],
            "ln1_bias": sd[b + "sa_layer_norm.bias"],
            "mlp_up_w": sd[b + "ffn.lin1.weight"].T,
            "mlp_up_b": sd[b + "ffn.lin1.bias"],
            "mlp_down_w": sd[b + "ffn.lin2.weight"].T,
            "mlp_down_b": sd[b + "ffn.lin2.bias"],
            "ln2_scale": sd[b + "output_layer_norm.weight"],
            "ln2_bias": sd[b + "output_layer_norm.bias"],
        })
    V = cfg.vocab_size
    params = {
        "word_emb": jnp.asarray(sd["distilbert.embeddings.word_embeddings.weight"], dtype),
        "pos_emb": jnp.asarray(sd["distilbert.embeddings.position_embeddings.weight"], dtype),
        "type_emb": jnp.zeros((1, D), dtype),
        "emb_ln_scale": jnp.asarray(sd["distilbert.embeddings.LayerNorm.weight"], dtype),
        "emb_ln_bias": jnp.asarray(sd["distilbert.embeddings.LayerNorm.bias"], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "mlm_dense_w": jnp.asarray(sd["vocab_transform.weight"].T, dtype),
        "mlm_dense_b": jnp.asarray(sd["vocab_transform.bias"], dtype),
        "mlm_ln_scale": jnp.asarray(sd["vocab_layer_norm.weight"], dtype),
        "mlm_ln_bias": jnp.asarray(sd["vocab_layer_norm.bias"], dtype),
        "mlm_bias": jnp.asarray(sd.get("vocab_projector.bias", np.zeros(V)), dtype),
        "pooler_w": jnp.zeros((D, D), dtype),   # distilbert has no pooler
        "pooler_b": jnp.zeros((D,), dtype),
    }
    logger.info(f"adapted HF DistilBERT: {cfg.n_layer}L d={cfg.d_model} vocab={V}")
    return cfg, params


# ----------------------------------------------------------------------
# Megatron-LM GPT
# ----------------------------------------------------------------------


def _megatron_qkv_to_packed(w, n_heads, head_dim, version):
    """Megatron fused query_key_value rows → contiguous (q, k, v).

    Three row orderings exist across Megatron checkpoint versions (reference
    `MegatronSDLoader.merge_query_key_value`, `state_dict_factory.py:220`):
      0:   [3*H*hd, ...]  — already [Q; K; V] blocks
      1.0: [H*hd*3, ...]  — per head, per hd-row, (q,k,v) triplets
      2.0: [H*3*hd, ...]  — per head, (q,k,v) groups of hd rows
    Returns (q, k, v) each [H*hd, in_dim] (or [H*hd] for biases).
    """
    in_dim = w.shape[-1] if w.ndim == 2 else 1
    flat = (lambda t: t.reshape(n_heads * head_dim, in_dim)) if in_dim > 1 \
        else (lambda t: t.reshape(n_heads * head_dim))
    if version == 0:
        q, k, v = np.split(w, 3, axis=0)
        return q, k, v
    if version == 1.0:
        w = w.reshape(n_heads, head_dim, 3, -1)
        return flat(w[:, :, 0]), flat(w[:, :, 1]), flat(w[:, :, 2])
    if version == 2.0:
        w = w.reshape(n_heads, 3, head_dim, -1)
        return flat(w[:, 0]), flat(w[:, 1]), flat(w[:, 2])
    raise ValueError(f"unsupported Megatron checkpoint version {version!r}")


def from_megatron_gpt(model_or_sd, hf_config=None, dtype=jnp.float32, *,
                      num_heads=None, version=None):
    """Megatron-LM GPT state dict → (GPTConfig, params).

    Reference: `module_inject/containers/megatron_gpt.py` (MegatronLayerPolicy)
    + `runtime/state_dict_factory.py:190` (MegatronSDLoader). Handles both the
    old `attention.` and new `self_attention.` module paths and the three qkv
    row orderings (see `_megatron_qkv_to_packed`). The state dict may be
    wrapped in a 'model'/'module' envelope with a 'checkpoint_version' key
    (reference `get_checkpoint_version`, `state_dict_factory.py:425`).

    `num_heads` is required for version 1.0/2.0 de-interleave (Megatron does
    not store it in the weights); pass it directly or via an hf_config-like
    object with `num_attention_heads`.
    """
    raw = model_or_sd
    if version is None and isinstance(raw, dict):
        version = raw.get("checkpoint_version", 0)
    if isinstance(raw, dict):
        for env in ("module", "model"):
            if env in raw and isinstance(raw[env], dict):
                raw = raw[env]
        if "language_model" in raw:
            raw = raw["language_model"]
    sd = _state_dict({k: v for k, v in raw.items()
                      if hasattr(v, "shape") or hasattr(v, "detach")})
    version = float(version or 0)
    if num_heads is None and hf_config is not None:
        num_heads = getattr(hf_config, "num_attention_heads", None)

    wte = sd["word_embeddings.weight"]
    wpe = sd["position_embeddings.weight"]
    D = wte.shape[1]
    attn = "self_attention" if any("self_attention." in k for k in sd) else "attention"
    n_layer = 1 + max(int(k.split(".")[2]) for k in sd
                      if k.startswith("transformer.layers."))
    assert num_heads, "from_megatron_gpt needs num_heads (not stored in weights)"
    H = int(num_heads)
    hd = D // H

    cfg = GPTConfig(
        vocab_size=wte.shape[0], n_layer=n_layer, n_head=H, d_model=D,
        d_ff=sd[f"transformer.layers.0.mlp.dense_h_to_4h.weight"].shape[0],
        max_seq_len=wpe.shape[0],
        use_rotary=False, use_swiglu=False, use_rmsnorm=False,
        tie_embeddings="lm_head.weight" not in sd,
        dtype=dtype, remat=False)

    layers = []
    for i in range(n_layer):
        b = f"transformer.layers.{i}."
        qw, kw, vw = _megatron_qkv_to_packed(
            sd[b + f"{attn}.query_key_value.weight"], H, hd, version)
        qb, kb, vb = _megatron_qkv_to_packed(
            sd[b + f"{attn}.query_key_value.bias"], H, hd, version)
        layers.append({
            "ln1_scale": sd[b + "input_layernorm.weight"],
            "ln1_bias": sd[b + "input_layernorm.bias"],
            "attn_qkv_w": np.concatenate([qw, kw, vw], axis=0).T,
            "attn_qkv_b": np.concatenate([qb, kb, vb]),
            "attn_out_w": sd[b + f"{attn}.dense.weight"].T,
            "attn_out_b": sd[b + f"{attn}.dense.bias"],
            "ln2_scale": sd[b + "post_attention_layernorm.weight"],
            "ln2_bias": sd[b + "post_attention_layernorm.bias"],
            "mlp_up_w": sd[b + "mlp.dense_h_to_4h.weight"].T,
            "mlp_up_b": sd[b + "mlp.dense_h_to_4h.bias"],
            "mlp_down_w": sd[b + "mlp.dense_4h_to_h.weight"].T,
            "mlp_out_b": sd[b + "mlp.dense_4h_to_h.bias"],
        })
    params = {
        "wte": jnp.asarray(wte, dtype),
        "wpe": jnp.asarray(wpe, dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd["transformer.final_layernorm.weight"], dtype),
        "lnf_bias": jnp.asarray(sd["transformer.final_layernorm.bias"], dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(sd["lm_head.weight"], dtype)
    logger.info(f"adapted Megatron GPT: {n_layer}L d={D} H={H} "
                f"ckpt_version={version}")
    return cfg, params


# ----------------------------------------------------------------------
# CLIP text encoder (diffusers/stable-diffusion conditioning)
# ----------------------------------------------------------------------


def from_hf_clip_text(model_or_sd, hf_config=None, dtype=jnp.float32):
    """CLIPTextModel → (GPTConfig, params) for models/diffusion.py's
    clip_text_encode (reference container: `containers/clip.py` maps
    CLIPEncoderLayer onto the fused GPT block — same mapping here, as a
    GPTConfig with quick-gelu + causal mask)."""
    sd = _state_dict(model_or_sd)
    if hf_config is None:
        hf_config = getattr(model_or_sd, "config", None)
    assert hf_config is not None
    tc = getattr(hf_config, "text_config", hf_config)  # CLIPConfig or CLIPTextConfig
    D = tc.hidden_size
    pre = "text_model."

    from deepspeed_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(
        vocab_size=tc.vocab_size,
        n_layer=tc.num_hidden_layers,
        n_head=tc.num_attention_heads,
        d_model=D, d_ff=tc.intermediate_size,
        max_seq_len=tc.max_position_embeddings,
        norm_eps=float(getattr(tc, "layer_norm_eps", 1e-5)),
        activation="quick_gelu" if tc.hidden_act == "quick_gelu" else tc.hidden_act,
        use_rotary=False, use_swiglu=False, use_rmsnorm=False,
        tie_embeddings=True, dtype=dtype, remat=False)

    layers = []
    for i in range(cfg.n_layer):
        b = f"{pre}encoder.layers.{i}."
        q, k, v = (sd[b + f"self_attn.{n}_proj.weight"] for n in ("q", "k", "v"))
        qb, kb, vb = (sd[b + f"self_attn.{n}_proj.bias"] for n in ("q", "k", "v"))
        layers.append({
            "ln1_scale": sd[b + "layer_norm1.weight"],
            "ln1_bias": sd[b + "layer_norm1.bias"],
            "attn_qkv_w": np.concatenate([q, k, v], axis=0).T,
            "attn_qkv_b": np.concatenate([qb, kb, vb]),
            "attn_out_w": sd[b + "self_attn.out_proj.weight"].T,
            "attn_out_b": sd[b + "self_attn.out_proj.bias"],
            "ln2_scale": sd[b + "layer_norm2.weight"],
            "ln2_bias": sd[b + "layer_norm2.bias"],
            "mlp_up_w": sd[b + "mlp.fc1.weight"].T,
            "mlp_up_b": sd[b + "mlp.fc1.bias"],
            "mlp_down_w": sd[b + "mlp.fc2.weight"].T,
            "mlp_out_b": sd[b + "mlp.fc2.bias"],
        })
    params = {
        "wte": jnp.asarray(sd[f"{pre}embeddings.token_embedding.weight"], dtype),
        "wpe": jnp.asarray(sd[f"{pre}embeddings.position_embedding.weight"], dtype),
        "blocks": {k2: v2.astype(dtype) for k2, v2 in _stack(layers).items()},
        "lnf_scale": jnp.asarray(sd[f"{pre}final_layer_norm.weight"], dtype),
        "lnf_bias": jnp.asarray(sd[f"{pre}final_layer_norm.bias"], dtype),
    }
    logger.info(f"adapted HF CLIP text encoder: {cfg.n_layer}L d={D}")
    return cfg, params


# ----------------------------------------------------------------------
# dispatch
# ----------------------------------------------------------------------

_ADAPTERS = {
    "gpt2": from_hf_gpt2,
    "llama": from_hf_llama,
    "mistral": from_hf_mistral,
    "internlm": from_hf_internlm,
    "opt": from_hf_opt,
    "bloom": from_hf_bloom,
    "gpt_neox": from_hf_gpt_neox,
    "gpt_neo": from_hf_gpt_neo,
    "gptj": from_hf_gptj,
    "bert": from_hf_bert,
    "distilbert": from_hf_distilbert,
    "megatron": from_megatron_gpt,
}


def adapt_hf_model(model, dtype=jnp.float32):
    """HF PreTrainedModel → (GPTConfig, params), dispatched on config.model_type
    (reference: `replace_policy.py` policy matching)."""
    mt = getattr(model.config, "model_type", None)
    if mt not in _ADAPTERS:
        raise NotImplementedError(
            f"no adapter for model_type={mt!r}; available: {sorted(_ADAPTERS)}")
    return _ADAPTERS[mt](model, model.config, dtype=dtype)


def hf_decode_model(model, dtype=jnp.float32):
    """HF model → DecodeModelSpec (inference engine input, causal LMs only)."""
    from deepspeed_tpu.models.gpt import make_gpt_decode_model
    mt = getattr(model.config, "model_type", None)
    assert mt not in ("bert", "distilbert"), \
        "BERT-family models are encoders — use hf_train_model / bert_encode"
    cfg, params = adapt_hf_model(model, dtype=dtype)
    spec = make_gpt_decode_model(cfg=cfg, params=params,
                                 name=getattr(model.config, "model_type", "hf"))
    spec.eos_token_id = getattr(model.config, "eos_token_id", None)
    return spec


def hf_train_model(model, dtype=jnp.float32):
    """HF model → training ModelSpec (continued pretraining / finetuning)."""
    import dataclasses
    from functools import partial
    mt = getattr(model.config, "model_type", "hf")
    cfg, params = adapt_hf_model(model, dtype=dtype)
    cfg = dataclasses.replace(cfg, remat=True, dtype=jnp.bfloat16)
    if mt in ("bert", "distilbert"):
        from deepspeed_tpu.models.bert import (bert_param_specs, bert_mlm_loss,
                                               bert_encode)
        from deepspeed_tpu.runtime.engine import ModelSpec
        return ModelSpec(loss_fn=partial(bert_mlm_loss, cfg=cfg), params=params,
                         param_specs=bert_param_specs(cfg),
                         apply_fn=partial(bert_encode, cfg=cfg), name=mt)
    from deepspeed_tpu.models.gpt import make_gpt_model
    spec = make_gpt_model(cfg=cfg, name=mt)
    spec.params = params
    return spec


def from_megatron_gpt_moe(model_or_sd, hf_config=None, dtype=jnp.float32, *,
                          num_heads=None, version=None):
    """Megatron-LM GPT + DeepSpeed-MoE state dict → (MoEGPTConfig, params).

    Reference: `module_inject/containers/megatron_gpt_moe.py:1`
    (DS_MegatronGPTMoEContainer = Megatron attention/norm mapping + MoE
    expert MLPs). Composes `from_megatron_gpt`'s layer mapping with the MoE
    zoo layout (`models/moe_gpt.py`): layers whose MLP lives under
    `mlp.deepspeed_moe.` contribute a gate (`gate.wg.weight`) and stacked
    per-expert FFNs (`experts.deepspeed_experts.<e>.dense_{h_to_4h,4h_to_h}`,
    the DeepSpeed-MoE checkpoint naming); their dense-MLP slots in the
    stacked blocks are zero-filled (never read — `moe_gpt_forward` routes
    those layers through the expert MLP)."""
    from deepspeed_tpu.models.moe_gpt import MoEGPTConfig

    raw = model_or_sd
    if version is None and isinstance(raw, dict):
        version = raw.get("checkpoint_version", 0)
    if isinstance(raw, dict):
        for env in ("module", "model"):
            if env in raw and isinstance(raw[env], dict):
                raw = raw[env]
        if "language_model" in raw:
            raw = raw["language_model"]
    sd = _state_dict({k: v for k, v in raw.items()
                      if hasattr(v, "shape") or hasattr(v, "detach")})
    moe_prefix = "mlp.deepspeed_moe."
    moe_keys = {k for k in sd if moe_prefix in k}
    assert moe_keys, ("no deepspeed_moe keys found — use from_megatron_gpt "
                      "for a dense Megatron checkpoint")

    def layer_of(k):
        return int(k.split(".")[2])

    moe_ids = sorted({layer_of(k) for k in moe_keys})
    # dense skeleton: satisfy from_megatron_gpt by zero-filling the MoE
    # layers' dense-MLP entries (shapes from any expert's FFN)
    any_moe = moe_ids[0]
    up_w = sd[f"transformer.layers.{any_moe}.{moe_prefix}"
              f"experts.deepspeed_experts.0.dense_h_to_4h.weight"]
    F, D = up_w.shape
    dense_sd = dict(sd)
    for lid in moe_ids:
        b = f"transformer.layers.{lid}."
        dense_sd[b + "mlp.dense_h_to_4h.weight"] = np.zeros((F, D), np.float32)
        dense_sd[b + "mlp.dense_h_to_4h.bias"] = np.zeros((F,), np.float32)
        dense_sd[b + "mlp.dense_4h_to_h.weight"] = np.zeros((D, F), np.float32)
        dense_sd[b + "mlp.dense_4h_to_h.bias"] = np.zeros((D,), np.float32)
    dense_sd = {k: v for k, v in dense_sd.items() if moe_prefix not in k}
    base_cfg, params = from_megatron_gpt(dense_sd, hf_config, dtype,
                                         num_heads=num_heads, version=version)

    # moe_freq must reproduce the checkpoint's MoE placement (the zoo places
    # MoE at {i : i % freq == 1})
    freq = None
    for f in range(1, base_cfg.n_layer + 1):
        if [i for i in range(base_cfg.n_layer) if i % f == 1] == moe_ids:
            freq = f
            break
    assert freq is not None, \
        f"MoE layer ids {moe_ids} do not match the zoo's every-freq pattern"

    moe = {}
    num_experts = None
    for lid in moe_ids:
        b = f"transformer.layers.{lid}.{moe_prefix}"
        E = 1 + max(int(k.split("deepspeed_experts.")[1].split(".")[0])
                    for k in moe_keys if k.startswith(b + "experts."))
        num_experts = num_experts or E
        assert E == num_experts, "expert count must match across layers"
        ups, up_bs, downs, down_bs = [], [], [], []
        for e in range(E):
            eb = f"{b}experts.deepspeed_experts.{e}."
            ups.append(sd[eb + "dense_h_to_4h.weight"].T)        # [D, F]
            up_bs.append(sd[eb + "dense_h_to_4h.bias"])
            downs.append(sd[eb + "dense_4h_to_h.weight"].T)      # [F, D]
            down_bs.append(sd[eb + "dense_4h_to_h.bias"])
        moe[str(lid)] = {
            "gate_w": jnp.asarray(sd[b + "gate.wg.weight"].T, dtype),  # [D, E]
            "w_up": jnp.asarray(np.stack(ups), dtype),
            "b_up": jnp.asarray(np.stack(up_bs), dtype),
            "w_down": jnp.asarray(np.stack(downs), dtype),
            "b_down": jnp.asarray(np.stack(down_bs), dtype),
        }
    params["moe"] = moe

    cfg = MoEGPTConfig(**{f.name: getattr(base_cfg, f.name)
                          for f in dataclasses.fields(base_cfg)},
                       num_experts=num_experts, moe_freq=freq)
    logger.info(f"adapted Megatron GPT-MoE: {cfg.n_layer}L d={cfg.d_model} "
                f"E={num_experts} moe_layers={moe_ids}")
    return cfg, params
