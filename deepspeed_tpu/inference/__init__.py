from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.inference.config import TpuInferenceConfig
