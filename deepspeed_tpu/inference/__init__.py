from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.inference.config import TpuInferenceConfig, ServingConfig
from deepspeed_tpu.inference.scheduler import (CompletedRequest, Request,
                                               ServingEngine)
