from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.inference.config import (ServingConfig,
                                            ServingQuantizationConfig,
                                            TpuInferenceConfig)
from deepspeed_tpu.inference.scheduler import (CompletedRequest, Request,
                                               ServingEngine)
from deepspeed_tpu.inference.kv_cache import BlockAllocator
from deepspeed_tpu.inference.prefix_cache import PrefixCache
