"""Multinode runner command builders.

Reference: `launcher/multinode_runner.py:51-366` (PDSH/OpenMPI/MPICH/IMPI/SLURM/
MVAPICH runners, each turning (args, resource pool) into the shell command that
starts the per-node launcher).

TPU launch model: ONE process per host drives all local chips, so every runner
below emits one task per host running `python -m deepspeed_tpu.launcher.launch`
with the node rank; rendezvous is `jax.distributed.initialize` against the
coordinator (MASTER_ADDR:MASTER_PORT), carried by the same env-var contract the
reference uses (RANK/WORLD_SIZE/MASTER_*).
"""

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote, split

PDSH_MAX_FAN_OUT = 1024
MVAPICH_TMP_HOSTFILE = "/tmp/dstpu_mvapich_hostfile"


class MultiNodeRunner(ABC):
    """Builds the host-fanout command for one launcher backend."""

    def __init__(self, args, world_info_base64):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports = {}

    @abstractmethod
    def backend_exists(self):
        """Whether the backend binary is installed on this machine."""

    @abstractmethod
    def get_cmd(self, environment, active_resources):
        """The command to execute (list of argv tokens)."""

    def add_export(self, key, var):
        self.exports[key.strip()] = str(var).strip()

    def parse_user_args(self):
        return list(self.args.user_args)

    @property
    def name(self):
        return self.__class__.__name__

    def _launch_module(self):
        """argv tail shared by all runners: the node-local launcher module."""
        return [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            f"--master_addr={self.args.master_addr}",
            f"--master_port={self.args.master_port}",
        ]


class PDSHRunner(MultiNodeRunner):
    """ssh fanout via pdsh; node rank comes from pdsh's %n substitution."""

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    @property
    def name(self):
        return "pdsh"

    def get_cmd(self, environment, active_resources):
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())

        pdsh_cmd = ["pdsh", "-S", "-f", str(PDSH_MAX_FAN_OUT), "-w", active_workers]
        pdsh_cmd += split(getattr(self.args, "launcher_args", "") or "")

        exports = "".join(f"export {k}={quote(v)}; " for k, v in self.exports.items())
        launch = (self._launch_module() + ["--node_rank=%n"] +
                  [quote(self.user_script)] +
                  [a if a.startswith("-") else quote(a) for a in self.user_arguments])
        return pdsh_cmd + [exports + f"cd {quote(os.path.abspath('.'))}; " +
                           " ".join(launch)], environment


class OpenMPIRunner(MultiNodeRunner):

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        self.add_export("UCX_TLS", "tcp")

    def backend_exists(self):
        return shutil.which("ompi_info") is not None

    @property
    def name(self):
        return "openmpi"

    def get_cmd(self, environment, active_resources):
        # one task per host; node rank taken from OMPI env at the far end.
        # The hostfile passed to mpirun is regenerated from the FILTERED
        # resource set so include/exclude/num_nodes filters hold.
        total_hosts = len(active_resources)
        tmp_hostfile = "/tmp/dstpu_openmpi_hostfile"
        with open(tmp_hostfile, "w") as f:
            for host in active_resources:
                f.write(f"{host} slots=1\n")
        mpirun = [
            "mpirun", "-n", str(total_hosts), "--map-by", "ppr:1:node",
            "-hostfile", tmp_hostfile,
            "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include", "eth0",
        ] + split(getattr(self.args, "launcher_args", "") or "")
        for k, v in self.exports.items():
            mpirun += ["-x", f"{k}={v}"]
        launch = self._launch_module() + ["--node_rank=OMPI_COMM_WORLD_RANK"]
        return mpirun + launch + [self.user_script] + self.user_arguments, environment


class MPICHRunner(MultiNodeRunner):

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    @property
    def name(self):
        return "mpich"

    def get_cmd(self, environment, active_resources):
        devices_per_node = self.resource_pool.values()
        total_hosts = len(self.resource_pool)
        if len(set(devices_per_node)) != 1:
            raise ValueError("MPICH requires same slot count on all hosts")
        mpirun = ["mpirun", "-n", str(total_hosts), "-ppn", "1"] + \
            split(getattr(self.args, "launcher_args", "") or "")
        for k, v in self.exports.items():
            mpirun += ["-genv", k, str(v)]
        launch = self._launch_module() + ["--node_rank=PMI_RANK"]
        return mpirun + launch + [self.user_script] + self.user_arguments, environment


class IMPIRunner(MultiNodeRunner):

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    @property
    def name(self):
        return "impi"

    def get_cmd(self, environment, active_resources):
        total_hosts = len(self.resource_pool)
        mpirun = ["mpirun", "-ppn", "1"] + \
            split(getattr(self.args, "launcher_args", "") or "")
        for k, v in self.exports.items():
            mpirun += ["-genv", k, str(v)]
        # Intel MPI: explicit per-host blocks
        out = list(mpirun)
        for rank, host in enumerate(active_resources):
            out += ["-host", host]
            out += self._launch_module() + [f"--node_rank={rank}"]
            out += [self.user_script] + self.user_arguments
            if rank != total_hosts - 1:
                out.append(":")
        return out, environment


class SlurmRunner(MultiNodeRunner):

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool

    def backend_exists(self):
        return shutil.which("sinfo") is not None

    @property
    def name(self):
        return "slurm"

    def get_cmd(self, environment, active_resources):
        bad = [k for k, v in self.exports.items() if "," in k or "," in str(v)]
        assert not bad, (f"exports {bad} contain commas, which srun --export "
                         "splits on — pass them through launcher_args instead")
        total_hosts = len(active_resources)
        srun = ["srun", "-N", str(total_hosts), "--ntasks-per-node=1"] + \
            split(getattr(self.args, "launcher_args", "") or "")
        if getattr(self.args, "include", ""):
            srun += ["--nodelist", self.args.include]
        if getattr(self.args, "exclude", ""):
            srun += ["--exclude", self.args.exclude]
        exports = "ALL"
        for k, v in self.exports.items():
            exports += f",{k}={v}"
        srun += [f"--export={exports}"]
        launch = self._launch_module() + ["--node_rank=SLURM_NODEID"]
        return srun + launch + [self.user_script] + self.user_arguments, environment


class MVAPICHRunner(MultiNodeRunner):

    def __init__(self, args, world_info_base64, resource_pool):
        super().__init__(args, world_info_base64)
        self.resource_pool = resource_pool
        self.add_export("MV2_SMP_USE_CMA", "0")
        self.add_export("MV2_DEBUG_SHOW_BACKTRACE", "1")

    def backend_exists(self):
        return shutil.which("mpirun_rsh") is not None

    @property
    def name(self):
        return "mvapich"

    def get_cmd(self, environment, active_resources):
        devices_per_node = self.resource_pool.values()
        total_hosts = len(self.resource_pool)
        if len(set(devices_per_node)) != 1:
            raise ValueError("MVAPICH requires same slot count on all hosts")
        with open(MVAPICH_TMP_HOSTFILE, "w") as f:
            for host in self.resource_pool.keys():
                f.write(f"{host}\n")
        mpirun = ["mpirun_rsh", "-np", str(total_hosts),
                  "-hostfile", MVAPICH_TMP_HOSTFILE] + \
            split(getattr(self.args, "launcher_args", "") or "")
        exports = []
        for k, v in self.exports.items():
            exports.append(f"{k}={v}")
        launch = self._launch_module() + ["--node_rank=MV2_COMM_WORLD_RANK"]
        return mpirun + exports + launch + [self.user_script] + self.user_arguments, \
            environment


RUNNER_CLASSES = {
    "pdsh": PDSHRunner,
    "openmpi": OpenMPIRunner,
    "mpich": MPICHRunner,
    "impi": IMPIRunner,
    "slurm": SlurmRunner,
    "mvapich": MVAPICHRunner,
}


def make_runner(name, args, world_info_base64, resource_pool):
    cls = RUNNER_CLASSES[name]
    if cls is PDSHRunner:
        return cls(args, world_info_base64)
    return cls(args, world_info_base64, resource_pool)
