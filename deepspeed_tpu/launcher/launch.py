"""Node-local launcher.

Reference: `launcher/launch.py:132` — decodes the world-info blob, sets
RANK/LOCAL_RANK/WORLD_SIZE/MASTER_* env per spawned process, handles signals and
kills the process tree on exit.

TPU model: the default is ONE process per host (that process drives every local
chip through jax); `--procs_per_node > 1` spawns N processes with distinct
RANK/LOCAL_RANK for CPU-simulation of multi-process jax.distributed (the analog
of the reference's per-GPU fork, used by tests and by hosts exposing chips as
separate processes).
"""

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
import time

from deepspeed_tpu.utils.logging import logger

PID_FILE_BASEPATH = "/tmp"


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="deepspeed-tpu node-local launcher")
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64 json {hostname: slots}")
    parser.add_argument("--node_rank", type=str, default="0",
                        help="this node's rank, or the NAME of an env var holding it "
                             "(e.g. SLURM_NODEID, OMPI_COMM_WORLD_RANK)")
    parser.add_argument("--master_addr", type=str, default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--procs_per_node", type=int, default=1,
                        help="processes to fork on this node (1 = one process "
                             "drives all chips; >1 = per-process jax.distributed)")
    parser.add_argument("--module", action="store_true",
                        help="interpret the script as a python module (python -m)")
    parser.add_argument("--no_python", action="store_true",
                        help="exec the script directly without the interpreter")
    parser.add_argument("--save_pid", type=str, default="")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def resolve_node_rank(value, env=None):
    """`--node_rank` is either an int literal or an env-var name (the MPI/SLURM
    runners can't template the rank into argv, so they pass the var name)."""
    env = env if env is not None else os.environ
    try:
        return int(value)
    except ValueError:
        if value in env:
            return int(env[value])
        raise ValueError(f"node_rank '{value}' is neither an int nor a set env var")


def build_rank_env(world_info, node_rank, local_rank, procs_per_node,
                   master_addr, master_port, base_env=None):
    """Env block for one spawned process (reference launch.py:168-175)."""
    env = dict(base_env if base_env is not None else os.environ)
    hosts = list(world_info.keys())
    nnodes = len(hosts)
    world_size = nnodes * procs_per_node
    rank = node_rank * procs_per_node + local_rank
    env.update({
        "RANK": str(rank),
        "LOCAL_RANK": str(local_rank),
        "WORLD_SIZE": str(world_size),
        "LOCAL_SIZE": str(procs_per_node),
        "CROSS_RANK": str(node_rank),
        "CROSS_SIZE": str(nnodes),
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
        # jax.distributed contract (comm.init_distributed reads these)
        "COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
        "NUM_PROCESSES": str(world_size),
        "PROCESS_ID": str(rank),
    })
    return env


def _signal_child(p, sig):
    """Signal a child's process group — but NEVER our own group. If the child
    shares our group (spawned without start_new_session, or its pid was
    recycled), killpg would TERM the caller and every sibling — in an
    in-process harness that detonates unrelated work."""
    try:
        pgid = os.getpgid(p.pid)
        if pgid == os.getpgid(0):
            p.send_signal(sig)
        else:
            os.killpg(pgid, sig)
    except (ProcessLookupError, PermissionError):
        pass


def terminate_process_tree(procs, timeout=30):
    """SIGTERM then SIGKILL the spawned processes (children ride the process
    group — each child is started in its own session)."""
    for p in procs:
        if p.poll() is None:
            _signal_child(p, signal.SIGTERM)
    deadline = time.time() + timeout
    for p in procs:
        remaining = max(0.1, deadline - time.time())
        try:
            p.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            _signal_child(p, signal.SIGKILL)


def main(args=None):
    args = parse_args(args)
    world_info = json.loads(base64.urlsafe_b64decode(args.world_info))
    if not world_info:
        raise ValueError("world_info must not be empty")
    node_rank = resolve_node_rank(args.node_rank)
    logger.info(f"launch: node_rank={node_rank} nnodes={len(world_info)} "
                f"procs_per_node={args.procs_per_node}")

    if args.save_pid:
        pid_file = os.path.join(PID_FILE_BASEPATH, f"{args.save_pid}.dstpu")
        with open(pid_file, "w") as fd:
            fd.write(str(os.getpid()))

    if args.no_python:
        cmd_head = []
    elif args.module:
        cmd_head = [sys.executable, "-u", "-m"]
    else:
        cmd_head = [sys.executable, "-u"]
    cmd = cmd_head + [args.training_script] + args.training_script_args

    procs = []
    for local_rank in range(args.procs_per_node):
        env = build_rank_env(world_info, node_rank, local_rank,
                             args.procs_per_node, args.master_addr,
                             args.master_port)
        procs.append(subprocess.Popen(cmd, env=env, start_new_session=True))

    def handler(signum, frame):
        logger.info(f"launch: got signal {signum}, terminating children")
        terminate_process_tree(procs)
        sys.exit(128 + signum)

    saved = {sig: signal.signal(sig, handler)
             for sig in (signal.SIGINT, signal.SIGTERM)}

    rc = 0
    try:
        for p in procs:
            p_rc = p.wait()
            if p_rc != 0 and rc == 0:
                # keep the ORIGINATING failure code; siblings killed below exit
                # with signal statuses that would mask it
                rc = p_rc
                # one rank died → bring the node down (reference kills siblings)
                terminate_process_tree(procs)
    finally:
        terminate_process_tree(procs, timeout=5)
        # restore: leaving our handler installed poisons in-process callers
        # (a stray signal later would run it with dead procs and sys.exit)
        for sig, old in saved.items():
            signal.signal(sig, old)
    return rc


if __name__ == "__main__":
    sys.exit(main())
