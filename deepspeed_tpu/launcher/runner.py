"""Launcher CLI — `deepspeed-tpu` entry point.

Reference: `bin/deepspeed` → `launcher/runner.py:389` (hostfile parsing,
include/exclude filters, world-info b64, multinode runners) +
`launcher/launch.py:132` (per-rank fork with RANK/WORLD_SIZE env).

TPU launch model differs fundamentally: ONE process per host drives all local
chips (no per-device fork), and multi-host rendezvous is
`jax.distributed.initialize` against a coordinator. So the launcher:

  * single host: exec the script directly (sets JAX env);
  * multi host: ssh fanout (PDSH-style) running one process per host with
    RANK/WORLD_SIZE/MASTER_ADDR exported — the same env contract the reference's
    node launcher uses, consumed by our comm.init_distributed;
  * GKE/pod-slice: honored via env passthrough (TPU runtime sets topology).

Hostfile format is the reference's: `hostname slots=N` per line.
"""

import argparse
import base64
import contextlib
import json
import os
import shlex
import signal
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ("JAX_", "XLA_", "TPU_", "LIBTPU_", "PYTHON", "PATH", "LD_LIBRARY_PATH",
               "DSTPU_")


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed-tpu launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile: lines of `hostname slots=N`")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Host filter, e.g. 'host1,host2' or 'host1@host2'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Hosts to exclude")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="ssh",
                        choices=["ssh", "pdsh", "local", "openmpi", "mpich",
                                 "impi", "slurm", "mvapich"])
    parser.add_argument("--launcher_args", type=str, default="",
                        help="extra flags passed through to the backend "
                             "(pdsh/mpirun/srun)")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--autotuning", type=str, default="",
                        choices=["", "tune", "run"])
    parser.add_argument("user_script", type=str, help="training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def fetch_hostfile(path):
    """Reference `fetch_hostfile` (`runner.py:201`)."""
    if not os.path.isfile(path):
        return {}
    resources = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            resources[host] = slots
    return resources


def filter_resources(resources, include, exclude):
    hosts = dict(resources)
    if include:
        keep = set(h.split(":")[0] for h in include.replace("@", ",").split(",") if h)
        hosts = {h: s for h, s in hosts.items() if h in keep}
    if exclude:
        drop = set(h.split(":")[0] for h in exclude.replace("@", ",").split(",") if h)
        hosts = {h: s for h, s in hosts.items() if h not in drop}
    return hosts


def encode_world_info(resources):
    data = json.dumps(resources).encode()
    return base64.urlsafe_b64encode(data).decode()


def _export_env_items():
    """(key, value) pairs of env vars forwarded to remote hosts."""
    return [(k, v) for k, v in os.environ.items()
            if any(k.startswith(p) for p in EXPORT_ENVS)]


def _build_env_exports():
    return "; ".join(f"export {k}={shlex.quote(v)}" for k, v in _export_env_items())


def main(args=None):
    args = parse_args(args)
    resources = fetch_hostfile(args.hostfile)
    resources = filter_resources(resources, args.include, args.exclude)
    if args.num_nodes > 0:
        resources = dict(list(resources.items())[:args.num_nodes])

    cmd_tail = [args.user_script] + args.user_args

    if not resources or (len(resources) == 1 and not args.force_multi) \
            or args.launcher == "local":
        # single host: exec in-place (one process drives all chips)
        env = dict(os.environ)
        env.setdefault("WORLD_SIZE", "1")
        env.setdefault("RANK", "0")
        logger.info(f"launching single-host: {' '.join(cmd_tail)}")
        proc = subprocess.Popen([sys.executable] + cmd_tail, env=env)
        with _forward_signals(proc):
            return proc.wait()

    if args.launcher not in ("ssh",):
        # backend-managed fanout (pdsh / mpirun / srun ... — reference
        # multinode_runner.py:51-366); we only build + exec the command
        from deepspeed_tpu.launcher.multinode_runner import make_runner
        if not getattr(args, "master_addr", ""):
            args.master_addr = list(resources.keys())[0]
        runner = make_runner(args.launcher, args, encode_world_info(resources),
                             resources)
        if not runner.backend_exists():
            raise RuntimeError(f"launcher backend '{args.launcher}' not installed")
        for key, val in _export_env_items():
            runner.add_export(key, val)
        cmd, env = runner.get_cmd(dict(os.environ), resources)
        logger.info(f"launching via {runner.name}: {' '.join(map(str, cmd))}")
        proc = subprocess.Popen(cmd, env=env)
        with _forward_signals(proc):
            return proc.wait()

    # multi-host ssh fanout: rank i on host i
    hosts = list(resources.keys())
    master = args.master_addr or hosts[0]
    world = len(hosts)
    procs = []
    exports = _build_env_exports()
    for rank, host in enumerate(hosts):
        remote_env = (f"{exports}; export RANK={rank} WORLD_SIZE={world} "
                      f"MASTER_ADDR={master} MASTER_PORT={args.master_port}")
        remote_cmd = f"{remote_env}; cd {shlex.quote(os.getcwd())}; " \
                     f"{sys.executable} {' '.join(shlex.quote(c) for c in cmd_tail)}"
        full = ["ssh", "-o", "StrictHostKeyChecking=no", host, remote_cmd]
        logger.info(f"rank {rank} -> {host}")
        procs.append(subprocess.Popen(full))
    rc = 0
    try:
        for p in procs:
            rc |= p.wait()
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        rc = 1
    return rc


@contextlib.contextmanager
def _forward_signals(proc):
    """Forward INT/TERM to `proc` for the duration of the wait, then RESTORE
    the previous handlers. Leaving them installed poisons in-process callers
    (e.g. a test harness): a later signal would hit a handler holding a dead
    proc long after the launch returned."""
    def handler(signum, frame):
        try:
            proc.send_signal(signum)
        except ProcessLookupError:
            pass

    saved = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        saved[sig] = signal.signal(sig, handler)
    try:
        yield
    finally:
        for sig, old in saved.items():
            signal.signal(sig, old)


if __name__ == "__main__":
    sys.exit(main())
