"""Collective micro-benchmark CLI (`python -m deepspeed_tpu.launcher.comm_bench`).

Reference: `bin/ds_bench` → DeepSpeedExamples communication benchmarks (latency /
algbw / busbw tables per collective and message size).

Runs each collective over the local mesh's data axis across a size sweep and
prints the standard latency/algbw/busbw table. busbw factors follow the NCCL
conventions: allreduce 2(n-1)/n, allgather & reducescatter (n-1)/n, alltoall
(n-1)/n.
"""

import argparse
import time


def _busbw_factor(op, n):
    if n <= 1:
        return 1.0
    if op == "all_reduce":
        return 2.0 * (n - 1) / n
    return (n - 1) / n


def run_collective(op, size_bytes, trials, warmup, dtype_name="bfloat16"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.comm import mesh as mesh_mod

    mesh = mesh_mod.get_mesh()
    n = mesh.devices.size
    dtype = jnp.dtype(dtype_name)
    elems = max(n, size_bytes // dtype.itemsize)
    elems -= elems % n  # divisible for scatter/alltoall
    axes = tuple(mesh.axis_names)

    x = jax.device_put(jnp.ones((elems,), dtype), NamedSharding(mesh, P(axes)))

    from deepspeed_tpu.utils.jax_compat import shard_map

    if op == "all_reduce":
        def body(v):
            return jax.lax.psum(v, axes)
        out_spec = P(axes)
    elif op == "all_gather":
        def body(v):
            return jax.lax.all_gather(v, axes, tiled=True)
        out_spec = P()
    elif op == "reduce_scatter":
        def body(v):
            return jax.lax.psum_scatter(v, axes, tiled=True)
        out_spec = P(axes)
    elif op == "all_to_all":
        def body(v):
            return jax.lax.all_to_all(v.reshape(n, -1), axes, 0, 0,
                                      tiled=False).reshape(-1)
        out_spec = P(axes)
    else:
        raise ValueError(op)

    # dstpu: ignore[DT004]: the bench compiles one program per measured collective by definition; compile time is excluded by the warmup
    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P(axes), out_specs=out_spec,
                           check_vma=False))
    for _ in range(warmup):
        # dstpu: ignore[DT001]: warmup fence — the timed region must start from a drained device
        fn(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    # dstpu: ignore[DT001]: bench timing fence — bandwidth math needs the last collective finished
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / trials

    nbytes = elems * dtype.itemsize
    algbw = nbytes / dt / 1e9
    busbw = algbw * _busbw_factor(op, n)
    return dt, algbw, busbw, nbytes


def main(argv=None):
    parser = argparse.ArgumentParser(description="deepspeed-tpu comm benchmark")
    parser.add_argument("--ops", type=str,
                        default="all_reduce,all_gather,reduce_scatter,all_to_all")
    parser.add_argument("--minsize", type=int, default=1 << 12)
    parser.add_argument("--maxsize", type=int, default=1 << 26)
    parser.add_argument("--trials", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--dtype", type=str, default="bfloat16")
    args = parser.parse_args(argv)

    from deepspeed_tpu import comm
    if not comm.is_initialized():
        comm.init_distributed()

    for op in args.ops.split(","):
        print(f"\n==== {op} ({args.dtype}) ====")
        print(f"{'bytes':>12} {'latency(us)':>12} {'algbw(GB/s)':>12} {'busbw(GB/s)':>12}")
        size = args.minsize
        while size <= args.maxsize:
            dt, algbw, busbw, nbytes = run_collective(
                op, size, args.trials, args.warmup, args.dtype)
            print(f"{nbytes:>12} {dt*1e6:>12.1f} {algbw:>12.2f} {busbw:>12.2f}")
            size *= 4
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
