"""`ds_ssh` CLI — run a command on every host of a hostfile.

Behavioral analog of the reference's `bin/ds_ssh` (pdsh fan-out over the
hostfile's first column). Uses pdsh when available, otherwise a plain
ssh-per-host loop, so it works on minimal images.
"""

import argparse
import shlex
import shutil
import subprocess
import sys

from deepspeed_tpu.launcher.runner import fetch_hostfile

DEFAULT_HOSTFILE = "/job/hostfile"


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="run a command on all hosts in a hostfile")
    parser.add_argument("-f", "--hostfile", default=DEFAULT_HOSTFILE,
                        help="hostfile path (default: /job/hostfile)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run on every host")
    args = parser.parse_args(argv)

    if not args.command:
        parser.error("no command given")

    resources = fetch_hostfile(args.hostfile)
    if not resources:
        print(f"Missing or empty hostfile at {args.hostfile}, unable to proceed",
              file=sys.stderr)
        return 1
    hosts = list(resources.keys())

    cmd = " ".join(shlex.quote(c) for c in args.command)
    if shutil.which("pdsh"):
        env = {"PDSH_RCMD_TYPE": "ssh"}
        full = ["pdsh", "-w", ",".join(hosts), cmd]
        import os
        return subprocess.call(full, env={**os.environ, **env})

    rc = 0
    for host in hosts:
        proc = subprocess.run(["ssh", "-n", "-o", "StrictHostKeyChecking=no", host, cmd],
                              stdin=subprocess.DEVNULL, capture_output=True, text=True)
        prefix = f"{host}: "
        for line in proc.stdout.splitlines():
            print(prefix + line)
        for line in proc.stderr.splitlines():
            print(prefix + line, file=sys.stderr)
        rc = rc or proc.returncode
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
