"""Distributed serving: a router over a pool of ServingEngine replicas.

PRs 3-5 built ONE continuous-batching engine on ONE mesh; this package is
the front-end layer that spreads production traffic over N data-parallel
engine replicas (SURVEY §2.5/§3.4, §7 step 7 — InferenceEngine replicas over
AutoTP shards):

  * `ServingRouter` (`router.py`) — scores replicas per request on
    prefix-cache AFFINITY (the PR 4 chained block hash is the affinity key),
    LOAD (queue depth, active slots, free+reclaimable blocks) and HEALTH
    (throwing replicas are quarantined, their work re-routed, restarts paced
    by the shared `elasticity/restart_policy.py` budget); admission is
    backpressure-aware (bounded global queue, shed-or-block, per-request
    TTL);
  * `ReplicaHandle` / `InProcessReplica` (`replica.py`) — the small protocol
    the router drives, so a process- or host-separated backend can plug in
    later without touching the routing logic;
  * disaggregated prefill/decode — replicas tagged `role="prefill"` run
    chunked prefill only and hand each slot's KV blocks to a
    `role="decode"` replica (`kv_cache.transplant_blocks`), so long
    prefills stop stalling decode TPOT;
  * self-healing (`degradation.py` + the router's watchdog/hedging knobs +
    `inference/audit.py`) — a hung-replica watchdog (per-step deadline,
    strike budget, health probe) converging hangs onto the crash-failover
    path, hard per-request deadlines + hedged dispatch, a KV-pool
    invariant auditor with in-place repair, and `PressureController`'s
    graceful-degradation ladder under sustained overload.

This PR adds the multi-process fabric: `transport.py` (stdlib length-
prefixed-frame RPC + heartbeat push), `remote_replica.py`
(`RemoteReplica` — every protocol verb over the wire, heartbeat-budget
liveness, process respawn under the router's restart budget),
`replica_server.py` (the `bin/dstpu_replica` entrypoint), and
`autoscaler.py` (elastic scale-up under queue/headroom/degradation
pressure, graceful drain + reap on scale-down).

See docs/inference.md "Distributed serving", docs/serving_fabric.md, and
"Self-healing & degradation".
"""

from deepspeed_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig
from deepspeed_tpu.serving.degradation import PressureController
from deepspeed_tpu.serving.remote_replica import (RemoteConfig,
                                                  RemoteReplica,
                                                  ReplicaProcess)
from deepspeed_tpu.serving.replica import (InProcessReplica, ReplicaHandle,
                                           ReplicaUnavailableError)
from deepspeed_tpu.serving.router import RouterConfig, ServingRouter

__all__ = ["ServingRouter", "RouterConfig", "ReplicaHandle",
           "InProcessReplica", "PressureController",
           "ReplicaUnavailableError", "RemoteReplica", "RemoteConfig",
           "ReplicaProcess", "Autoscaler", "AutoscalerConfig"]
