"""Elastic pool autoscaler: spawn under pressure, drain gracefully when idle.

The controller is deliberately dumb-simple — a sustain/cooldown hysteresis
loop over signals the stack ALREADY exports, so there is nothing new to
instrument and nothing to tune twice:

  * router queue depth per live replica (the primary pressure signal);
  * `mem/pool_headroom_frac` from the router's aggregated memscope
    snapshot (a pool near OOM scales OUT, not up — more replicas, each
    with its own HBM budget);
  * `serving/degradation_level` from replica stats (PR 10's pressure
    ladder): replicas already shedding quality is late-stage pressure.

Scale-up path: `spawn()` (user-supplied: returns a fresh `ReplicaHandle` —
an `InProcessReplica` in tests, a `RemoteReplica` around a spawned process
in production) → prefix-cache **warmup** (replay the router's hottest
prompt prefixes through the new replica so it joins with affinity instead
of stealing cold-prefill latency from live traffic) → `router.add_replica`,
which gates the join through `_check_pool_compat` — a divergent replica is
refused at join time, never at first transplant.

Scale-down path: pick the least-loaded replica above `min_replicas`,
`router.drain_replica` it (admission stops, queued work re-queues at the
router, active slots run to completion), then poll `router.replica_idle`
and reap via `router.remove_replica` (which closes the handle — engine
close in-process, shutdown RPC + process reap remotely). A drain in flight
blocks further scale decisions: one pool mutation at a time.

Every decision lands in `fabric/*` telemetry counters and the flight
recorder, so a scaling flap is diagnosable from the black box alone.
"""

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.serving.replica import ReplicaUnavailableError
from deepspeed_tpu.serving.router import ServingRouter
from deepspeed_tpu.utils.logging import log_dist, logger


@dataclasses.dataclass
class AutoscalerConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    # -- scale-up triggers (any one fires) -------------------------------
    scale_up_queue_per_replica: float = 4.0   # router queue depth / live
    scale_up_headroom_frac: float = 0.08      # pool headroom below this
    scale_up_degradation_level: int = 2       # any replica at/above this
    # -- scale-down trigger (all must hold) ------------------------------
    scale_down_queue_per_replica: float = 0.5
    scale_down_idle_active: int = 0           # max total active slots that
                                              # still counts as "idle"
    # -- hysteresis ------------------------------------------------------
    sustain_up: int = 2        # consecutive pressured ticks before spawn
    sustain_down: int = 8      # consecutive idle ticks before drain
    cooldown_ticks: int = 8    # ticks after any action before the next
    # -- join warmup -----------------------------------------------------
    warmup_prompts: int = 2    # hottest shared prefixes replayed through a
                               # joining replica (0 disables)


class Autoscaler:
    """Drive with one `tick()` per router step (or per poll interval):

        scaler = Autoscaler(router, spawn=lambda i: make_replica(i))
        while serving:
            router.step()
            scaler.tick()

    `spawn(index)` returns a ready `ReplicaHandle`; `clock` is injectable
    (tests drive hysteresis deterministically). Warmup prompts are sampled
    from the router's hottest observed prefixes — callers can also seed
    `note_prompt()` with representative traffic."""

    def __init__(self, router: ServingRouter, spawn: Callable[[int], Any],
                 config: AutoscalerConfig = None,
                 clock: Callable[[], float] = None, **overrides):
        if config is None:
            config = AutoscalerConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        assert config.min_replicas >= 1, "a pool needs at least one replica"
        assert config.max_replicas >= config.min_replicas
        self.router = router
        self.config = config
        self.spawn = spawn
        self._clock = clock if clock is not None else time.monotonic
        self.ticks = 0
        self._pressured = 0          # consecutive pressured ticks
        self._idle = 0               # consecutive idle ticks
        self._cooldown = 0           # ticks until the next action allowed
        self._spawned = 0            # monotone spawn index
        self._draining_rid: Optional[str] = None
        self._warmup_pool: List[Any] = []   # recent prompts for join warmup
        self._warmup_cap = 8
        self.counters = {k: 0 for k in (
            "scale_up", "scale_down", "joins", "join_refused", "reaps",
            "warmup_prompts")}

    # ------------------------------------------------------------------
    # signals
    # ------------------------------------------------------------------

    def note_prompt(self, tokens):
        """Remember a representative prompt for join warmup (bounded ring;
        callers feed real traffic, tests feed the shared prefix)."""
        self._warmup_pool.append(tokens)
        if len(self._warmup_pool) > self._warmup_cap:
            self._warmup_pool.pop(0)

    def signals(self) -> Dict[str, Any]:
        r = self.router
        live = r._healthy()
        n = max(1, len(live))
        queue_per = len(r.queue) / n
        active = 0
        degradation = 0
        for rep in live:
            try:
                active += rep.num_active
                lvl = rep.stats().get("degradation", {}).get("level", 0)
                degradation = max(degradation, int(lvl))
            except Exception:
                continue    # a dying replica is the router's problem
        mem = {}
        try:
            mem = r.memory_snapshot()
        except Exception:
            pass
        return {"live": len(live), "queue_depth": len(r.queue),
                "queue_per_replica": queue_per, "active": active,
                "headroom_frac": mem.get("headroom_frac"),
                "degradation_level": degradation,
                "draining": self._draining_rid}

    def _pressure(self, sig) -> Optional[str]:
        cfg = self.config
        if sig["queue_per_replica"] >= cfg.scale_up_queue_per_replica:
            return f"queue_per_replica={sig['queue_per_replica']:.1f}"
        hr = sig["headroom_frac"]
        if hr is not None and hr < cfg.scale_up_headroom_frac:
            return f"headroom_frac={hr:.3f}"
        if sig["degradation_level"] >= cfg.scale_up_degradation_level:
            return f"degradation_level={sig['degradation_level']}"
        return None

    def _is_idle(self, sig) -> bool:
        cfg = self.config
        return (sig["queue_per_replica"] <= cfg.scale_down_queue_per_replica
                and sig["active"] <= cfg.scale_down_idle_active)

    # ------------------------------------------------------------------
    # the control loop
    # ------------------------------------------------------------------

    def tick(self) -> Optional[str]:
        """One control decision. Returns "scale_up", "scale_down", "reap",
        or None (no action)."""
        self.ticks += 1
        tel = self.router.telemetry
        # finish an in-flight drain before anything else
        if self._draining_rid is not None:
            rid = self._draining_rid
            if rid not in self.router.replicas:
                self._draining_rid = None       # quarantined+removed under us
            elif self.router.replica_idle(rid):
                self.router.remove_replica(rid)
                self._draining_rid = None
                self._count("reaps")
                if self.router.flightrec.enabled:
                    self.router.flightrec.record(
                        "reap", replica=rid,
                        pool=len(self.router.replicas))
                self._gauge_pool(tel)
                return "reap"
            else:
                return None                     # still finishing its slots
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        sig = self.signals()
        why = self._pressure(sig)
        if why is not None:
            self._pressured += 1
            self._idle = 0
        elif self._is_idle(sig):
            self._idle += 1
            self._pressured = 0
        else:
            self._pressured = self._idle = 0
        cfg = self.config
        if (why is not None and self._pressured >= cfg.sustain_up
                and sig["live"] < cfg.max_replicas):
            return self._scale_up(sig, why, tel)
        if (self._idle >= cfg.sustain_down
                and sig["live"] > cfg.min_replicas):
            return self._scale_down(sig, tel)
        return None

    def _scale_up(self, sig, why, tel):
        idx = self._spawned
        self._spawned += 1
        try:
            handle = self.spawn(idx)
        except Exception as e:
            logger.warning(f"autoscaler: spawn #{idx} failed: {e}")
            self._after_action(tel)
            return None
        warmed = self._warmup(handle)
        try:
            self.router.add_replica(handle)
        except ValueError as e:
            # _check_pool_compat refused the join — an incompatible spawn
            # recipe is a config bug; count it, close the orphan, carry on
            self._count("join_refused")
            logger.error(f"autoscaler: join refused for spawn #{idx}: {e}")
            if self.router.flightrec.enabled:
                self.router.flightrec.record(
                    "join_refused", replica=getattr(handle, "replica_id", "?"),
                    reason=str(e), pool=len(self.router.replicas))
            try:
                handle.close()
            except Exception:
                pass
            self._after_action(tel)
            return None
        self._count("scale_up")
        self._count("joins")
        if warmed:
            self._count("warmup_prompts", warmed)
        log_dist(f"autoscaler: +replica {handle.replica_id} ({why}, "
                 f"warmed {warmed} prompts, pool="
                 f"{len(self.router.replicas)})", ranks=[0])
        if self.router.flightrec.enabled:
            self.router.flightrec.record(
                "scale_up", replica=handle.replica_id, reason=why,
                warmed=warmed, pool=len(self.router.replicas))
        self._after_action(tel)
        return "scale_up"

    def _warmup(self, handle) -> int:
        """Replay remembered prompts through the joining replica BEFORE it
        takes traffic: its prefix cache registers the hot prefixes, so its
        first routed requests hit warm blocks (affinity > 0) instead of
        paying cold prefill. Runs directly on the handle — the replica is
        not in the pool yet, so live traffic never waits on warmup."""
        n = 0
        from deepspeed_tpu.inference.scheduler import Request
        for i, tokens in enumerate(self._warmup_pool[:self.config
                                                     .warmup_prompts]):
            try:
                handle.submit(Request(uid=f"__warmup_{self._spawned}_{i}",
                                      tokens=tokens, max_new_tokens=1,
                                      stop_on_eos=False))
                while handle.num_active or handle.queue_depth:
                    handle.step()
                n += 1
            except Exception as e:
                logger.warning(f"autoscaler: warmup prompt {i} failed: {e}")
                break
        return n

    def _scale_down(self, sig, tel):
        victim = self._pick_victim()
        if victim is None:
            return None
        self.router.drain_replica(victim)
        self._draining_rid = victim
        self._count("scale_down")
        if self.router.flightrec.enabled:
            self.router.flightrec.record(
                "scale_down", replica=victim,
                pool=len(self.router.replicas))
        log_dist(f"autoscaler: draining {victim} "
                 f"(queue_per={sig['queue_per_replica']:.2f})", ranks=[0])
        self._after_action(tel)
        return "scale_down"

    def _pick_victim(self) -> Optional[str]:
        """Least-loaded live replica (prefer zero active slots — its drain
        reaps immediately)."""
        best, best_key = None, None
        for rep in self.router._healthy():
            try:
                key = (rep.num_active, rep.queue_depth)
            except ReplicaUnavailableError:
                continue
            if best_key is None or key < best_key:
                best, best_key = rep.replica_id, key
        return best

    def _after_action(self, tel):
        self._cooldown = self.config.cooldown_ticks
        self._pressured = self._idle = 0
        self._gauge_pool(tel)

    def _count(self, name, n=1):
        self.counters[name] += n
        self.router.telemetry.inc(f"fabric/{name}", n)

    def _gauge_pool(self, tel):
        tel.set_gauge("fabric/pool_size", len(self.router.replicas))

    def stats(self) -> Dict[str, Any]:
        return {"ticks": self.ticks, "counters": dict(self.counters),
                "cooldown": self._cooldown, "draining": self._draining_rid,
                "pool_size": len(self.router.replicas)}
