"""RemoteReplica: a `ReplicaHandle` whose engine lives in another process.

The router drives this handle exactly like an `InProcessReplica` — every
protocol verb becomes one RPC over `serving/transport.py`. The pieces:

  * **ReplicaProcess** — spawns `python -m deepspeed_tpu.serving.
    replica_server` with an engine factory (`module:function` + JSON
    kwargs), waits for its ready-file (host/port of the bound listener),
    and owns the OS-process lifecycle (poll/terminate/kill/wait). It is
    also the restart recipe: `RemoteReplica.restart()` respawns the
    process under the router's existing `elasticity/restart_policy` budget;
  * **HeartbeatMonitor** — a push-stream liveness watch: the server sends a
    beat every `heartbeat_interval_s`; the monitor drains them without
    blocking and declares the replica dead after `heartbeat_miss_budget`
    beat-less intervals or an EOF (the instant a killed process's socket
    closes). Clock AND beat source are injectable, so the miss budget is
    unit-testable with zero real waiting;
  * **RemoteReplica** — the handle. Idempotent verbs (pure reads: stats,
    signals, affinity, admissibility...) retry transient transport errors
    under a bounded backoff+jitter policy; non-idempotent verbs (submit,
    step, cancel, drain_queued) are at-most-once — a lost reply surfaces
    as `ReplicaUnavailableError` and the router's quarantine/failover path
    owns recovery (re-route + greedy rerun = exactly-once completion).

Clock protocol (the `set_clock` boundary): a Python callable cannot cross a
process boundary, so a remote replica KEEPS ITS OWN monotonic clock and the
router's clock never leaves the router. `set_clock` here only swaps the
handle's LOCAL clock — the one used to convert the router's absolute
`deadline_at` into a remaining-seconds budget at submit time; the server
re-anchors that budget onto its own clock. Router-side TTL, watchdog and
hedge math were always router-clocked and are unaffected. The one thing
this gives up is deterministic time-travel INSIDE a remote engine (its
TTFT stamps are its own); deadlines, TTLs and liveness all stay exact.
"""

import dataclasses
import json
import os
import select
import socket
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.inference.scheduler import InadmissibleRequestError
from deepspeed_tpu.serving.replica import ReplicaHandle, ReplicaUnavailableError
from deepspeed_tpu.serving.transport import (MAGIC, RetryPolicy, RpcClient,
                                             RemoteCallError, TransportError,
                                             call_with_retry, send_frame)
from deepspeed_tpu.utils.logging import logger


class ReplicaDeadError(ReplicaUnavailableError):
    """Liveness said dead BEFORE a verb was issued: the OS process exited,
    or the heartbeat budget ran out. Raised from step() so the router's
    quarantine path fires without ever blocking on a step timeout."""


@dataclasses.dataclass
class RemoteConfig:
    """Knobs for one remote replica (see docs/serving_fabric.md)."""
    connect_timeout_s: float = 5.0
    call_timeout_s: float = 10.0       # cheap verbs (signals, stats, cancel)
    submit_timeout_s: float = 30.0     # submit ships the whole prompt
    step_timeout_s: float = 300.0      # step may compile on first use; the
                                       # heartbeat, not this, detects death
    ready_timeout_s: float = 120.0     # process spawn -> ready-file
    # retry policy: IDEMPOTENT verbs only
    max_retries: int = 2
    base_backoff_s: float = 0.05
    backoff_factor: float = 2.0
    max_backoff_s: float = 1.0
    jitter: float = 0.25
    # heartbeat liveness
    heartbeat_interval_s: float = 0.5
    heartbeat_miss_budget: int = 4     # beat-less intervals before "dead"

    def retry_policy(self) -> RetryPolicy:
        return RetryPolicy(max_retries=self.max_retries,
                           base_backoff_s=self.base_backoff_s,
                           backoff_factor=self.backoff_factor,
                           max_backoff_s=self.max_backoff_s,
                           jitter=self.jitter)


# ----------------------------------------------------------------------
# heartbeat liveness
# ----------------------------------------------------------------------

class SocketBeatSource:
    """Drains beat frames from a server heartbeat connection without ever
    blocking: `drain()` returns (new_beats, eof). Frames are counted, not
    decoded — a beat's only information is that it arrived."""

    _HDR = 8   # MAGIC(4) + length(4)

    def __init__(self, host: str, port: int, connect_timeout_s: float = 5.0):
        try:
            self._sock = socket.create_connection(
                (host, port), timeout=connect_timeout_s)
            send_frame(self._sock, {"hello": "heartbeat"})
        except (OSError, TransportError) as e:
            raise ReplicaUnavailableError(
                f"heartbeat connect to {host}:{port} failed: {e}") from None
        self._sock.setblocking(False)
        self._buf = b""
        self._eof = False

    def drain(self):
        if self._eof:
            return 0, True
        while True:
            try:
                r, _, _ = select.select([self._sock], [], [], 0)
            except (OSError, ValueError):
                self._eof = True
                break
            if not r:
                break
            try:
                chunk = self._sock.recv(1 << 16)
            except BlockingIOError:
                break
            except OSError:
                self._eof = True
                break
            if not chunk:
                self._eof = True
                break
            self._buf += chunk
        beats = 0
        while len(self._buf) >= self._HDR:
            if self._buf[:4] != MAGIC:      # desynced: trust EOF/miss instead
                self._eof = True
                self._buf = b""
                break
            length = int.from_bytes(self._buf[4:8], "big")
            if len(self._buf) < self._HDR + length:
                break
            self._buf = self._buf[self._HDR + length:]
            beats += 1
        return beats, self._eof

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class HeartbeatMonitor:
    """Miss-budget liveness over a beat source. `check()` is O(1) and
    non-blocking — call it as often as you like (the router does, before
    every step dispatch). Both the clock and the source are injectable:
    tests drive `check()` through a fake clock + scripted beats and prove
    the budget math without one real sleep."""

    def __init__(self, source, interval_s: float, miss_budget: int,
                 clock: Callable[[], float] = None):
        self._source = source
        self.interval_s = float(interval_s)
        self.miss_budget = int(miss_budget)
        self._clock = clock if clock is not None else time.monotonic
        self._last_beat_t = self._clock()   # grace: spawn counts as a beat
        self.beats = 0
        self.dead_reason: Optional[str] = None

    @property
    def alive(self) -> bool:
        return self.dead_reason is None

    def missed_intervals(self) -> float:
        return (self._clock() - self._last_beat_t) / self.interval_s

    def check(self) -> bool:
        """True = alive. Once dead, stays dead (a restart builds a fresh
        monitor)."""
        if self.dead_reason is not None:
            return False
        beats, eof = self._source.drain()
        if beats:
            self.beats += beats
            self._last_beat_t = self._clock()
        if eof:
            # the socket closed: for a replica process this is the moment
            # the OS reaped it — no need to wait out the miss budget
            self.dead_reason = "heartbeat connection closed (EOF)"
            return False
        missed = self.missed_intervals()
        if missed > self.miss_budget:
            self.dead_reason = (f"no heartbeat for {missed:.1f} intervals "
                                f"(budget {self.miss_budget})")
            return False
        return True

    def close(self):
        self._source.close()


# ----------------------------------------------------------------------
# the replica OS process
# ----------------------------------------------------------------------

class ReplicaProcess:
    """One replica-server OS process: spawn, readiness, lifecycle.

    The server binds an ephemeral port and writes ``host port`` to
    `ready_file` once listening (AFTER the engine is built — readiness
    means "serving", not "booting"). `env` entries override the parent's;
    `JAX_PLATFORMS=cpu` is what tests pass there."""

    def __init__(self, factory: str, factory_kwargs: Dict[str, Any] = None,
                 heartbeat_interval_s: float = 0.5, ready_file: str = None,
                 env: Dict[str, str] = None, replica_id: str = "r?",
                 clock: Callable[[], float] = None):
        self.factory = factory
        self.factory_kwargs = dict(factory_kwargs or {})
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.replica_id = replica_id
        self._env_overrides = dict(env or {})
        self._clock = clock if clock is not None else time.monotonic
        if ready_file is None:
            import tempfile
            fd, ready_file = tempfile.mkstemp(prefix="dstpu_replica_",
                                              suffix=".ready")
            os.close(fd)
            os.unlink(ready_file)
        self.ready_file = ready_file
        self.proc: Optional[subprocess.Popen] = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None

    def spawn(self):
        if os.path.exists(self.ready_file):
            os.unlink(self.ready_file)
        env = dict(os.environ)
        # the child must import deepspeed_tpu from the same tree the parent
        # runs, wherever the parent found it
        import deepspeed_tpu as _pkg
        tree = os.path.dirname(os.path.dirname(os.path.abspath(_pkg.__file__)))
        env["PYTHONPATH"] = tree + os.pathsep + env.get("PYTHONPATH", "")
        env.update(self._env_overrides)
        cmd = [sys.executable, "-m", "deepspeed_tpu.serving.replica_server",
               "--factory", self.factory,
               "--kwargs", json.dumps(self.factory_kwargs),
               "--port", "0",
               "--heartbeat-interval", str(self.heartbeat_interval_s),
               "--ready-file", self.ready_file]
        self.proc = subprocess.Popen(cmd, env=env)
        return self

    def wait_ready(self, timeout_s: float = 120.0):
        """Poll for the ready-file (real wall time: a subprocess boots on
        the OS clock, no injected clock can speed it up)."""
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            if self.proc.poll() is not None:
                raise ReplicaUnavailableError(
                    f"replica {self.replica_id} process exited rc="
                    f"{self.proc.returncode} before becoming ready")
            if os.path.exists(self.ready_file):
                text = open(self.ready_file).read().strip()
                if text:
                    host, port = text.split()
                    self.host, self.port = host, int(port)
                    return self.host, self.port
            time.sleep(0.05)
        raise ReplicaUnavailableError(
            f"replica {self.replica_id} not ready after {timeout_s}s")

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def poll(self):
        return self.proc.poll() if self.proc is not None else -1

    def terminate(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()

    def wait(self, timeout_s: float = 10.0):
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=timeout_s)
        if os.path.exists(self.ready_file):
            try:
                os.unlink(self.ready_file)
            except OSError:
                pass


# ----------------------------------------------------------------------
# the handle
# ----------------------------------------------------------------------

# verbs safe to re-ask after a lost reply: pure reads, no server-side state
# (observability_pull qualifies because a pull never consumes spool items —
# the same cursor always answers with the same data, so a retried pull is
# byte-identical and can never double-count)
_IDEMPOTENT = frozenset({
    "ping", "signals", "affinity", "hash_chain", "check_admissible",
    "has_output", "audit_state", "memory_snapshot", "stats",
    "compile_stats", "compat", "progress", "observability_pull"})


class RemoteReplica(ReplicaHandle):
    """The router-facing proxy for a process-separated replica.

    Build it around a `ReplicaProcess` (spawned + ready) for the full
    lifecycle (heartbeat, restart-respawn), or from a bare host/port for an
    externally managed server (no restart, heartbeat optional)::

        proc = ReplicaProcess(factory="mypkg.engines:make", ...).spawn()
        proc.wait_ready()
        rep = RemoteReplica(process=proc, replica_id="r0")
        router.add_replica(rep)

    Load-signal reads are batched: the five routing properties + progress
    ride ONE cached "signals" RPC, invalidated by any state-changing verb —
    the router's scoring loop costs one round trip per replica per step,
    not five."""

    def __init__(self, process: ReplicaProcess = None, host: str = None,
                 port: int = None, replica_id: str = "r0",
                 role: str = "mixed", config: RemoteConfig = None,
                 clock: Callable[[], float] = None,
                 sleep: Callable[[float], None] = None,
                 rng: Callable[[], float] = None,
                 heartbeat: bool = True):
        assert role in ("mixed", "prefill", "decode"), \
            f"unknown replica role {role!r}"
        if process is None and (host is None or port is None):
            raise ValueError("RemoteReplica needs a ReplicaProcess or a "
                             "host+port")
        self.replica_id = str(replica_id)
        self.role = role
        self.config = config or RemoteConfig()
        self.process = process
        self._host = host if host is not None else process.host
        self._port = port if port is not None else process.port
        if self._host is None or self._port is None:
            raise ValueError("replica process has no address — call "
                             "spawn() + wait_ready() first")
        # see module docstring: this clock is LOCAL (deadline translation);
        # it never crosses the wire
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep     # None -> call_with_retry's default
        self._rng = rng
        self._heartbeat_enabled = heartbeat
        self._client: Optional[RpcClient] = None
        self._monitor: Optional[HeartbeatMonitor] = None
        self._signals_cache: Optional[Dict[str, Any]] = None
        self._closed = False
        self.transport_counters = {"calls": 0, "retries": 0, "errors": 0}
        if heartbeat:
            self._monitor = self._build_monitor()

    # -- wiring ----------------------------------------------------------

    def _build_monitor(self) -> HeartbeatMonitor:
        src = SocketBeatSource(self._host, self._port,
                               self.config.connect_timeout_s)
        return HeartbeatMonitor(src, self.config.heartbeat_interval_s,
                                self.config.heartbeat_miss_budget,
                                clock=self._clock)

    def _rpc(self) -> RpcClient:
        if self._client is None:
            self._client = RpcClient(
                self._host, self._port,
                connect_timeout_s=self.config.connect_timeout_s,
                default_timeout_s=self.config.call_timeout_s)
        return self._client

    def _call(self, verb: str, payload: Dict[str, Any] = None,
              timeout_s: float = None) -> Any:
        """One verb over the wire; transient failures retried only for
        idempotent verbs. `RemoteCallError` carrying the engine's own
        `InadmissibleRequestError` is translated back so the router's
        routing/validation `except` clauses keep working unmodified."""
        if self._closed:
            raise ReplicaUnavailableError(
                f"replica {self.replica_id} is closed")
        idem = verb in _IDEMPOTENT
        if verb not in _IDEMPOTENT:
            self._signals_cache = None
        self.transport_counters["calls"] += 1

        def attempt():
            return self._rpc().call(verb, payload, timeout_s=timeout_s)

        def on_retry(n, _e):
            self.transport_counters["retries"] += 1

        try:
            return call_with_retry(attempt, idempotent=idem,
                                   policy=self.config.retry_policy(),
                                   sleep=self._sleep, rng=self._rng,
                                   on_retry=on_retry)
        except TransportError:
            self.transport_counters["errors"] += 1
            raise
        except RemoteCallError as e:
            if e.err_type == "InadmissibleRequestError":
                raise InadmissibleRequestError(e.remote_message) from None
            raise

    def _ensure_alive(self):
        """Cheap pre-flight before expensive verbs: OS process state first,
        then the heartbeat budget — a killed or wedged process is declared
        dead HERE, in O(1), instead of burning a step timeout."""
        if self.process is not None and self.process.poll() is not None:
            raise ReplicaDeadError(
                f"replica {self.replica_id} process exited rc="
                f"{self.process.poll()}")
        if self._monitor is not None and not self._monitor.check():
            raise ReplicaDeadError(
                f"replica {self.replica_id}: {self._monitor.dead_reason}")

    def heartbeat_alive(self) -> bool:
        """Non-raising liveness read (the pool CLI's status column)."""
        try:
            self._ensure_alive()
            return True
        except ReplicaUnavailableError:
            return False

    # -- request lifecycle ------------------------------------------------

    def submit(self, request, prefill_only=False, hashes=None, trace=None,
               deadline_at=None):
        # trace is dropped at the boundary: a span context cannot cross a
        # process boundary. The remote engine records its own side; the
        # router pulls those spans home over `observability_pull` and
        # re-parents them under its trace id (attach_observability below)
        deadline_in_s = None
        if deadline_at is not None:
            # absolute (router clock) -> remaining budget -> the server
            # re-anchors on ITS clock; the budget, not the clock, crosses
            deadline_in_s = max(0.0, float(deadline_at) - self._clock())
        self._call("submit", {
            "request": request, "prefill_only": bool(prefill_only),
            "hashes": list(hashes) if hashes else None,
            "deadline_in_s": deadline_in_s,
        }, timeout_s=self.config.submit_timeout_s)

    def step(self):
        self._ensure_alive()
        return self._call("step", {}, timeout_s=self.config.step_timeout_s)

    def cancel(self, uid, queued_only=False):
        return self._call("cancel", {"uid": uid,
                                     "queued_only": bool(queued_only)})

    def drain_queued(self):
        return self._call("drain_queued", {})

    # -- routing signals --------------------------------------------------

    def _signals(self) -> Dict[str, Any]:
        if self._signals_cache is None:
            self._signals_cache = self._call("signals", {})
        return self._signals_cache

    def check_admissible(self, prompt_len, max_new, prefill_only=False,
                         uid="?", padded_prompt=None):
        return self._call("check_admissible", {
            "prompt_len": int(prompt_len), "max_new": int(max_new),
            "prefill_only": bool(prefill_only), "uid": uid,
            "padded_prompt": padded_prompt})

    def progress(self):
        return int(self._signals()["progress"])

    @property
    def prefill_chunk(self):
        return int(self._signals()["prefill_chunk"])

    def affinity(self, hashes):
        if not hashes:
            return 0
        return int(self._call("affinity", {"hashes": list(hashes)}))

    def hash_chain(self, prompt):
        out = self._call("hash_chain", {"prompt": prompt})
        return None if out is None else [bytes(h) for h in out]

    @property
    def queue_depth(self):
        return int(self._signals()["queue_depth"])

    @property
    def num_active(self):
        return int(self._signals()["num_active"])

    @property
    def available_blocks(self):
        return int(self._signals()["available_blocks"])

    @property
    def has_free_slot(self):
        return bool(self._signals()["has_free_slot"])

    # -- disaggregated handoff -------------------------------------------
    # KV blocks are device buffers; shipping them between processes is the
    # pod-spanning-handoff item (ROADMAP 1), not this PR. A remote replica
    # therefore serves role="mixed" only — the router never calls these
    # outside disaggregated pools.

    def handoff_ready(self):
        return []

    def export_handoff(self, uid):
        raise NotImplementedError(
            "cross-process KV handoff is not supported yet — remote "
            "replicas serve role='mixed'")

    def receive_handoff(self, state, src_pool):
        raise NotImplementedError(
            "cross-process KV handoff is not supported yet — remote "
            "replicas serve role='mixed'")

    def release_handoff(self, uid):
        raise NotImplementedError(
            "cross-process KV handoff is not supported yet")

    # -- observability ----------------------------------------------------

    def attach_observability(self, tracer=None, flightrec=None, tid=None):
        """The wire version of tracer sharing: the objects stay router-side
        (a tracer cannot cross a process boundary) — instead this probes
        the replica server's observability plane (`observability_pull` at
        cursor 0) and caches its spool path + pid so the router can pull
        spans/flight events home on its sync cadence and drain the on-disk
        spool post-mortem. Warns loudly — once per handle — when the
        router wants traces but the remote process recorded none (its
        engine config must enable telemetry tracing/flight_recorder too),
        so a silently dark replica is never mistaken for a healthy one."""
        self.obs_spool_path: Optional[str] = None
        self.obs_pid: Optional[int] = None
        self._obs_enabled = False
        if tracer is None and flightrec is None:
            return
        try:
            probe = self.observability_pull(cursor=0)
        except (ReplicaUnavailableError, RemoteCallError):
            probe = None
        if not (probe or {}).get("enabled"):
            if not getattr(self, "_obs_warned", False):
                self._obs_warned = True
                logger.warning(
                    f"replica {self.replica_id}: router observability is on "
                    f"but the remote process ships nothing back — its spans "
                    f"and flight events will NOT appear in the pool trace. "
                    f"Enable telemetry tracing/flight_recorder in the remote "
                    f"engine's config (the replica server spools them for "
                    f"the router automatically).")
            return
        self._obs_enabled = True
        self.obs_spool_path = probe.get("spool_path")
        self.obs_pid = probe.get("pid")

    def observability_pull(self, cursor=0):
        return self._call("observability_pull", {"cursor": int(cursor)})

    def set_clock(self, clock):
        # LOCAL swap only (deadline translation); never forwarded — see
        # the module docstring for the full clock protocol
        self._clock = clock
        if self._monitor is not None:
            self._monitor._clock = clock

    # -- health -----------------------------------------------------------

    def restart(self):
        """Respawn the replica process (the router calls this under its
        restart budget). Externally managed replicas (no ReplicaProcess)
        cannot restart — `can_restart` already said so."""
        if self.process is None:
            raise RuntimeError(
                f"replica {self.replica_id}: externally managed, no spawn "
                f"recipe to restart from")
        self.close_transport()
        self.process.kill()
        self.process.wait()
        self.process.spawn()
        self.process.wait_ready(self.config.ready_timeout_s)
        self._host, self._port = self.process.host, self.process.port
        self._closed = False
        if self._heartbeat_enabled:
            self._monitor = self._build_monitor()
        logger.info(f"remote replica {self.replica_id} respawned "
                    f"(pid {self.process.pid} @ {self._host}:{self._port})")

    @property
    def can_restart(self):
        return self.process is not None

    def health_probe(self):
        try:
            return bool(self._call("ping", {}, timeout_s=min(
                2.0, self.config.call_timeout_s)))
        except (ReplicaUnavailableError, RemoteCallError):
            return False

    def has_output(self, uid):
        return bool(self._call("has_output", {"uid": uid}))

    def audit_state(self):
        return self._call("audit_state", {})

    def memory_snapshot(self):
        return self._call("memory_snapshot", {})

    def compat_descriptor(self):
        return self._call("compat", {})

    def transport_stats(self) -> Dict[str, Any]:
        out = dict(self.transport_counters)
        if self._monitor is not None:
            out["heartbeats"] = self._monitor.beats
            out["heartbeat_alive"] = self._monitor.alive
            if self._monitor.dead_reason:
                out["heartbeat_dead_reason"] = self._monitor.dead_reason
        if self.process is not None:
            out["pid"] = self.process.pid
        return out

    def stats(self):
        out = self._call("stats", {})
        out["transport"] = self.transport_stats()
        return out

    def compile_stats(self):
        return self._call("compile_stats", {})

    # -- teardown ---------------------------------------------------------

    def close_transport(self):
        if self._client is not None:
            self._client.close()
            self._client = None
        if self._monitor is not None:
            self._monitor.close()
            self._monitor = None
        self._signals_cache = None

    def close(self):
        """Graceful teardown: ask the server to shut down (it closes its
        engine — final audit + telemetry flush — before exiting), then reap
        the process. Idempotent; safe on an already-dead replica."""
        if self._closed:
            return
        self._closed = True
        try:
            self._rpc().call("shutdown", {}, timeout_s=min(
                10.0, self.config.step_timeout_s))
        except (ReplicaUnavailableError, RemoteCallError, OSError):
            pass
        self.close_transport()
        if self.process is not None:
            self.process.terminate()
            self.process.wait()
