"""Replica handles: the protocol the serving router drives.

The router never touches a `ServingEngine` directly — it speaks this small
surface, so the in-process pool built here (N engines in one process, the
CPU-harness and single-host-pod case) can later be swapped for a
process-separated or RPC backend replica-by-replica without changing one
line of routing logic. Everything the router needs is here: submit/step/
cancel, queue extraction for failover, the read-only affinity probe, load
signals (queue depth / active slots / available blocks — the same
quantities the PR 5 gauges export), and the prefill->decode handoff verbs.
"""

from typing import Any, Dict, List, Optional

from deepspeed_tpu.inference.scheduler import (CompletedRequest, Request,
                                               ServingEngine)


class ReplicaUnavailableError(RuntimeError):
    """A replica could not be reached AT ALL — the process died, the wire
    broke, the call timed out. Distinct from a verb that ran and raised:
    the router treats this as "quarantine + reroute" at EVERY call site
    (probes, submit, properties), not just inside step(). Transport errors
    (serving/transport.py) subclass this."""


class ReplicaHandle:
    """Abstract replica surface. Implementations wrap one serving engine
    (or a remote proxy to one). `replica_id` must be unique in a pool;
    `role` is "mixed" (prefill+decode, the default), "prefill" or
    "decode" (disaggregated serving)."""

    replica_id: str = "?"
    role: str = "mixed"

    # -- request lifecycle ------------------------------------------------
    def submit(self, request: Request, prefill_only: bool = False,
               hashes=None, trace=None, deadline_at=None):
        raise NotImplementedError

    def step(self) -> List[CompletedRequest]:
        raise NotImplementedError

    # -- observability ----------------------------------------------------
    def attach_observability(self, tracer=None, flightrec=None, tid=None):
        """Share the router's request tracer / flight recorder with this
        replica (and hand it its Perfetto track id), so a pool's spans land
        in ONE trace file and one black box. Default no-op: a remote
        backend records on its own side and ships spans home out of band."""

    def set_clock(self, clock):
        """Unified clock injection: the router hands every replica ITS
        clock so TTL checks, engine TTFT/TPOT stamps, hard deadlines, and
        the watchdog/hedging timers all read one time source — chaos tests
        drive the whole pool's time deterministically through it. Default
        no-op: a remote backend keeps its own wall clock and the router's
        absolute deadlines are re-anchored at its boundary."""

    def cancel(self, uid, queued_only: bool = False) -> Optional[CompletedRequest]:
        raise NotImplementedError

    def drain_queued(self) -> List[Request]:
        raise NotImplementedError

    # -- routing signals --------------------------------------------------
    def check_admissible(self, prompt_len: int, max_new: int,
                         prefill_only: bool = False, uid: Any = "?",
                         padded_prompt: int = None) -> int:
        raise NotImplementedError

    def progress(self) -> int:
        """Monotone work counter (tokens + chunks + adoptions): the router's
        cheap liveness probe — must not build a full stats()/telemetry
        snapshot."""
        raise NotImplementedError

    @property
    def prefill_chunk(self) -> int:
        raise NotImplementedError

    def affinity(self, hashes) -> int:
        raise NotImplementedError

    def hash_chain(self, prompt) -> Optional[List[bytes]]:
        raise NotImplementedError

    @property
    def queue_depth(self) -> int:
        raise NotImplementedError

    @property
    def num_active(self) -> int:
        raise NotImplementedError

    @property
    def available_blocks(self) -> int:
        raise NotImplementedError

    @property
    def has_free_slot(self) -> bool:
        raise NotImplementedError

    # -- disaggregated handoff -------------------------------------------
    def handoff_ready(self) -> List[Any]:
        raise NotImplementedError

    def export_handoff(self, uid) -> Dict[str, Any]:
        raise NotImplementedError

    def receive_handoff(self, state: Dict[str, Any], src_pool) -> bool:
        raise NotImplementedError

    def release_handoff(self, uid):
        raise NotImplementedError

    # -- health -----------------------------------------------------------
    def restart(self):
        raise NotImplementedError

    @property
    def can_restart(self) -> bool:
        raise NotImplementedError

    def health_probe(self) -> bool:
        """The hung-replica watchdog's liveness check, asked only after a
        replica exhausts its slow-step strike budget: True = slow but
        alive (strikes reset), False = presumed hung (quarantined through
        the same failover path a crash takes). Default True — an
        in-process replica that returned from step() at all is alive; a
        remote backend overrides this with a real ping."""
        return True

    def has_output(self, uid) -> bool:
        """True once `uid` has emitted its first token on this replica —
        the hedging probe: a dispatched request still silent past
        `hedge_after_ms` earns a speculative duplicate elsewhere. Default
        True (= never hedge) so a backend that cannot answer cheaply is
        never double-dispatched by mistake."""
        return True

    def audit(self, repair: bool = False):
        """Run the KV-pool invariant auditor (inference/audit.py) on this
        replica's pool now; returns the `AuditReport` (pre-repair) or None
        for a backend with no in-process pool to audit (a remote replica
        audits on its own side at its scheduled interval)."""
        return None

    def observability_pull(self, cursor: int = 0) -> Optional[Dict[str, Any]]:
        """Pull this replica's observability state for pool aggregation:
        `{"enabled", "cursor", "items", "dropped", "metrics", ...}` —
        spooled spans/flight events after `cursor` plus the current
        registry snapshot (see serving/observability.py for the cursor
        contract). None means "no plane here" (the default): the router
        skips this replica when merging. An in-process replica has no
        spool (its spans already land in the router's own tracer) but
        does expose its registry for merged pool percentiles."""
        return None

    def audit_state(self) -> Optional[Dict[str, Any]]:
        """Portable JSON snapshot of the pool bookkeeping (what
        `bin/dstpu_audit` consumes), or None for a remote backend."""
        return None

    def memory_snapshot(self) -> Optional[Dict[str, Any]]:
        """The replica's HBM ledger (telemetry/memscope.py snapshot), or
        None when the engine runs without `telemetry.memscope` — the
        router aggregates these into pool-level `mem/*` gauges."""
        return None

    def compat_descriptor(self) -> Optional[Dict[str, Any]]:
        """Portable pool-compatibility fingerprint: model cache fingerprint,
        kv block size, serving-effective kv dtype and int8 scale group —
        everything `_check_pool_compat` must agree on before blocks can
        move between pools. JSON-safe so a remote replica can ship it over
        the wire; None means "unknown" and the join-time gate skips this
        replica (handoff into it will still fail loudly)."""
        return None

    def close(self):
        """Release the replica's resources (final audit + telemetry close
        for a local engine; shutdown RPC + process reap for a remote one).
        Default no-op. Idempotent."""

    def stats(self) -> Dict[str, Any]:
        raise NotImplementedError

    def compile_stats(self) -> Dict[str, int]:
        raise NotImplementedError


class InProcessReplica(ReplicaHandle):
    """A `ServingEngine` living in this process.

    `engine` is the live engine; `factory` (optional, a zero-arg callable
    returning a fresh `ServingEngine`) is what `restart()` uses to rebuild
    after a quarantine — without one, a failed replica stays dead and the
    pool shrinks (the router's restart budget then never fires for it). A
    rebuilt engine recompiles its two step programs and starts with a cold
    pool/prefix cache; affinity re-warms organically.
    """

    def __init__(self, engine: ServingEngine = None, factory=None,
                 replica_id: str = "r0", role: str = "mixed"):
        assert role in ("mixed", "prefill", "decode"), \
            f"unknown replica role {role!r}"
        if engine is None:
            if factory is None:
                raise ValueError("InProcessReplica needs an engine or a factory")
            engine = factory()
        self.engine = engine
        self._factory = factory
        self.replica_id = str(replica_id)
        self.role = role

    # -- request lifecycle ------------------------------------------------
    def submit(self, request, prefill_only=False, hashes=None, trace=None,
               deadline_at=None):
        self.engine.submit(request, prefill_only=prefill_only, hashes=hashes,
                           trace=trace, deadline_at=deadline_at)

    def step(self):
        return self.engine.step()

    # -- observability ----------------------------------------------------
    def attach_observability(self, tracer=None, flightrec=None, tid=None):
        self.engine.attach_observability(tracer=tracer, flightrec=flightrec,
                                         tid=tid)

    def set_clock(self, clock):
        self.engine.set_clock(clock)

    def memory_snapshot(self):
        ms = getattr(self.engine, "memscope", None)
        return ms.snapshot() if ms is not None else None

    def observability_pull(self, cursor=0):
        # no spool: an in-process engine's spans/flight events already land
        # in the router's attached tracer/recorder. What pool aggregation
        # needs from here is the registry (per-engine TTFT/TPOT histograms
        # for the exact bucket-wise merge).
        tel = getattr(self.engine, "telemetry", None)
        if tel is None or not getattr(tel, "enabled", False):
            return None
        return {"enabled": True, "cursor": int(cursor), "items": [],
                "dropped": 0, "metrics": tel.registry.snapshot()}

    def cancel(self, uid, queued_only=False):
        return self.engine.cancel(uid, queued_only=queued_only)

    def drain_queued(self):
        return self.engine.drain_queued()

    # -- routing signals --------------------------------------------------
    def check_admissible(self, prompt_len, max_new, prefill_only=False,
                         uid="?", padded_prompt=None):
        return self.engine.check_admissible(prompt_len, max_new,
                                            prefill_only=prefill_only,
                                            uid=uid,
                                            padded_prompt=padded_prompt)

    def progress(self):
        e = self.engine
        return e.tokens_generated + e.prefill_chunks + e.handoffs_in

    @property
    def prefill_chunk(self):
        return self.engine.chunk

    def affinity(self, hashes):
        return self.engine.prefix_affinity(hashes)

    def hash_chain(self, prompt):
        return self.engine.hash_chain(prompt)

    @property
    def queue_depth(self):
        return self.engine.queue_depth

    @property
    def num_active(self):
        return self.engine.num_active

    @property
    def available_blocks(self):
        return self.engine.allocator.available

    @property
    def has_free_slot(self):
        return self.engine.has_free_slot

    # -- disaggregated handoff -------------------------------------------
    def handoff_ready(self):
        return self.engine.handoff_ready()

    def export_handoff(self, uid):
        return self.engine.export_handoff(uid)

    def receive_handoff(self, state, src_pool):
        return self.engine.adopt_handoff(state, src_pool)

    def release_handoff(self, uid):
        self.engine.release_handoff(uid)

    @property
    def pool(self):
        """The engine's paged KV pool — the handoff source buffer."""
        return self.engine.pool

    # -- health -----------------------------------------------------------
    def restart(self):
        if self._factory is None:
            raise RuntimeError(
                f"replica {self.replica_id}: no factory to rebuild from")
        self.engine = self._factory()

    @property
    def can_restart(self):
        return self._factory is not None

    def health_probe(self):
        # answering a host-side attribute read is all "alive" means for an
        # in-process engine; a wedged backend surfaces as an exception here
        try:
            return self.engine.num_active >= 0
        except Exception:
            return False

    def has_output(self, uid):
        return self.engine.has_output(uid)

    def audit(self, repair=False):
        return self.engine.audit(repair=repair)

    def audit_state(self):
        return self.engine.audit_state()

    def compat_descriptor(self):
        e = self.engine
        spec = e.engine.model_spec
        return {
            "fingerprint": spec.cache_fingerprint or spec.name,
            "kv_block_size": int(e.block_size),
            "kv_cache_dtype": str(getattr(e, "kv_cache_dtype",
                                          e.config.kv_cache_dtype)),
            "kv_group_size": int(getattr(e, "kv_group_size", 0)),
        }

    def close(self):
        self.engine.close()

    def stats(self):
        return self.engine.stats()

    def compile_stats(self):
        return self.engine.compile_stats()
