"""Replica-side observability spool: the wire buffer of the pod plane.

A `RemoteReplica` pool runs each engine in its own process, so the PR 8
tracer/flight-recorder objects cannot be injected across the boundary —
each process records into its OWN telemetry dir. The pod observability
plane ships those records home instead: the replica server taps its
tracer (`Tracer.on_record`) and flight recorder (`FlightRecorder
.on_record`) into an `ObservabilitySpool`, and the router pulls the spool
over the idempotent `observability_pull` verb on its sync cadence.

Spool contract:

  * **bounded** — a ring of the last `capacity` items. A router that
    stops pulling (network partition, hung router) costs the replica a
    fixed amount of memory, never unbounded growth; overflow drops
    OLDEST-first and counts every drop into `obs/spool_dropped`.
  * **cursor-addressed** — every item carries a monotonically increasing
    cursor. A pull asks "everything after cursor C"; items are never
    consumed by a pull (only by ring overflow), so a retried pull returns
    byte-identical data and the router advances its cursor only after a
    successful ingest — re-pulls can never double-count.
  * **crash-durable** — every item is also appended (and flushed) to an
    on-disk JSONL spool file. When the process dies to `kill -9` the
    router drains the victim's tail directly from that file for the
    post-mortem dump; the file is compacted back to the live ring
    whenever it grows past ~4x capacity, so disk stays bounded too.
  * **clockless** — the spool never reads a wall clock; item timestamps
    are whatever the (injectable-clock) tracer/recorder stamped.
"""

import json
import os
import threading
from typing import Any, Dict, List, Optional

__all__ = ["ObservabilitySpool", "read_spool_file"]


class ObservabilitySpool:
    """Bounded, cursor-addressed ring of observability items with an
    on-disk JSONL mirror. Items are `{"cursor", "kind", "rec"}` where
    `kind` is `"span"` (a tracer JSONL record) or `"flight"` (a flight-
    recorder event)."""

    def __init__(self, path=None, capacity=1024, telemetry=None):
        self.path = str(path) if path is not None else None
        self.capacity = max(1, int(capacity))
        self.telemetry = telemetry
        self.dropped = 0
        self._items: List[Dict[str, Any]] = []
        self._cursor = 0
        self._file_items = 0
        self._lock = threading.Lock()

    # ---- producer side (tracer / flight-recorder taps) ----------------

    def append(self, kind, rec):
        with self._lock:
            self._cursor += 1
            item = {"cursor": self._cursor, "kind": kind, "rec": rec}
            self._items.append(item)
            if len(self._items) > self.capacity:
                # oldest-first drop: the tail (most recent past) is what a
                # post-mortem needs
                over = len(self._items) - self.capacity
                del self._items[:over]
                self.dropped += over
                if self.telemetry is not None:
                    self.telemetry.inc("obs/spool_dropped", over)
            self._append_file(item)

    def span_hook(self, rec):
        """`Tracer.on_record` adapter."""
        self.append("span", rec)

    def flight_hook(self, ev):
        """`FlightRecorder.on_record` adapter."""
        self.append("flight", ev)

    # ---- consumer side (the observability_pull verb) -------------------

    def pull(self, cursor=0) -> Dict[str, Any]:
        """Everything after `cursor`, oldest first. Pure read: the same
        cursor always returns the same items (until ring overflow claims
        them), which is what makes the wire verb idempotent."""
        with self._lock:
            items = [it for it in self._items if it["cursor"] > int(cursor)]
            return {"cursor": self._cursor, "items": items,
                    "dropped": self.dropped}

    # ---- on-disk mirror -------------------------------------------------

    def _append_file(self, item):
        if self.path is None:
            return
        try:
            if self._file_items >= 4 * self.capacity:
                self._compact()
            with open(self.path, "a") as f:
                f.write(json.dumps(item, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._file_items += 1
        except Exception:
            # the mirror is best-effort forensics; never let disk trouble
            # take down the serving hot path
            pass

    def _compact(self):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for it in self._items:
                f.write(json.dumps(it, default=str) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._file_items = len(self._items)


def read_spool_file(path, after_cursor=0) -> List[Dict[str, Any]]:
    """Post-mortem read of a dead replica's on-disk spool: items with
    cursor > `after_cursor`, oldest first, deduplicated by cursor (the
    file may hold pre-compaction duplicates). A torn final line — the
    `kill -9` landing mid-append — is skipped."""
    items: Dict[int, Dict[str, Any]] = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    it = json.loads(line)
                except json.JSONDecodeError:
                    continue
                cur = it.get("cursor")
                if isinstance(cur, int) and cur > int(after_cursor):
                    items[cur] = it
    except OSError:
        return []
    return [items[c] for c in sorted(items)]
