"""PressureController: the serving pool's graceful-degradation ladder.

Sustained overload has exactly two honest outcomes: shed load on purpose,
or fall over at an arbitrary point (pool exhaustion, queue blowup, TTFT
collapse) chosen by whatever resource happens to run out first. This
module picks on purpose. It watches the same quantities the PR 5 gauges
export — free-block fraction, engine queue depth, and (when telemetry is
on) the TTFT p99 histogram — and walks an ORDERED ladder of service
degradations, cheapest reversible lever first:

  level 0  normal service
  level 1  cap the accepted draft length to 1 (spec decode keeps its
           compiled [S, k+1] verify shape — the drafter just proposes
           less, shrinking the per-step write overhang and verify waste)
  level 2  disable speculative decoding (fall back to the single-step
           decode program; blocks sized for the k-draft overhang make the
           1-step program the only safe fallback)
  level 3  force the 1-step decode window (finer retirement/admission
           granularity: freed blocks and slots turn over K times sooner)
  level 4  aggressively flush the reclaimable prefix-cache blocks to the
           free list. NOT a capacity lever — `available` already counts
           reclaimable blocks and alloc() evicts them on demand — but a
           POOL-level one: an empty cache zeroes this replica's prefix-
           affinity score, so the router stops steering shared-prefix
           traffic at the overloaded replica, and demand-eviction work
           (hash unregistration, chain trimming) moves off the admission
           path while it is hottest
  level 5  shed queued requests below `shed_below_priority` (the only
           rung that drops work — and it drops the work the operator
           marked droppable)

Escalation moves ONE rung per evaluation while any signal is over its
high watermark; de-escalation moves one rung only after `hold_steps`
consecutive CALM evaluations (every signal under its low watermark).
The high/low watermark gap plus the hold count is the hysteresis that
prevents flapping: a pool oscillating around one threshold sits still on
its current rung instead of toggling service features per step.

Everything here is host-side control flow at scheduler-sync granularity.
With `serving.degradation.enabled` false (the default) the controller is
never constructed — the scheduler's hot path, its compiled programs, and
`compile_stats()` are untouched.
"""

from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

__all__ = ["PressureController", "LEVEL_NAMES"]

LEVEL_NAMES = ("normal", "cap_draft", "no_spec", "window_1",
               "flush_cache", "shed")
MAX_LEVEL = len(LEVEL_NAMES) - 1


class PressureController:
    """The ladder, bound to one `ServingEngine`.

    The scheduler calls `update(finished)` once per sync (after decode,
    before its gauge export); the controller evaluates pressure every
    `eval_interval` syncs and exposes its decisions as three cheap
    attributes the scheduler reads inline:

      * `draft_cap`     — None, or the max accepted draft length (level 1)
      * `spec_disabled` — verify step replaced by 1-step decode (level 2+)
      * `force_window_1`— decode window forced to 1 (level 3+)

    Levels 4 and 5 act at evaluation time (cache flush / priority shed)
    rather than through a flag. Telemetry surface: the
    `serving/degradation_level` gauge, escalation/de-escalation counters,
    a flight-recorder event per level CHANGE, and per-level sync occupancy
    in `stats()["level_occupancy"]`.
    """

    def __init__(self, engine, config):
        self.engine = engine
        self.config = config
        self.level = 0
        self.calm_streak = 0
        self.evals = 0
        self.escalations = 0
        self.deescalations = 0
        self.flushed_blocks = 0
        self.occupancy = [0] * (MAX_LEVEL + 1)   # syncs spent at each level
        self._syncs = 0
        self._interval = max(1, int(config.eval_interval))

    # -- the flags the scheduler reads inline --------------------------

    @property
    def draft_cap(self) -> Optional[int]:
        return 1 if self.level >= 1 else None

    @property
    def spec_disabled(self) -> bool:
        return self.level >= 2

    @property
    def force_window_1(self) -> bool:
        return self.level >= 3

    # -- pressure signals ----------------------------------------------

    def _signals(self) -> Dict[str, float]:
        eng = self.engine
        alloc = eng.allocator
        out = {"free_frac": alloc.available / max(1, alloc.capacity),
               "queue": float(len(eng.queue))}
        if self.config.ttft_p99_ms > 0 and eng.telemetry.enabled:
            p99 = eng.latency_snapshot().get("ttft_ms", {}).get("p99")
            if p99 is not None:
                out["ttft_p99_ms"] = float(p99)
        # optional HBM-headroom signal from the memscope ledger (needs
        # telemetry.memscope AND a known capacity; omitted otherwise so
        # the ladder falls back to its pool/queue/TTFT signals)
        ms = getattr(eng, "memscope", None)
        if self.config.headroom_low > 0 and ms is not None:
            hf = ms.headroom_frac()
            if hf is not None:
                out["headroom_frac"] = float(hf)
        return out

    def _classify(self, sig) -> str:
        """One of "pressured" (some signal over its high watermark),
        "calm" (every signal under its low watermark), or "hold" (inside
        the hysteresis band — neither escalate nor count toward
        de-escalation)."""
        cfg = self.config
        # headroom hysteresis band mirrors the others (absent signal reads
        # as fully calm: sig only carries it when the ledger can compute it)
        hr_high = max(cfg.headroom_high, cfg.headroom_low)
        if (sig["free_frac"] < cfg.free_block_low
                or sig["queue"] > cfg.queue_high
                or sig.get("ttft_p99_ms", 0.0) > cfg.ttft_p99_ms > 0
                or sig.get("headroom_frac", 1.0) < cfg.headroom_low):
            return "pressured"
        if (sig["free_frac"] >= cfg.free_block_high
                and sig["queue"] <= cfg.queue_low
                and not sig.get("ttft_p99_ms", 0.0) > cfg.ttft_p99_ms > 0
                and sig.get("headroom_frac", 1.0) >= hr_high):
            return "calm"
        return "hold"

    # -- the ladder -----------------------------------------------------

    def update(self, finished: List) -> None:
        """Once per scheduler sync. Evaluates every `eval_interval` syncs;
        level-5 sheds complete into `finished` (the caller's per-step
        completion list), exactly like a retirement."""
        self.occupancy[self.level] += 1
        self._syncs += 1
        if self._syncs % self._interval:
            return
        self.evals += 1
        sig = self._signals()
        verdict = self._classify(sig)
        if verdict == "pressured":
            self.calm_streak = 0
            if self.level < MAX_LEVEL:
                self._change_level(self.level + 1, sig)
                self.escalations += 1
        elif verdict == "calm":
            self.calm_streak += 1
            if self.level > 0 and self.calm_streak >= self.config.hold_steps:
                self._change_level(self.level - 1, sig)
                self.deescalations += 1
                self.calm_streak = 0
        else:                                    # hysteresis band: sit still
            self.calm_streak = 0

        # the action rungs re-apply every evaluation while engaged: new
        # reclaimable blocks keep appearing (retirements) and new low-
        # priority requests keep arriving while the pressure persists
        eng = self.engine
        if self.level >= 4:
            n = eng.allocator.flush_reclaimable()
            if n:
                self.flushed_blocks += n
                if eng.telemetry.enabled:
                    eng.telemetry.inc("serving/degradation_flushed_blocks", n)
        if self.level >= 5:
            finished.extend(eng.shed_queued_below_priority(
                int(self.config.shed_below_priority)))
        if eng.telemetry.enabled:
            eng.telemetry.set_gauge("serving/degradation_level", self.level)

    def _change_level(self, new: int, sig) -> None:
        old, self.level = self.level, new
        eng = self.engine
        if eng.telemetry.enabled:
            if new > old:
                eng.telemetry.inc("serving/degradation_escalations")
            else:
                eng.telemetry.inc("serving/degradation_deescalations")
        if eng.flightrec.enabled:
            eng.flightrec.record(
                "degrade", level=new, name=LEVEL_NAMES[new],
                **{k: round(v, 4) for k, v in sig.items()})
        log_dist(f"serving degradation: level {old} -> {new} "
                 f"({LEVEL_NAMES[new]}; free_frac={sig['free_frac']:.2f} "
                 f"queue={int(sig['queue'])})", ranks=[0])

    def stats(self) -> Dict:
        return {"level": self.level,
                "level_name": LEVEL_NAMES[self.level],
                "evals": self.evals,
                "escalations": self.escalations,
                "deescalations": self.deescalations,
                "flushed_blocks": self.flushed_blocks,
                "sheds": self.engine.degradation_sheds,
                "level_occupancy": {LEVEL_NAMES[i]: n for i, n
                                    in enumerate(self.occupancy)}}
