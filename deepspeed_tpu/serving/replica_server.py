"""Replica-server entrypoint: one ServingEngine behind the fabric wire.

Run as ``python -m deepspeed_tpu.serving.replica_server`` (or via
``bin/dstpu_replica``)::

    dstpu_replica --factory deepspeed_tpu.testing.fabric:tiny_serving_engine \
                  --kwargs '{"max_slots": 2}' --port 0 \
                  --heartbeat-interval 0.5 --ready-file /tmp/r0.ready

`--factory module:function` names a zero-or-kwargs callable returning a
`ServingEngine` (the child process builds its OWN engine — params, mesh,
compiled programs; nothing crosses the process boundary but the wire). The
server binds, THEN builds the engine, THEN writes ``host port`` to the
ready-file — readiness means "serving", compile cost included in spawn
latency, never in the first request's.

The verb table is a straight projection of `InProcessReplica`: the same
handle the router drives in-process answers each RPC here, so the two
backends cannot drift. Engine verbs run under the transport's lock (one
engine, many connections — the router plus any `dstpu_pool --status`
observers). A received deadline is a REMAINING budget in seconds,
re-anchored on this process's own clock (see remote_replica.py for the
clock protocol).
"""

import argparse
import importlib
import json
import os
import sys
import time


def load_factory(spec: str):
    """Resolve "pkg.module:function" to the callable."""
    if ":" not in spec:
        raise SystemExit(f"--factory must be 'module:function', got {spec!r}")
    mod_name, fn_name = spec.split(":", 1)
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name, None)
    if fn is None:
        raise SystemExit(f"{mod_name} has no attribute {fn_name!r}")
    return fn


class ReplicaServerApp:
    """The verb table + lifecycle around one engine and one RpcServer."""

    def __init__(self, engine, host="127.0.0.1", port=0,
                 heartbeat_interval_s=0.5, clock=None, spool_capacity=1024):
        from deepspeed_tpu.serving.replica import InProcessReplica
        from deepspeed_tpu.serving.transport import RpcServer
        self.handle = InProcessReplica(engine=engine, replica_id="remote")
        self._clock = clock if clock is not None else time.monotonic
        self.telemetry = getattr(engine, "telemetry", None)
        self.spool = self._build_spool(spool_capacity)
        self.server = RpcServer(self.verb_table(), host=host, port=port,
                                heartbeat_interval_s=heartbeat_interval_s)

    def _build_spool(self, capacity):
        """Tap this process's tracer/flight-recorder into a bounded spool
        the router can pull over the wire (`observability_pull`). Only when
        a diagnostic is actually enabled — the observability-off default
        stays zero-overhead and writes no spool file."""
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return None
        tracer = getattr(tel, "tracer", None)
        flightrec = getattr(tel, "flightrec", None)
        traced = bool(getattr(tracer, "enabled", False))
        flight = bool(getattr(flightrec, "enabled", False))
        if not (traced or flight):
            return None
        import pathlib

        from deepspeed_tpu.serving.observability import ObservabilitySpool
        out = pathlib.Path(getattr(tel.config, "output_path", None)
                           or "telemetry")
        spool = ObservabilitySpool(
            path=out / f"{tel.subsystem}.obs.spool.jsonl",
            capacity=capacity, telemetry=tel)
        if traced:
            tracer.on_record = spool.span_hook
        if flight:
            flightrec.on_record = spool.flight_hook
        return spool

    def _observability_pull(self, p):
        """Idempotent, cursor-based pull of spooled spans/flight events plus
        the current registry snapshot. Items are never consumed by a pull
        (only by ring overflow), so a retried pull at the same cursor
        returns identical data and can never double-count."""
        if self.spool is None:
            return {"enabled": False}
        out = self.spool.pull(p.get("cursor", 0))
        return {"enabled": True,
                "cursor": out["cursor"],
                "items": out["items"],
                "dropped": out["dropped"],
                "spool_path": self.spool.path,
                "pid": os.getpid(),
                "metrics": self.telemetry.registry.snapshot()}

    def verb_table(self):
        h = self.handle
        return {
            "ping": lambda p: True,
            "submit": self._submit,
            "step": lambda p: h.step(),
            "cancel": lambda p: h.cancel(p["uid"],
                                         queued_only=p.get("queued_only",
                                                           False)),
            "drain_queued": lambda p: h.drain_queued(),
            "check_admissible": lambda p: h.check_admissible(
                p["prompt_len"], p["max_new"],
                prefill_only=p.get("prefill_only", False),
                uid=p.get("uid", "?"),
                padded_prompt=p.get("padded_prompt")),
            "signals": lambda p: {
                "queue_depth": h.queue_depth,
                "num_active": h.num_active,
                "available_blocks": h.available_blocks,
                "has_free_slot": h.has_free_slot,
                "prefill_chunk": h.prefill_chunk,
                "progress": h.progress(),
            },
            "affinity": lambda p: h.affinity(
                [bytes(x) for x in p["hashes"]]),
            "hash_chain": lambda p: h.hash_chain(p["prompt"]),
            "has_output": lambda p: h.has_output(p["uid"]),
            "audit_state": lambda p: h.audit_state(),
            "memory_snapshot": lambda p: h.memory_snapshot(),
            "stats": lambda p: h.stats(),
            "compile_stats": lambda p: h.compile_stats(),
            "compat": lambda p: h.compat_descriptor(),
            "observability_pull": self._observability_pull,
            "shutdown": lambda p: True,   # RpcServer stops after the reply
        }

    def _submit(self, p):
        deadline_at = None
        if p.get("deadline_in_s") is not None:
            # remaining budget -> absolute on THIS process's clock
            deadline_at = self._clock() + float(p["deadline_in_s"])
        hashes = p.get("hashes")
        if hashes is not None:
            hashes = [bytes(x) for x in hashes]
        self.handle.submit(p["request"],
                           prefill_only=p.get("prefill_only", False),
                           hashes=hashes, deadline_at=deadline_at)
        return None

    def serve(self, ready_file=None):
        if ready_file is not None:
            tmp = ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{self.server.host} {self.server.port}\n")
            os.replace(tmp, ready_file)   # atomic: never read half-written
        try:
            self.server.serve_forever()
        finally:
            try:
                self.handle.close()       # final audit + telemetry flush
            except Exception:
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu_replica",
        description="serve one engine replica over the fabric wire")
    ap.add_argument("--factory", required=True,
                    help="module:function returning a ServingEngine")
    ap.add_argument("--kwargs", default="{}",
                    help="JSON kwargs for the factory")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (read the bound port from the "
                         "ready-file)")
    ap.add_argument("--heartbeat-interval", type=float, default=0.5)
    ap.add_argument("--ready-file", default=None,
                    help="write 'host port' here once serving")
    ap.add_argument("--spool-capacity", type=int, default=1024,
                    help="observability spool ring size (spans + flight "
                         "events retained for the router to pull)")
    args = ap.parse_args(argv)

    factory = load_factory(args.factory)
    engine = factory(**json.loads(args.kwargs))
    app = ReplicaServerApp(engine, host=args.host, port=args.port,
                           heartbeat_interval_s=args.heartbeat_interval,
                           spool_capacity=args.spool_capacity)
    print(f"dstpu_replica: serving on {app.server.host}:{app.server.port} "
          f"(pid {os.getpid()})", file=sys.stderr, flush=True)
    app.serve(ready_file=args.ready_file)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
