"""`dstpu_top` — live pool dashboard over `router.observability_snapshot()`.

Two sources for the snapshot:

  * `--attach HOST:PORT ...` — build throwaway `RemoteReplica` handles
    around already-running replica servers, pull each one's observability
    state over the idempotent `observability_pull` verb, and render the
    merged pool view. Pulls never consume spool items, so an observer
    attaching to a pool a real router is also pulling cannot steal its
    data;
  * a positional `snapshot.json` — render a previously dumped
    `observability_snapshot()` (post-mortem / scripting round-trip).

`--json` emits the snapshot raw; `--watch` re-renders every `--interval`
seconds. The dashboard shows what the ISSUE calls the pool story: merged
latency percentiles (exact, from bucket-wise-merged histograms), one row
per replica (health / queue / active / blocks / degradation rung /
headroom / spool drops), the fabric + router counters, and the most
recent flight events. See docs/profiling.md "Pod observability".
"""

import argparse
import json
import sys
import time
from typing import Any, Dict, List

_LAT_COLS = ("count", "mean", "p50", "p90", "p99")
_REP_COLS = ("id", "role", "health", "queue", "active", "blocks",
             "degrade", "headroom", "restarts", "dropped", "pid")


def _table(rows: List[tuple]) -> List[str]:
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(rows[0]))]
    return ["  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip()
            for r in rows]


def _fmt(v, nd=1):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_top(snap: Dict[str, Any]) -> str:
    """Pure snapshot -> dashboard text (what the tests drive)."""
    lines = [f"pool: steps={snap.get('steps', 0)} "
             f"queue={snap.get('queue_depth', 0)} "
             f"in_flight={snap.get('in_flight', 0)} "
             f"live={snap.get('live_replicas', 0)}/"
             f"{len(snap.get('replicas', {}))}"]

    lat = snap.get("pool_latency") or {}
    if lat:
        lines += ["", "pool latency (merged histograms):"]
        rows = [("metric",) + _LAT_COLS]
        for name in sorted(lat):
            m = lat[name]
            rows.append((name, str(int(m.get("count", 0))),
                         _fmt(m.get("mean")), _fmt(m.get("p50")),
                         _fmt(m.get("p90")), _fmt(m.get("p99"))))
        lines += _table(rows)

    reps = snap.get("replicas") or {}
    if reps:
        lines += ["", "replicas:"]
        rows = [_REP_COLS]
        for rid in sorted(reps):
            r = reps[rid]
            obs = r.get("obs") or {}
            rows.append((rid, r.get("role", "?"), r.get("health", "?"),
                         _fmt(r.get("queue")), _fmt(r.get("active")),
                         _fmt(r.get("available_blocks")),
                         _fmt(r.get("degradation_level")),
                         _fmt(r.get("headroom_frac"), nd=3),
                         _fmt(r.get("restarts")),
                         _fmt(obs.get("dropped")), _fmt(obs.get("pid"))))
        lines += _table(rows)

    counters = {k: v for k, v in (snap.get("counters") or {}).items() if v}
    if counters:
        lines += ["", "counters: " + "  ".join(
            f"{k}={counters[k]:g}" for k in sorted(counters))]

    events = snap.get("flight_events") or []
    if events:
        lines += ["", f"flight events (last {len(events)}):"]
        for ev in events:
            ev = dict(ev)
            seq, kind = ev.pop("seq", "?"), ev.pop("kind", "?")
            ev.pop("t", None)
            detail = " ".join(f"{k}={ev[k]}" for k in sorted(ev))[:100]
            lines.append(f"  [{seq}] {kind} {detail}".rstrip())
    return "\n".join(lines)


def _attach_snapshot(addrs: List[str]) -> Dict[str, Any]:
    """Ephemeral router over running replica servers -> one snapshot."""
    from deepspeed_tpu.serving.remote_replica import RemoteReplica
    from deepspeed_tpu.serving.router import ServingRouter
    reps = []
    for i, addr in enumerate(addrs):
        host, port = addr.rsplit(":", 1)
        reps.append(RemoteReplica(host=host, port=int(port),
                                  replica_id=f"r{i}"))
    router = ServingRouter(replicas=reps)
    try:
        return router.observability_snapshot(refresh=True)
    finally:
        for r in reps:
            try:
                r.close_transport()
            except Exception:
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu_top",
        description="live serving-pool dashboard (merged latency, "
                    "per-replica health, flight events)")
    ap.add_argument("snapshot", nargs="?",
                    help="a dumped observability_snapshot() JSON file")
    ap.add_argument("--attach", nargs="*", metavar="HOST:PORT",
                    help="pull live state from running replica servers")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the snapshot raw instead of the dashboard")
    ap.add_argument("--watch", action="store_true",
                    help="re-render every --interval seconds")
    ap.add_argument("--interval", type=float, default=2.0)
    args = ap.parse_args(argv)
    if not args.attach and not args.snapshot:
        ap.error("a snapshot file or --attach HOST:PORT is required")

    def emit() -> int:
        if args.attach:
            snap = _attach_snapshot(args.attach)
        else:
            try:
                with open(args.snapshot) as f:
                    snap = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                print(f"dstpu_top: cannot read {args.snapshot!r}: {e}",
                      file=sys.stderr)
                return 1
        print(json.dumps(snap, indent=2, default=str) if args.as_json
              else render_top(snap))
        return 0

    if not args.watch:
        return emit()
    try:
        while True:
            sys.stdout.write("\x1b[2J\x1b[H")
            emit()
            time.sleep(max(args.interval, 0.2))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
