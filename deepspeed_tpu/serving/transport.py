"""Stdlib-only RPC transport for process-separated serving replicas.

The router speaks `ReplicaHandle` (serving/replica.py); everything an
in-process replica answers from attribute reads, a remote one must answer
over a wire. This module is that wire, built from nothing but the standard
library so a replica process needs no dependency the engine itself doesn't:

  * **frames** — every message is `MAGIC(4) | length(4, big-endian) | body`,
    body = JSON with tagged extension objects for the payloads JSON cannot
    carry natively: numpy arrays (token prompts, completions), raw bytes
    (prefix-cache hash chains), and the `Request`/`CompletedRequest`
    dataclasses. A frame that ends early decodes to a "truncated" error and
    one that starts with the wrong magic to a "garbage" error — the codec
    never guesses at a desynced stream;
  * **RpcClient** — one socket, one in-flight call (the router is
    single-threaded by design), per-call timeouts via `settimeout`. A
    timeout poisons the connection (the reply may still arrive later and
    desync the stream), so the client closes and reconnects lazily;
  * **retry** — `call_with_retry` wraps transient transport failures in
    bounded retries with exponential backoff + jitter, for IDEMPOTENT verbs
    only: a lost `stats` reply is safely re-asked, a lost `submit` reply is
    not (the server may have enqueued it) — non-idempotent verbs surface
    the first failure to the caller, whose failover path (router
    quarantine) already handles at-most-once delivery;
  * **RpcServer** — the replica process side: accepts connections whose
    first frame declares a role (`rpc` request/reply loop, or `heartbeat`,
    a push-only stream of beat frames from a dedicated thread). Heartbeats
    prove the PROCESS is alive — they keep flowing while the engine is busy
    inside a long step, and stop the instant the process is killed (the
    socket EOFs) or the OS stops scheduling it. A live process with a
    wedged engine is the hung-replica watchdog's job, not the heartbeat's.

Every duration knob is data, not a clock read: the transport itself never
calls the wall clock (timeouts ride `socket.settimeout`; retry sleeps are
injectable) so the layers above keep the chaos-testable injected-clock
discipline (DT002).
"""

import dataclasses
import json
import random
import socket
import struct
import threading
import time
from base64 import b64decode, b64encode
from typing import Any, Callable, Dict, Optional

import numpy as np

from deepspeed_tpu.serving.replica import ReplicaUnavailableError

MAGIC = b"DSFB"                  # DeepSpeed-tpu Serving FaBric
_HEADER = struct.Struct(">4sI")
MAX_FRAME_BYTES = 256 * 1024 * 1024   # one frame must fit a prompt + pool
                                      # snapshot, not a checkpoint


class TransportError(ReplicaUnavailableError):
    """Base for every wire failure. Subclasses `ReplicaUnavailableError` so
    the router treats any of these like a replica it cannot reach —
    quarantine + reroute, never a crash of the routing loop."""


class FrameError(TransportError):
    """The byte stream is not a valid frame: truncated mid-frame, wrong
    magic (garbage / protocol mismatch), or an absurd declared length."""


class TransportTimeout(TransportError):
    """The per-call deadline expired waiting on the socket."""


class TransportClosed(TransportError):
    """The peer hung up (EOF / reset) — for a replica process, usually the
    moment it died."""


class RemoteCallError(RuntimeError):
    """The VERB ran remotely and raised: the server caught the exception
    and shipped `{type, message}` home. Deliberately NOT a TransportError —
    the wire worked; the caller decides what the remote failure means."""

    def __init__(self, verb: str, err_type: str, message: str):
        super().__init__(f"remote {verb} raised {err_type}: {message}")
        self.verb = verb
        self.err_type = err_type
        self.remote_message = message


# ----------------------------------------------------------------------
# codec: JSON + tagged extensions
# ----------------------------------------------------------------------

def _pack(obj):
    """Recursively rewrite payloads into JSON-safe tagged forms."""
    # local import: scheduler pulls jax; the codec itself must stay usable
    # (and unit-testable) without touching it until a dataclass shows up
    if isinstance(obj, np.ndarray):
        return {"__nd__": [str(obj.dtype), list(obj.shape),
                           b64encode(np.ascontiguousarray(obj).tobytes())
                           .decode("ascii")]}
    if isinstance(obj, (bytes, bytearray)):
        return {"__by__": b64encode(bytes(obj)).decode("ascii")}
    if isinstance(obj, np.generic):
        # dstpu: ignore[DT001]: numpy scalar in the host-side codec, no device buffer
        return obj.item()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        tag = {"Request": "__req__", "CompletedRequest": "__done__"}.get(
            type(obj).__name__)
        if tag is None:
            raise TypeError(f"codec cannot ship dataclass "
                            f"{type(obj).__name__} (add a tag for it)")
        return {tag: {f.name: _pack(getattr(obj, f.name))
                      for f in dataclasses.fields(obj)}}
    if isinstance(obj, dict):
        return {str(k): _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_pack(v) for v in obj]
    return obj


def _unpack(obj):
    if isinstance(obj, dict):
        if "__nd__" in obj and len(obj) == 1:
            dtype, shape, data = obj["__nd__"]
            return np.frombuffer(b64decode(data),
                                 dtype=np.dtype(dtype)).reshape(shape).copy()
        if "__by__" in obj and len(obj) == 1:
            return b64decode(obj["__by__"])
        if "__req__" in obj and len(obj) == 1:
            from deepspeed_tpu.inference.scheduler import Request
            return Request(**{k: _unpack(v)
                              for k, v in obj["__req__"].items()})
        if "__done__" in obj and len(obj) == 1:
            from deepspeed_tpu.inference.scheduler import CompletedRequest
            return CompletedRequest(**{k: _unpack(v)
                                       for k, v in obj["__done__"].items()})
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v) for v in obj]
    return obj


def encode_frame(obj: Any) -> bytes:
    body = json.dumps(_pack(obj), separators=(",", ":")).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise FrameError(f"frame body {len(body)}B exceeds the "
                         f"{MAX_FRAME_BYTES}B cap")
    return _HEADER.pack(MAGIC, len(body)) + body


def decode_frame(buf: bytes) -> Any:
    """Decode ONE complete frame from `buf` (exact size — the socket layer
    already read the header and body). Raises `FrameError` on garbage."""
    if len(buf) < _HEADER.size:
        raise FrameError(f"truncated frame: {len(buf)}B is shorter than "
                         f"the {_HEADER.size}B header")
    magic, length = _HEADER.unpack_from(buf)
    if magic != MAGIC:
        raise FrameError(f"garbage frame: bad magic {magic!r} "
                         f"(expected {MAGIC!r})")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"garbage frame: declared length {length}B "
                         f"exceeds the {MAX_FRAME_BYTES}B cap")
    body = buf[_HEADER.size:]
    if len(body) != length:
        raise FrameError(f"truncated frame: header declares {length}B, "
                         f"got {len(body)}B")
    try:
        return _unpack(json.loads(body.decode("utf-8")))
    except (ValueError, UnicodeDecodeError) as e:
        raise FrameError(f"garbage frame body: {e}") from None


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except socket.timeout:
            raise TransportTimeout(
                f"timed out mid-frame ({got}/{n}B)") from None
        except OSError as e:
            raise TransportClosed(f"socket error mid-frame: {e}") from None
        if not chunk:
            if got == 0:
                raise TransportClosed("peer closed the connection")
            raise FrameError(f"truncated frame: peer closed after "
                             f"{got}/{n}B")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_frame(sock: socket.socket, obj: Any):
    try:
        sock.sendall(encode_frame(obj))
    except socket.timeout:
        raise TransportTimeout("timed out sending frame") from None
    except OSError as e:
        raise TransportClosed(f"send failed: {e}") from None


def recv_frame(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _HEADER.size)
    magic, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(f"garbage frame: bad magic {magic!r} "
                         f"(expected {MAGIC!r})")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"garbage frame: declared length {length}B "
                         f"exceeds the {MAX_FRAME_BYTES}B cap")
    return decode_frame(header + _recv_exact(sock, length))


# ----------------------------------------------------------------------
# retry policy (idempotent verbs only)
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries for transient transport failures. Backoff before
    attempt #n (n>=1 retries) is ``min(base * factor**(n-1), max)`` scaled
    by ``1 + jitter*U[0,1)`` — the same shape `elasticity/restart_policy`
    uses, scaled down to RPC cadence."""
    max_retries: int = 2
    base_backoff_s: float = 0.05
    backoff_factor: float = 2.0
    max_backoff_s: float = 1.0
    jitter: float = 0.25

    def delay(self, attempt: int, rng: Callable[[], float]) -> float:
        if self.base_backoff_s <= 0:
            return 0.0
        d = min(self.base_backoff_s *
                (self.backoff_factor ** max(attempt - 1, 0)),
                self.max_backoff_s)
        return d * (1.0 + self.jitter * rng())


def call_with_retry(fn: Callable[[], Any], idempotent: bool,
                    policy: RetryPolicy, sleep: Callable[[float], None] = None,
                    rng: Callable[[], float] = None,
                    on_retry: Callable[[int, Exception], None] = None) -> Any:
    """Run `fn`, retrying `TransportError`s up to `policy.max_retries`
    times — but only when `idempotent`: a verb whose side effect may have
    landed before the reply was lost must fail loudly instead (at-most-once;
    the router's quarantine path owns recovery). `sleep`/`rng` are
    injectable so the retry schedule is unit-testable without real waits."""
    sleep = sleep if sleep is not None else time.sleep
    rng = rng if rng is not None else random.random
    attempt = 0
    while True:
        try:
            return fn()
        except TransportError:
            attempt += 1
            if not idempotent or attempt > policy.max_retries:
                raise
            if on_retry is not None:
                on_retry(attempt, None)
            sleep(policy.delay(attempt, rng))


# ----------------------------------------------------------------------
# client
# ----------------------------------------------------------------------

class RpcClient:
    """One request/reply connection to a replica server.

    Lazy-connects on first call and reconnects after any failure (a timed-
    out call poisons the stream: the stale reply could otherwise be read as
    the answer to the NEXT verb). Not thread-safe by design — the router
    drives each replica from one thread; a second observer (the pool CLI)
    opens its own client."""

    def __init__(self, host: str, port: int, connect_timeout_s: float = 5.0,
                 default_timeout_s: float = 30.0):
        self.host = host
        self.port = int(port)
        self.connect_timeout_s = float(connect_timeout_s)
        self.default_timeout_s = float(default_timeout_s)
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        try:
            sock = socket.create_connection((self.host, self.port),
                                            timeout=self.connect_timeout_s)
        except socket.timeout:
            raise TransportTimeout(
                f"connect to {self.host}:{self.port} timed out") from None
        except OSError as e:
            raise TransportClosed(
                f"connect to {self.host}:{self.port} failed: {e}") from None
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            send_frame(sock, {"hello": "rpc"})
        except TransportError:
            sock.close()
            raise
        return sock

    def call(self, verb: str, payload: Optional[Dict[str, Any]] = None,
             timeout_s: Optional[float] = None) -> Any:
        if self._sock is None:
            self._sock = self._connect()
        sock = self._sock
        sock.settimeout(timeout_s if timeout_s is not None
                        else self.default_timeout_s)
        try:
            send_frame(sock, {"verb": verb, "payload": payload or {}})
            reply = recv_frame(sock)
        except TransportError:
            self.close()                 # the stream is desynced: reconnect
            raise
        if not isinstance(reply, dict) or ("ok" not in reply
                                           and "err" not in reply):
            self.close()
            raise FrameError(f"malformed reply to {verb!r}: {reply!r}")
        if "err" in reply:
            err = reply["err"]
            raise RemoteCallError(verb, err.get("type", "Exception"),
                                  err.get("message", ""))
        return reply["ok"]

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


# ----------------------------------------------------------------------
# server (the replica-process side)
# ----------------------------------------------------------------------

class RpcServer:
    """Serve one `ServingEngine` over the fabric wire.

    `verbs` maps verb name -> callable(payload_dict) -> result. A verb that
    raises ships `{type, message}` home as an error reply (the client
    re-raises `RemoteCallError`); transport failures on one connection
    never take the server down. Engine access is serialized by one lock so
    an observer connection (pool CLI `--status`) can read stats while the
    router drives steps.

    Heartbeat connections get a dedicated sender thread pushing
    ``{"beat": n, "interval_s": i}`` every `heartbeat_interval_s`,
    independent of the engine lock — the beat stream answers "is the
    process alive", nothing more."""

    def __init__(self, verbs: Dict[str, Callable[[Dict[str, Any]], Any]],
                 host: str = "127.0.0.1", port: int = 0,
                 heartbeat_interval_s: float = 0.5):
        self.verbs = verbs
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()[:2]
        self._threads = []

    def serve_forever(self):
        """Accept loop; returns after `shutdown()` (e.g. from the "shutdown"
        verb handler). Each connection runs in its own thread."""
        self._listener.settimeout(0.25)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self._listener.close()

    def serve_in_thread(self) -> threading.Thread:
        """Test/CLI convenience: run the accept loop in a daemon thread."""
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._stop.set()

    def _handle(self, conn: socket.socket):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            hello = recv_frame(conn)
        except TransportError:
            conn.close()
            return
        role = hello.get("hello") if isinstance(hello, dict) else None
        if role == "heartbeat":
            self._heartbeat_loop(conn)
        elif role == "rpc":
            self._rpc_loop(conn)
        else:
            try:
                send_frame(conn, {"err": {"type": "FrameError",
                                          "message": f"bad hello {hello!r}"}})
            except TransportError:
                pass
            conn.close()

    def _heartbeat_loop(self, conn: socket.socket):
        n = 0
        while not self._stop.is_set():
            try:
                send_frame(conn, {"beat": n,
                                  "interval_s": self.heartbeat_interval_s})
            except TransportError:
                break                    # monitor went away; that's its call
            n += 1
            if self._stop.wait(self.heartbeat_interval_s):
                break
        conn.close()

    def _rpc_loop(self, conn: socket.socket):
        while not self._stop.is_set():
            try:
                msg = recv_frame(conn)
            except TransportError:
                break
            verb = msg.get("verb") if isinstance(msg, dict) else None
            fn = self.verbs.get(verb)
            if fn is None:
                reply = {"err": {"type": "KeyError",
                                 "message": f"unknown verb {verb!r}"}}
            else:
                try:
                    with self._lock:
                        reply = {"ok": fn(msg.get("payload") or {})}
                except Exception as e:   # ship EVERY verb failure home
                    reply = {"err": {"type": type(e).__name__,
                                     "message": str(e)[:2000]}}
            try:
                send_frame(conn, reply)
            except TransportError:
                break
            if verb == "shutdown":
                self._stop.set()
        conn.close()
