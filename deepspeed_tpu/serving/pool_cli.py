"""`dstpu_pool`: operate a multi-process serving pool from a config file.

Config (JSON)::

    {
      "factory": "deepspeed_tpu.testing.fabric:tiny_serving_engine",
      "kwargs": {"max_slots": 2},
      "replicas": 2,
      "heartbeat_interval_s": 0.5,
      "router": {"max_replica_restarts": 1}
    }

Modes:

  * (default) launch `replicas` replica processes + a router, print the
    status table, serve an optional `--demo N` trace through the pool
    (smoke-proof: N requests, exactly-once, completion report), then shut
    everything down;
  * `--status` with `--attach host:port ...` — don't spawn anything; probe
    already-running replica servers and print the liveness table;
  * `--drain <id>` — in launch mode, drain that replica gracefully before
    the demo runs (the scale-down path, operable by hand);
  * `--json` — machine-readable output instead of the table.

The status table is built from each replica's OWN wire verbs (signals +
stats + heartbeat), so "what the operator sees" and "what the router acts
on" are the same numbers.
"""

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def load_config(path_or_json: str) -> Dict[str, Any]:
    """Accept a filename or an inline JSON object (starts with '{')."""
    text = path_or_json
    if not path_or_json.lstrip().startswith("{"):
        with open(path_or_json) as f:
            text = f.read()
    cfg = json.loads(text)
    if "factory" not in cfg:
        raise ValueError("pool config needs a 'factory' (module:function)")
    cfg.setdefault("kwargs", {})
    cfg.setdefault("replicas", 2)
    cfg.setdefault("heartbeat_interval_s", 0.5)
    cfg.setdefault("router", {})
    if int(cfg["replicas"]) < 1:
        raise ValueError("pool config needs replicas >= 1")
    return cfg


def replica_row(rep) -> Dict[str, Any]:
    """One status row from a live handle's wire verbs; degrades gracefully
    per-column on a dead replica (liveness is itself a column)."""
    from deepspeed_tpu.serving.replica import ReplicaUnavailableError
    row: Dict[str, Any] = {"id": rep.replica_id, "role": rep.role}
    alive = rep.heartbeat_alive() if hasattr(rep, "heartbeat_alive") else True
    row["alive"] = alive
    pid = getattr(getattr(rep, "process", None), "pid", None)
    if pid is not None:
        row["pid"] = pid
    if not alive:
        return row
    try:
        row["queue"] = rep.queue_depth
        row["active"] = rep.num_active
        row["free_blocks"] = rep.available_blocks
        snap = rep.memory_snapshot()
        if snap and snap.get("headroom_frac") is not None:
            row["headroom_frac"] = round(float(snap["headroom_frac"]), 4)
    except ReplicaUnavailableError as e:
        row["alive"] = False
        row["error"] = str(e)[:120]
    return row


def status_table(rows: List[Dict[str, Any]]) -> str:
    """Fixed-order text table (the --status human view)."""
    cols = ["id", "role", "alive", "pid", "queue", "active", "free_blocks",
            "headroom_frac"]
    used = [c for c in cols if any(c in r for r in rows)] or cols[:3]
    widths = {c: max(len(c), *(len(str(r.get(c, "-"))) for r in rows))
              for c in used}
    lines = ["  ".join(c.ljust(widths[c]) for c in used),
             "  ".join("-" * widths[c] for c in used)]
    for r in rows:
        lines.append("  ".join(str(r.get(c, "-")).ljust(widths[c])
                               for c in used))
    return "\n".join(lines)


def _build_pool(cfg, drain: Optional[str]):
    from deepspeed_tpu.serving.remote_replica import (RemoteConfig,
                                                      RemoteReplica,
                                                      ReplicaProcess)
    from deepspeed_tpu.serving.router import ServingRouter
    rcfg = RemoteConfig(
        heartbeat_interval_s=float(cfg["heartbeat_interval_s"]))
    reps = []
    for i in range(int(cfg["replicas"])):
        proc = ReplicaProcess(
            factory=cfg["factory"], factory_kwargs=cfg["kwargs"],
            heartbeat_interval_s=rcfg.heartbeat_interval_s,
            replica_id=f"r{i}").spawn()
        proc.wait_ready(rcfg.ready_timeout_s)
        reps.append(RemoteReplica(process=proc, replica_id=f"r{i}",
                                  config=rcfg))
    router = ServingRouter(replicas=reps, **cfg["router"])
    if drain is not None:
        router.drain_replica(drain)
    return router, reps


def _demo(router, n: int) -> Dict[str, Any]:
    import numpy as np

    from deepspeed_tpu.inference.scheduler import Request
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 200, (int(rng.integers(4, 24)),))
               .astype(np.int32) for _ in range(n)]
    done = router.run([Request(uid=i, tokens=p, max_new_tokens=8,
                               stop_on_eos=False)
                       for i, p in enumerate(prompts)])
    reasons: Dict[str, int] = {}
    for d in done.values():
        reasons[d.finish_reason] = reasons.get(d.finish_reason, 0) + 1
    return {"submitted": n, "completed": len(done), "reasons": reasons,
            "exactly_once": len(done) == n
            and sorted(done) == list(range(n))}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu_pool",
        description="launch/inspect a multi-process serving pool")
    ap.add_argument("config", nargs="?",
                    help="pool config: a JSON file or an inline JSON object")
    ap.add_argument("--status", action="store_true",
                    help="print the per-replica liveness/queue/headroom "
                         "table (with --attach: probe running servers)")
    ap.add_argument("--attach", nargs="*", metavar="HOST:PORT",
                    help="existing replica servers instead of spawning")
    ap.add_argument("--drain", metavar="ID",
                    help="gracefully drain this replica after launch")
    ap.add_argument("--demo", type=int, default=0, metavar="N",
                    help="serve N random requests through the pool")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.attach:
        from deepspeed_tpu.serving.remote_replica import RemoteReplica
        reps = []
        for i, addr in enumerate(args.attach):
            host, port = addr.rsplit(":", 1)
            reps.append(RemoteReplica(host=host, port=int(port),
                                      replica_id=f"r{i}"))
        rows = [replica_row(r) for r in reps]
        print(json.dumps(rows, indent=2) if args.as_json
              else status_table(rows))
        for r in reps:
            r.close_transport()
        return 0 if all(r.get("alive") for r in rows) else 1

    if not args.config:
        ap.error("a pool config (or --attach) is required")
    cfg = load_config(args.config)
    router, reps = _build_pool(cfg, args.drain)
    rc = 0
    try:
        out: Dict[str, Any] = {"pool": [replica_row(r) for r in reps]}
        if args.demo:
            out["demo"] = _demo(router, args.demo)
            rc = 0 if out["demo"]["exactly_once"] else 1
        if args.as_json:
            out["router"] = {"counters": dict(router.counters)}
            print(json.dumps(out, indent=2))
        else:
            print(status_table(out["pool"]))
            if "demo" in out:
                print(f"\ndemo: {out['demo']}")
    finally:
        for rid in list(router.replicas):
            try:
                router.replicas[rid].close()
            except Exception:
                pass
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
