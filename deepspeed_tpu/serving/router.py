"""ServingRouter: prefix-affinity routing, backpressure admission, failover.

One `ServingEngine` (PR 3) is iteration-level scheduling on one mesh; this
router is the layer above — it owns N engine replicas behind `ReplicaHandle`
and decides, per request, WHERE to run it:

  * **affinity** — the PR 4 prefix cache made KV blocks content-addressed
    (chained block hashes seeded with the model's `cache_fingerprint`).
    That chain is exactly a routing key: hash the prompt once, probe each
    replica's cache read-only (`PrefixCache.match_len`), and prefer the
    replica that already HOLDS the longest registered prefix — a shared
    system prompt then prefills once per POOL, not once per replica;
  * **load** — queue depth, active slots and free+reclaimable blocks (the
    same quantities the PR 5 gauges export) push back: a saturated replica
    loses to a cold one even against affinity (a counted "load spill");
  * **health** — a replica whose step() throws (or that an operator kills)
    is quarantined: its queued-but-unstarted requests are extracted and
    its in-flight ones re-submitted from scratch elsewhere (greedy decoding
    makes the rerun token-identical), and restarts are paced by the shared
    `elasticity/restart_policy.py` budget — the same backoff/budget
    machinery that supervises training restarts.

Admission is backpressure-aware end to end: the router's own queue is
BOUNDED (`max_pending`) with a shed-or-block policy, each request may carry
a TTL that cancels it if still queued past deadline (built on
`ServingEngine.cancel`), and dispatch into a replica defers while that
replica's queue is deep — the request waits at the router where TTL and
failover can still reach it cheaply.

Disaggregated prefill/decode rides the same pool: replicas tagged
`role="prefill"` run chunked prefill only; when a slot's prefill finishes,
the router transplants its KV blocks into a `role="decode"`/`"mixed"`
replica (`kv_cache.transplant_blocks` — a block-indexed gather) and decode
continues there, so a long arriving prompt never stalls decode TPOT.

Scoring formula (policy "affinity"):

    score(r) = affinity_blocks(r) * affinity_weight
               - (queue_depth(r) + active_slots(r)) * load_penalty
               - block_penalty * [blocks_needed > available_blocks(r)]

highest score wins; ties break toward the replica with the least pending
work, then rotation order. `affinity_hits` counts dispatches whose winner
held a non-zero prefix; `load_spills` counts dispatches where some OTHER
replica held a strictly longer prefix but lost on load/saturation.

Self-healing (this PR) extends health beyond "step() threw":

  * **hung-replica watchdog** — each replica's step() is timed against
    `step_deadline_ms` on the router's (injectable) clock; a replica over
    the deadline `step_strike_budget` times IN A ROW is health-probed and,
    if the probe fails, quarantined through the exact failover path an
    exception takes — hangs and crashes converge on one recovery flow;
  * **hard deadlines** — `Request.deadline_ms` anchors an ABSOLUTE
    deadline at router submit that survives every re-dispatch (failover
    rerun, hedge duplicate): the engine enforces it past admission at
    every scheduler sync (`finish_reason="deadline"`), and the router
    expires requests still in its own queue;
  * **hedged dispatch** — a dispatched request with no first token after
    `hedge_after_ms` gets a speculative duplicate on another replica with
    capacity; first completion wins, the loser is cancelled, completion
    de-dup rides the same `_done` bookkeeping failover re-routes use;
  * **one clock** — the router's clock is injected into every replica
    (`set_clock`, re-applied after restarts), so TTL, TTFT/TPOT stamps,
    deadlines, the watchdog, and hedging share one deterministic time
    source under test.
"""

import collections
import dataclasses
import json
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.elasticity.restart_policy import RestartBudget, RestartPolicy
from deepspeed_tpu.inference.scheduler import (CompletedRequest,
                                               InadmissibleRequestError,
                                               Request, ServingEngine)
from deepspeed_tpu.serving.replica import (InProcessReplica, ReplicaHandle,
                                           ReplicaUnavailableError)
from deepspeed_tpu.telemetry import Telemetry, TraceContext, merge_snapshots
from deepspeed_tpu.utils.logging import log_dist, logger


@dataclasses.dataclass
class RouterConfig:
    """Router policy knobs (replica-pool shape lives on the replicas)."""
    max_pending: int = 256        # bounded ROUTER queue (dispatched requests
                                  # don't count — each replica's own FIFO
                                  # carries those)
    admission_policy: str = "block"  # queue full: "block" drives the pool
                                  # until room frees; "shed" completes the
                                  # newcomer immediately with reason
                                  # "cancelled" (counted as `shed`)
    default_ttl_s: Optional[float] = None  # per-request deadline while
                                  # QUEUED (router queue or replica queue);
                                  # never kills a generating request
    routing_policy: str = "affinity"  # "affinity" (scored) | "round_robin"
    affinity_weight: float = 4.0  # score per matched prefix BLOCK
    load_penalty: float = 1.0     # score per queued/active request
    block_penalty: float = 8.0    # flat penalty when the replica cannot
                                  # allocate the request's blocks right now
    max_replica_queue: int = 8    # dispatch defers while the target's queue
                                  # is this deep (router-side backpressure)
    max_replica_restarts: int = 1  # per-replica quarantine restart budget
    restart_backoff_s: float = 0.0  # base backoff before a replica restart
    restart_backoff_factor: float = 2.0
    restart_max_backoff_s: float = 60.0
    step_deadline_ms: Optional[float] = None  # hung-replica watchdog: a
                                  # replica step() over this budget (router
                                  # clock) is a STRIKE; None disables the
                                  # watchdog entirely
    step_strike_budget: int = 3   # consecutive strikes before the health
                                  # probe; probe False => quarantine (slow-
                                  # but-alive resets the strike count)
    hedge_after_ms: Optional[float] = None  # dispatched request with no
                                  # first token after this long gets a
                                  # speculative duplicate on another
                                  # replica with capacity (first completion
                                  # wins, loser cancelled); None disables.
                                  # MIXED pools only — a disaggregated
                                  # pool ignores it (one handoff home per
                                  # uid; see _maybe_hedge)


class ReplicaHungError(RuntimeError):
    """The watchdog gave up on a replica: `step_strike_budget` consecutive
    over-deadline steps AND a failed health probe. Used as the quarantine
    reason so hangs ride the same failover path exceptions take."""


@dataclasses.dataclass
class _Pending:
    """Router-side record of one live (incomplete) request."""
    request: Request
    prompt_len: int
    hashes: Optional[List[bytes]]
    t_submit: float
    deadline: Optional[float]       # TTL: queued-only cancellation
    replica: Optional[str] = None   # None while queued at the router
    trace: Any = None               # TraceContext; the router owns the root
                                    # span and closes it at completion
    deadline_at: Optional[float] = None  # HARD deadline (absolute, router
                                    # clock): anchored once at submit and
                                    # passed through every re-dispatch, so
                                    # failover/hedging never extend it
    t_dispatch: Optional[float] = None   # last dispatch time (hedge timer)
    hedge_replica: Optional[str] = None  # speculative duplicate's replica


class ServingRouter:
    """A pool of serving-engine replicas behind one submit/step/run front.

    Build it from live engines (each wrapped into an `InProcessReplica`),
    handles, or factories::

        router = ServingRouter(replicas=[engine.serving(), engine.serving()],
                               default_ttl_s=30)   # RouterConfig kwargs
        router.submit(Request(uid=0, tokens=prompt, max_new_tokens=64))
        while router.in_flight:
            for done in router.step():
                ...
        # or batch-style: results = router.run(requests)  # {uid: Completed}

    Replicas must serve the SAME model (enforced via `cache_fingerprint`
    when prefix caching is on — affinity across different models would
    transplant wrong KV) and share `kv_block_size` when disaggregated.
    """

    def __init__(self, replicas: Sequence = (), config: RouterConfig = None,
                 telemetry_config=None, clock: Callable[[], float] = None,
                 **overrides):
        if config is None:
            config = RouterConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.config = config
        assert config.admission_policy in ("block", "shed"), \
            f"unknown admission_policy {config.admission_policy!r}"
        assert config.routing_policy in ("affinity", "round_robin"), \
            f"unknown routing_policy {config.routing_policy!r}"
        self._clock = clock if clock is not None else time.monotonic
        # an EXPLICITLY injected clock propagates to every replica
        # (set_clock, re-applied after restarts) so the whole pool — TTL,
        # engine TTFT/TPOT stamps, deadlines, watchdog, hedging — reads one
        # time source; without injection both layers already default to
        # time.monotonic, so there is nothing to unify
        self._clock_injected = clock is not None
        self.replicas: Dict[str, ReplicaHandle] = {}
        self._quarantined: Dict[str, float] = {}   # rid -> earliest restart
        self._dead: set = set()                    # budget exhausted
        self._draining: set = set()                # graceful scale-down: no
                                                   # new admission, active
                                                   # slots run to completion
        self._budgets: Dict[str, RestartBudget] = {}
        self._restart_policy = RestartPolicy(
            max_restarts=config.max_replica_restarts,
            base_backoff_s=config.restart_backoff_s,
            backoff_factor=config.restart_backoff_factor,
            max_backoff_s=config.restart_max_backoff_s,
            jitter=0.0)

        self.queue = collections.deque()           # uids waiting at the router
        self._pending: Dict[Any, _Pending] = {}    # every incomplete uid
        self._done: set = set()
        self._finished_buf: List[CompletedRequest] = []
        self._rr = 0                               # rotation cursor
        # anticipated affinity: hash chains DISPATCHED to a replica, before
        # its prefill has registered the blocks. Without this, a whole wave
        # of shared-prefix requests arriving in one step would scatter (no
        # replica holds the prefix *yet*), and every replica would prefill
        # the prefix once. Bounded LRU per replica; a stale entry (evicted
        # at the replica) only costs a suboptimal route, never correctness.
        self._anticipated: Dict[str, collections.OrderedDict] = {}
        self._anticipated_cap = 4096
        self.steps = 0
        self.counters = {k: 0 for k in (
            "submitted", "completed", "affinity_hits", "load_spills",
            "reroutes", "ttl_cancelled", "shed", "replica_failures",
            "replica_restarts", "handoffs", "watchdog_strikes",
            "watchdog_quarantines", "hedges", "hedge_wins",
            "deadline_cancelled", "drains", "removed")}
        self._strikes: Dict[str, int] = {}  # consecutive over-deadline steps
        self._hedged: set = set()           # uids ever hedge-dispatched (the
                                            # expected-duplicate allowlist)
        # rid -> router-level TTFT ms, a bounded sliding window (the full
        # distribution lives in the telemetry histogram; this stays O(1))
        self._ttft: Dict[str, collections.deque] = {}
        self._ttft_window = 2048

        self.telemetry = Telemetry(telemetry_config, subsystem="router")
        # POOL-shared request tracing + flight recorder (telemetry.tracing /
        # telemetry.flight_recorder flags): the router owns both and injects
        # them into every replica, so a request that crosses replicas —
        # dispatch, failover re-route, KV handoff — still lands every span
        # in ONE file under ONE trace id, with one Perfetto track per
        # replica (tid 0 = the router itself).
        self.tracer = self.telemetry.tracer
        self.flightrec = self.telemetry.flightrec
        self._tids: Dict[str, int] = {}
        if self.tracer.enabled:
            self.tracer.name_process("dstpu serving pool")
            self.tracer.name_track(0, "router")
        # the pod observability plane (pull side): per-replica spool
        # cursors (advanced only after a successful ingest, so a retried
        # pull can never double-count), the latest registry snapshot per
        # replica (REPLACED on every pull, never accumulated — same
        # reason), remote->local span-id remaps for re-parenting, and the
        # wire facts (spool path, pid) the post-mortem drain needs once
        # the process is gone
        self._obs_cursors: Dict[str, int] = {}
        self._obs_metrics: Dict[str, Dict[str, Any]] = {}
        self._obs_remap: Dict[str, Dict[int, int]] = {}
        self._obs_info: Dict[str, Dict[str, Any]] = {}
        # uid -> TraceContext, kept PAST completion (bounded LRU): remote
        # spans arrive on the pull cadence, possibly after _complete
        # already closed the root — re-parenting must still find the
        # router's trace id for them
        self._trace_index: collections.OrderedDict = collections.OrderedDict()
        self._trace_index_cap = 4096

        for r in replicas:
            self.add_replica(r)

    # ------------------------------------------------------------------
    # pool assembly
    # ------------------------------------------------------------------

    def add_replica(self, replica, role: str = None,
                    replica_id: str = None, factory=None) -> ReplicaHandle:
        """Add a replica: a `ReplicaHandle`, a live `ServingEngine` (wrapped
        into an `InProcessReplica`), or a zero-arg factory returning one.
        `factory` doubles as the restart recipe after a quarantine. `role`
        (default "mixed") overrides an existing handle's role too when
        given explicitly."""
        if isinstance(replica, ReplicaHandle):
            handle = replica
            if replica_id is not None:
                handle.replica_id = str(replica_id)
            if role is not None:
                assert role in ("mixed", "prefill", "decode"), \
                    f"unknown replica role {role!r}"
                handle.role = role
        else:
            rid = replica_id if replica_id is not None \
                else f"r{len(self.replicas)}"
            if isinstance(replica, ServingEngine):
                handle = InProcessReplica(engine=replica, factory=factory,
                                          replica_id=rid,
                                          role=role or "mixed")
            elif callable(replica):
                handle = InProcessReplica(factory=replica, replica_id=rid,
                                          role=role or "mixed")
            else:
                raise TypeError(f"cannot build a replica from {replica!r}")
        rid = handle.replica_id
        if rid in self.replicas:
            raise ValueError(f"duplicate replica id {rid!r}")
        self._check_pool_compat(handle)
        self.replicas[rid] = handle
        self._budgets[rid] = RestartBudget(self._restart_policy)
        self._ttft[rid] = collections.deque(maxlen=self._ttft_window)
        self._anticipated[rid] = collections.OrderedDict()
        self._tids[rid] = len(self.replicas)       # tid 0 is the router's
        self._strikes[rid] = 0
        if self._clock_injected:
            handle.set_clock(self._clock)
        self._attach_observability(rid)
        log_dist(f"serving router: +replica {rid} role={handle.role} "
                 f"(pool: {len(self.replicas)})", ranks=[0])
        return handle

    def _attach_observability(self, rid):
        """Inject the pool's tracer/flight recorder into one replica (also
        re-run after a restart — the rebuilt engine starts detached). An
        in-process replica takes the objects directly; a RemoteReplica
        instead probes its server's spool so the router can pull spans
        home over the wire. Either way the pull cursor resets: a fresh
        engine/process starts a fresh spool cursor space."""
        if not (self.tracer.enabled or self.flightrec.enabled):
            return
        self.replicas[rid].attach_observability(
            tracer=self.tracer if self.tracer.enabled else None,
            flightrec=self.flightrec if self.flightrec.enabled else None,
            tid=self._tids[rid])
        self._obs_cursors[rid] = 0
        self._obs_remap[rid] = {}
        if self.tracer.enabled:
            self.tracer.name_track(self._tids[rid], f"replica {rid}")

    def _check_pool_compat(self, handle):
        """Same model (cache fingerprint) across the pool, same block size
        when blocks can move between pools (disaggregated handoff), same
        serving-effective KV dtype and int8 scale group. Runs at EVERY
        join — router construction AND runtime add (autoscaler scale-up) —
        over `compat_descriptor()`, so in-process and remote replicas gate
        identically: a divergent replica is refused here with a clear
        error, never mid-request at its first transplant. A replica whose
        descriptor is None (unknown backend) is admitted ungated; one that
        cannot answer at all is refused — joining a dead replica is
        always a mistake."""
        try:
            mine = handle.compat_descriptor()
        except ReplicaUnavailableError as e:
            raise ValueError(
                f"replica {handle.replica_id} is unreachable at join time "
                f"({e}); refusing to add it to the pool") from None
        if mine is None or not self.replicas:
            return
        ref = ref_rid = None
        for rid, other in self.replicas.items():
            if rid in self._dead or rid in self._quarantined:
                continue
            try:
                ref = other.compat_descriptor()
            except ReplicaUnavailableError:
                continue
            if ref is not None:
                ref_rid = rid
                break
        if ref is None:
            return
        if mine["fingerprint"] != ref["fingerprint"]:
            raise ValueError(
                f"replica {handle.replica_id} serves a different model "
                f"({mine['fingerprint']!r} vs {ref_rid}'s "
                f"{ref['fingerprint']!r}): affinity routing and KV handoff "
                f"require one model per pool")
        if mine["kv_block_size"] != ref["kv_block_size"]:
            raise ValueError(
                f"replica {handle.replica_id}: kv_block_size "
                f"{mine['kv_block_size']} != pool's {ref['kv_block_size']} "
                f"(blocks must transplant 1:1)")
        # serving-EFFECTIVE pool dtype (ServingConfig.quantization may pick
        # int8 over the engine-level kv_cache_dtype), plus the scale group:
        # an int8 pool next to a bf16 one — or two int8 pools with different
        # kv_group_size — would fail mid-request at the first handoff's
        # transplant instead of here at join time
        if mine["kv_cache_dtype"] != ref["kv_cache_dtype"]:
            raise ValueError(
                f"replica {handle.replica_id}: kv_cache_dtype "
                f"{mine['kv_cache_dtype']} != pool's "
                f"{ref['kv_cache_dtype']} (transplanted blocks must be "
                f"byte-identical)")
        if mine["kv_cache_dtype"] == "int8" \
                and mine["kv_group_size"] != ref["kv_group_size"]:
            raise ValueError(
                f"replica {handle.replica_id}: kv_group_size "
                f"{mine['kv_group_size']} != pool's {ref['kv_group_size']} "
                f"(int8 scale leaves must transplant 1:1)")

    @property
    def disaggregated(self) -> bool:
        return any(r.role == "prefill" for r in self.replicas.values())

    def _healthy(self, roles=None,
                 include_draining: bool = False) -> List[ReplicaHandle]:
        out = []
        for rid, r in self.replicas.items():
            if rid in self._quarantined or rid in self._dead:
                continue
            if rid in self._draining and not include_draining:
                continue
            if roles is not None and r.role not in roles:
                continue
            out.append(r)
        return out

    # ------------------------------------------------------------------
    # graceful drain / removal (the autoscaler's scale-down path)
    # ------------------------------------------------------------------

    def drain_replica(self, rid):
        """Begin a graceful drain: the replica stops receiving NEW work
        (dispatch, hedges, handoff targets all skip it) and its queued-but-
        unstarted requests move back to the router queue; active slots keep
        stepping to completion. `remove_replica` reaps it once idle — the
        autoscaler polls for that. A drain never loses a token: requeued
        requests re-dispatch from scratch (greedy rerun = identical), and
        running ones finish where they are."""
        if rid not in self.replicas:
            raise KeyError(f"unknown replica {rid!r}")
        if rid in self._draining or rid in self._dead:
            return
        if rid in self._quarantined:
            return          # already failed: quarantine owns its requests
        self._draining.add(rid)
        self._count("drains")
        requeue = []
        try:
            for req in self.replicas[rid].drain_queued():
                rec = self._pending.get(req.uid)
                if rec is not None and rec.replica == rid:
                    rec.replica = None
                    rec.t_dispatch = None
                    requeue.append(req.uid)
        except ReplicaUnavailableError as e:
            self._draining.discard(rid)
            self._quarantine(rid, e)
            return
        self.queue.extendleft(reversed(requeue))
        if requeue:
            self._count("reroutes", len(requeue))
        self._anticipated[rid].clear()
        log_dist(f"router: draining replica {rid} "
                 f"(requeued {len(requeue)})", ranks=[0])
        if self.flightrec.enabled:
            self.flightrec.record("drain", replica=rid,
                                  requeued=len(requeue))

    def replica_idle(self, rid) -> bool:
        """True when a replica owns no work at all — the reap condition."""
        rep = self.replicas[rid]
        if rid in self._dead:
            return True
        try:
            return rep.queue_depth == 0 and rep.num_active == 0 \
                and not any(rec.replica == rid or rec.hedge_replica == rid
                            for rec in self._pending.values())
        except ReplicaUnavailableError as e:
            self._quarantine(rid, e)
            return False

    def remove_replica(self, rid, close: bool = True) -> ReplicaHandle:
        """Reap a drained (or dead) replica from the pool. Refuses while it
        still owns work — call `drain_replica` first and poll
        `replica_idle`. With `close=True` the handle's resources are
        released (engine close / remote shutdown + process reap)."""
        if rid not in self.replicas:
            raise KeyError(f"unknown replica {rid!r}")
        if rid not in self._dead and not self.replica_idle(rid):
            raise RuntimeError(
                f"replica {rid} still owns work — drain it first")
        rep = self.replicas.pop(rid)
        for store in (self._budgets, self._ttft, self._anticipated,
                      self._strikes, self._quarantined, self._obs_cursors,
                      self._obs_metrics, self._obs_remap, self._obs_info):
            store.pop(rid, None)
        self._draining.discard(rid)
        self._dead.discard(rid)
        self._count("removed")
        log_dist(f"router: -replica {rid} (pool: {len(self.replicas)})",
                 ranks=[0])
        if self.flightrec.enabled:
            self.flightrec.record("remove_replica", replica=rid)
        if close:
            try:
                rep.close()
            except Exception as e:
                logger.warning(f"router: closing removed replica {rid} "
                               f"failed: {e}")
        return rep

    def _entry_roles(self):
        """Roles new requests dispatch to."""
        return ("prefill",) if self.disaggregated else ("mixed",)

    def _decode_roles(self):
        return ("decode", "mixed")

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def submit(self, request: Request,
               ttl_s: Optional[float] = None) -> Optional[CompletedRequest]:
        """Admit a request into the pool. Returns None when accepted; under
        admission_policy="shed" with a full router queue, returns the shed
        `CompletedRequest` (reason "cancelled") instead. Raises
        `InadmissibleRequestError` when NO replica's limits can ever fit
        the request."""
        if request.uid in self._pending or request.uid in self._done:
            raise ValueError(f"duplicate request uid {request.uid!r}")
        prompt_len = int(np.asarray(request.tokens).reshape(-1).shape[0])
        self._validate(request, prompt_len)
        now = self._clock()
        if len(self.queue) >= self.config.max_pending:
            if self.config.admission_policy == "shed":
                self._count("shed")
                if self.flightrec.enabled:
                    self.flightrec.record("shed", uid=request.uid,
                                          queued=len(self.queue))
                done = CompletedRequest(uid=request.uid,
                                        prompt_len=prompt_len,
                                        tokens=np.zeros((0,), np.int32),
                                        finish_reason="cancelled")
                self._done.add(request.uid)
                return done
            # "block": drive the pool until the queue drains below the cap;
            # finished requests land in the buffer the next step() returns
            while len(self.queue) >= self.config.max_pending:
                before = self._progress_mark()
                self._finished_buf.extend(self._step_inner())
                if self._progress_mark() == before:
                    self._await_restart_or_raise(
                        "router admission blocked with no possible progress "
                        f"(queue={len(self.queue)}, live replicas="
                        f"{len(self._healthy())})")
                    continue
        ttl = ttl_s if ttl_s is not None else self.config.default_ttl_s
        hashes = None
        for rep in self._healthy(self._entry_roles()):
            try:
                hashes = rep.hash_chain(request.tokens)
                break
            except ReplicaUnavailableError as e:
                self._quarantine(rep.replica_id, e)
        trace = None
        if self.tracer.enabled:
            # the router owns the trace: root span = submit -> completion,
            # closed in _complete (a failover in between stays inside it)
            trace = self.tracer.start(request.uid, t0=now, owner="router")
            # indexed past completion: remote replica spans arrive on the
            # pull cadence and must re-parent under this trace id even
            # after the root closed
            self._trace_index[request.uid] = trace
            while len(self._trace_index) > self._trace_index_cap:
                self._trace_index.popitem(last=False)
        self._pending[request.uid] = _Pending(
            request=request, prompt_len=prompt_len, hashes=hashes,
            t_submit=now, deadline=(now + ttl) if ttl is not None else None,
            trace=trace,
            # the HARD deadline anchors here, once: every re-dispatch
            # (failover rerun, hedge duplicate) passes the same absolute
            # value down, so recovery never silently extends the budget
            deadline_at=(now + float(request.deadline_ms) / 1e3)
            if request.deadline_ms is not None else None)
        self.queue.append(request.uid)
        self._count("submitted")
        return None

    def _validate(self, request, prompt_len):
        """At least one replica on each leg must be able to EVER fit the
        request; otherwise fail fast at the router instead of wedging.
        The decode leg is checked against the WORST-CASE prefill-side
        padding: a handoff target adopts a slot padded on the prefill
        replica's chunk grid, so validating it against its own (possibly
        finer) grid would admit requests no target can ever adopt."""
        legs = [(self._entry_roles(), self.disaggregated, None)]
        if self.disaggregated:
            chunks = [r.prefill_chunk for r in self._healthy(("prefill",))]
            padded = max(-(-prompt_len // c) * c for c in chunks) \
                if chunks else None
            legs.append((self._decode_roles(), False, padded))
        for roles, prefill_only, padded in legs:
            reps = self._healthy(roles)
            if not reps:
                raise RuntimeError(
                    f"router has no healthy replica for roles {roles} "
                    f"(pool={list(self.replicas)}, dead={sorted(self._dead)})")
            last_err = None
            answered = False
            for rep in reps:
                try:
                    rep.check_admissible(prompt_len, request.max_new_tokens,
                                         prefill_only=prefill_only,
                                         uid=request.uid,
                                         padded_prompt=padded)
                    last_err = None
                    answered = True
                    break
                except InadmissibleRequestError as e:
                    last_err = e
                    answered = True
                except ReplicaUnavailableError as e:
                    self._quarantine(rep.replica_id, e)
            if last_err is not None:
                raise last_err
            if not answered:
                raise RuntimeError(
                    f"router has no reachable replica for roles {roles} "
                    f"(pool={list(self.replicas)}, "
                    f"dead={sorted(self._dead)})")

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------

    def _affinity(self, rep: ReplicaHandle, hashes) -> int:
        """Blocks of the chain this replica already holds OR was already
        asked to build: max of the replica's registered prefix (read-only
        cache probe) and the router's anticipated chain for it."""
        if not hashes:
            return 0
        registered = rep.affinity(hashes)
        seen = self._anticipated[rep.replica_id]
        anticipated = 0
        for h in hashes:
            if h not in seen:
                break
            anticipated += 1
        return max(registered, anticipated)

    def _note_dispatch(self, rid, hashes):
        if not hashes:
            return
        seen = self._anticipated[rid]
        for h in hashes:
            if h in seen:
                seen.move_to_end(h)
            else:
                seen[h] = None
        while len(seen) > self._anticipated_cap:
            seen.popitem(last=False)

    def _choose(self, rec: _Pending):
        """Pick a dispatch target for a queued request, or None when every
        eligible replica is saturated (the request waits at the router).
        Returns (handle, affinity_blocks, score, spilled)."""
        cfg = self.config
        eligible = self._healthy(self._entry_roles())
        if not eligible:
            return None, 0, 0.0, False
        max_q = max(1, cfg.max_replica_queue)
        scored = []       # (handle, affinity, score, pending, saturated)
        for rep in eligible:
            try:
                need = rep.check_admissible(
                    rec.prompt_len, rec.request.max_new_tokens,
                    prefill_only=self.disaggregated, uid=rec.request.uid)
                aff = self._affinity(rep, rec.hashes)
                pending = rep.queue_depth + rep.num_active
                score = (aff * cfg.affinity_weight
                         - pending * cfg.load_penalty
                         - (cfg.block_penalty
                            if need > rep.available_blocks else 0))
                saturated = rep.queue_depth >= max_q
            except InadmissibleRequestError:
                continue
            except ReplicaUnavailableError as e:
                self._quarantine(rep.replica_id, e)
                continue
            scored.append((rep, aff, score, pending, saturated))
        if not scored:
            return None, 0, 0.0, False
        open_ = [s for s in scored if not s[4]]
        if not open_:
            return None, 0, 0.0, False
        if cfg.routing_policy == "round_robin":
            chosen = open_[self._rr % len(open_)]
            self._rr += 1
        else:
            order = {id(s): i for i, s in enumerate(open_)}
            chosen = min(open_, key=lambda s: (-s[2], s[3],
                                               (order[id(s)] - self._rr)
                                               % len(open_)))
            self._rr += 1
        best_aff = max(s[1] for s in scored)
        return chosen[0], chosen[1], chosen[2], chosen[1] < best_aff

    def _dispatch(self):
        """Drain the router queue head-first into replicas. Strict FIFO:
        the head not fitting anywhere right now keeps everything behind it
        queued (same no-starvation rule as the engine's own admission)."""
        while self.queue:
            uid = self.queue[0]
            rec = self._pending[uid]
            rep, aff, score, spilled = self._choose(rec)
            if rep is None:
                break
            self.queue.popleft()
            if self.tracer.enabled and rec.trace is not None:
                # dispatch is a zero-duration span (not an instant event)
                # so replica-side spans can NEST under it — a failover's
                # second dispatch then reads as a sibling subtree, not
                # interleaved with the first attempt. flow_begin opens the
                # Perfetto arrow the replica's admit closes on ITS track.
                t = self._clock()
                sid = self.tracer.record(
                    rec.trace, "dispatch", t, 0.0, tid=0,
                    parent=rec.trace.root_id,
                    attrs={"replica": rep.replica_id, "affinity": int(aff),
                           "score": round(float(score), 3)})
                rec.trace.parent_id = sid
                self.tracer.flow_begin(rec.trace, t, tid=0)
            if self.flightrec.enabled:
                self.flightrec.record(
                    "dispatch", uid=uid, replica=rep.replica_id,
                    affinity=int(aff), score=round(float(score), 3),
                    spilled=bool(spilled))
            try:
                rep.submit(rec.request, prefill_only=self.disaggregated,
                           hashes=rec.hashes, trace=rec.trace,
                           deadline_at=rec.deadline_at)
            except ReplicaUnavailableError as e:
                # died between scoring and submit: back to the queue head
                # (rec.replica is still None, so the quarantine sweep
                # doesn't double-requeue it), then re-choose
                self.queue.appendleft(uid)
                self._quarantine(rep.replica_id, e)
                continue
            rec.replica = rep.replica_id
            rec.t_dispatch = self._clock()
            self._note_dispatch(rep.replica_id, rec.hashes)
            if self.config.routing_policy == "affinity":
                if aff > 0:
                    self._count("affinity_hits")
                if spilled:
                    self._count("load_spills")

    # ------------------------------------------------------------------
    # TTL + completion + failover
    # ------------------------------------------------------------------

    def _sweep_ttl(self, now, finished):
        # hard deadlines first: a request still in the ROUTER queue past
        # its absolute budget completes with reason "deadline" (dispatched
        # requests are the engine's job — its sync-point sweep retires
        # them, and the completion flows back through step())
        dead = [uid for uid, rec in self._pending.items()
                if rec.deadline_at is not None and now >= rec.deadline_at
                and rec.replica is None]
        for uid in dead:
            rec = self._pending[uid]
            self.queue.remove(uid)
            self._count("deadline_cancelled")
            if self.flightrec.enabled:
                self.flightrec.record("deadline", uid=uid, queued=True)
            self._complete(CompletedRequest(
                uid=uid, prompt_len=rec.prompt_len,
                tokens=np.zeros((0,), np.int32),
                finish_reason="deadline"), finished)
        expired = [uid for uid, rec in self._pending.items()
                   if rec.deadline is not None and now >= rec.deadline
                   # a hedged request is by definition dispatched twice and
                   # possibly generating on either copy — TTL (queued-only
                   # semantics) leaves it to completion or its hard deadline
                   and rec.hedge_replica is None]
        for uid in expired:
            rec = self._pending.get(uid)
            if rec is None:                       # deadline-swept above
                continue
            if rec.replica is None:
                self.queue.remove(uid)
                done = CompletedRequest(uid=uid, prompt_len=rec.prompt_len,
                                        tokens=np.zeros((0,), np.int32),
                                        finish_reason="cancelled")
            else:
                # only queued-but-unstarted dies; a generating request runs
                # on (a slot PARKED for handoff counts as cancellable — it
                # holds exported blocks, see ServingEngine.cancel)
                try:
                    done = self.replicas[rec.replica].cancel(uid,
                                                             queued_only=True)
                except ReplicaUnavailableError as e:
                    # the replica died with the request on it: quarantine
                    # re-owns everything it held (this uid included)
                    self._quarantine(rec.replica, e)
                    continue
                if done is None:
                    continue
            self._count("ttl_cancelled")
            if self.flightrec.enabled:
                self.flightrec.record("ttl_cancel", uid=uid,
                                      replica=rec.replica or "")
            self._complete(done, finished)

    def _complete(self, done: CompletedRequest, finished, rid=None):
        if done.uid in self._done:
            if done.uid not in self._hedged:
                # a hedge loser finishing in the same router step as the
                # winner is the EXPECTED duplicate; anything else is a bug
                # worth a line in the log
                logger.warning(f"router: dropping duplicate completion for "
                               f"{done.uid!r}")
            return
        rec = self._pending.pop(done.uid, None)
        self._done.add(done.uid)
        self._count("completed")
        if rec is not None and rec.hedge_replica is not None:
            # first completion wins: cancel the other copy wherever it is
            # (it may be generating — full cancel, not queued_only), and
            # credit the hedge when the duplicate beat the primary
            winner = rid
            if winner == rec.hedge_replica:
                self._count("hedge_wins")
            for other in {rec.replica, rec.hedge_replica} - {winner}:
                if other in self.replicas and other not in self._dead \
                        and other not in self._quarantined:
                    try:
                        self.replicas[other].cancel(done.uid)
                    except Exception:
                        pass          # a dying loser gets quarantined later
            if self.flightrec.enabled:
                self.flightrec.record("hedge_resolved", uid=done.uid,
                                      winner=str(winner),
                                      won=winner == rec.hedge_replica)
        if rec is not None and rec.trace is not None:
            # close the root (whole-request e2e, router queue included)
            self.tracer.finish(rec.trace, self._clock(), tid=0,
                               attrs={"reason": done.finish_reason,
                                      "replica": rec.replica or ""})
        if rec is not None and rec.replica is not None:
            if done.timing and done.timing.get("first_token"):
                # ROUTER-level TTFT: first token relative to router arrival
                # (engine TTFT + router queue wait), tagged by replica
                ttft_ms = (done.timing["first_token"] - rec.t_submit) * 1e3
                self._ttft[rec.replica].append(ttft_ms)
                self.telemetry.observe(
                    f"router/replica/{rec.replica}/ttft_ms", ttft_ms)
        finished.append(done)

    def _quarantine(self, rid, reason):
        """Replica failed (step raised, or an operator killed it): pull its
        queued requests out, re-route EVERYTHING incomplete it owned (an
        in-flight request restarts from scratch — greedy decode makes the
        rerun token-identical), and schedule a restart if the budget
        allows."""
        if rid in self._quarantined or rid in self._dead:
            return          # already converged (several probes can trip on
                            # the same dead replica within one router step)
        rep = self.replicas[rid]
        self._count("replica_failures")
        self._draining.discard(rid)     # a dying drain becomes a plain crash
        logger.warning(f"router: quarantining replica {rid} ({reason!r})")
        try:
            rep.drain_queued()          # engine queue state is re-owned here
        except Exception:
            pass                        # a truly dead backend may not answer
        requeue = []
        for uid, rec in self._pending.items():
            if rec.hedge_replica == rid:
                rec.hedge_replica = None       # the duplicate died with it
            elif rec.replica == rid and rec.hedge_replica is not None:
                # the primary died but its hedge is alive and already
                # running the same request — promote it instead of a
                # from-scratch rerun
                rec.replica, rec.hedge_replica = rec.hedge_replica, None
            elif rec.replica == rid:
                requeue.append(uid)
        t = self._clock()
        for uid in requeue:
            rec = self._pending[uid]
            rec.replica = None
            rec.t_dispatch = None
            if self.tracer.enabled and rec.trace is not None:
                # a dispatch arrow the dead replica never admitted would
                # dangle as an orphan "s" event — terminate it at the
                # reroute mark on the router track instead (no-op when
                # admission already consumed it)
                self.tracer.flow_end(rec.trace, t, tid=0)
                # re-parent future spans back under the root: the NEXT
                # dispatch opens a fresh subtree, and this mark is the
                # visible seam between the two attempts — ONE trace id
                # throughout, which is the failover-continuity contract
                rec.trace.parent_id = rec.trace.root_id
                self.tracer.event(rec.trace, "reroute", t, tid=0,
                                  attrs={"from": rid,
                                         "reason": str(reason)[:120]})
        self.queue.extendleft(reversed(requeue))
        self._count("reroutes", len(requeue))
        self._anticipated[rid].clear()   # its pool (and cache) is gone
        self._strikes[rid] = 0           # the watchdog starts fresh post-restart
        budget = self._budgets[rid]
        if rep.can_restart and budget.consume("crash"):
            self._quarantined[rid] = self._clock() + budget.next_delay()
        else:
            self._dead.add(rid)
            logger.error(f"router: replica {rid} is out of restart budget; "
                         f"pool shrinks to {len(self._healthy())}")
        # drain the dying replica's last observability spool BEFORE the
        # dump so its final spans/flight events make it into the black box
        # (over the wire if the server still answers; from its on-disk
        # spool file when the process is already gone)
        postmortem = self._postmortem_drain(rid)
        if self.flightrec.enabled:
            # the black-box moment this whole subsystem exists for: the
            # quarantine event joins the ring, then the ring + a full
            # router/replica state snapshot hit disk
            self.flightrec.record("quarantine", replica=rid,
                                  reason=str(reason)[:200],
                                  requeued=len(requeue),
                                  dead=rid in self._dead)
            state = self._failure_snapshot()
            if postmortem is not None and isinstance(state, dict):
                state["postmortem"] = postmortem
            self.flightrec.dump(f"replica {rid} failed: {reason}",
                                state=state)

    def _failure_snapshot(self):
        """stats() guarded for the dump path — a half-dead pool must still
        produce a black box, even if some replica's stats() throws."""
        try:
            return self.stats()
        except Exception as e:
            return {"error": f"stats() failed during dump: {e}"}

    def _maybe_restart(self, now):
        for rid, t in list(self._quarantined.items()):
            if now < t:
                continue
            del self._quarantined[rid]
            try:
                self.replicas[rid].restart()
                self._count("replica_restarts")
                # a rebuilt engine starts detached from the pool's
                # tracer/recorder (and from its Perfetto track) AND from
                # the pool clock — re-inject both
                if self._clock_injected:
                    self.replicas[rid].set_clock(self._clock)
                self._attach_observability(rid)
                if self.flightrec.enabled:
                    self.flightrec.record(
                        "restart", replica=rid,
                        nth=self._budgets[rid].restarts)
                log_dist(f"router: replica {rid} restarted "
                         f"(#{self._budgets[rid].restarts})", ranks=[0])
            except Exception as e:
                self._quarantine(rid, e)

    def kill_replica(self, rid):
        """Operator/test hook: fail a replica NOW (fault injection, drain
        for maintenance). Everything it owned re-routes; restart follows
        the per-replica budget like any crash."""
        if rid not in self.replicas:
            raise KeyError(f"unknown replica {rid!r}")
        if rid in self._dead or rid in self._quarantined:
            return
        self._quarantine(rid, "killed")

    # ------------------------------------------------------------------
    # hung-replica watchdog + hedged dispatch
    # ------------------------------------------------------------------

    def _watchdog_check(self, rid, rep, t0):
        """Per-step() deadline with a strike budget: one slow step is
        noise, `step_strike_budget` IN A ROW earns a health probe, and a
        failed probe converges on the same quarantine/drain/reroute path
        an exception takes. A fast step resets the count — 'slow' and
        'dead' stay distinguishable."""
        if self.config.step_deadline_ms is None:
            return
        dt_ms = (self._clock() - t0) * 1e3
        if dt_ms <= self.config.step_deadline_ms:
            self._strikes[rid] = 0
            return
        self._strikes[rid] += 1
        self._count("watchdog_strikes")
        if self.flightrec.enabled:
            self.flightrec.record("watchdog_strike", replica=rid,
                                  step_ms=round(dt_ms, 3),
                                  strikes=self._strikes[rid])
        if self._strikes[rid] < max(1, self.config.step_strike_budget):
            return
        alive = False
        try:
            alive = bool(rep.health_probe())
        except Exception:
            pass
        if alive:
            self._strikes[rid] = 0      # slow but answering: keep serving
            return
        self._count("watchdog_quarantines")
        self._quarantine(rid, ReplicaHungError(
            f"replica {rid}: {self._strikes[rid]} consecutive steps over "
            f"{self.config.step_deadline_ms}ms and health probe failed"))

    def _hedge_target(self, rec):
        """A healthy entry replica (≠ primary) with room to take the
        duplicate right now — free slot or shallow queue, and the request
        admissible there."""
        for rep in self._healthy(self._entry_roles()):
            if rep.replica_id == rec.replica:
                continue
            try:
                if not (rep.has_free_slot
                        or rep.queue_depth < self.config.max_replica_queue):
                    continue
                rep.check_admissible(rec.prompt_len,
                                     rec.request.max_new_tokens,
                                     prefill_only=self.disaggregated,
                                     uid=rec.request.uid)
            except InadmissibleRequestError:
                continue
            except ReplicaUnavailableError as e:
                self._quarantine(rep.replica_id, e)
                continue
            return rep
        return None

    def _maybe_hedge(self, now):
        """Deadline-aware hedged retries: a dispatched request with no
        first token after `hedge_after_ms` gets ONE speculative duplicate
        on another replica with capacity. First completion wins
        (`_complete` cancels the loser and de-dups); the duplicate carries
        the same absolute hard deadline, so hedging never extends a
        budget. The duplicate carries no router trace context — the
        primary owns the request's root span tree (with tracing on, the
        hedge replica records it as a separate engine-owned trace).

        MIXED pools only: in a disaggregated pool a hedged request would
        park TWO prefill-complete copies in _HANDOFF, and the handoff
        bookkeeping tracks one decode home per uid — the second transplant
        would clobber it and strand the loser's slot for the whole
        generation. Hung prefill replicas there are the watchdog's job."""
        if self.disaggregated:
            return
        wait = float(self.config.hedge_after_ms) / 1e3
        for uid, rec in list(self._pending.items()):
            if (rec.replica is None or rec.hedge_replica is not None
                    or rec.t_dispatch is None
                    or now - rec.t_dispatch < wait):
                continue
            primary = self.replicas.get(rec.replica)
            if primary is None:
                continue
            try:
                if primary.has_output(uid):
                    continue            # first token arrived: no hedge
            except Exception:
                pass                    # unanswerable primary: hedge away
            rep = self._hedge_target(rec)
            if rep is None:
                continue
            try:
                rep.submit(rec.request, prefill_only=self.disaggregated,
                           hashes=rec.hashes, trace=None,
                           deadline_at=rec.deadline_at)
            except ReplicaUnavailableError as e:
                self._quarantine(rep.replica_id, e)
                continue
            rec.hedge_replica = rep.replica_id
            self._hedged.add(uid)
            self._note_dispatch(rep.replica_id, rec.hashes)
            self._count("hedges")
            if self.flightrec.enabled:
                self.flightrec.record(
                    "hedge", uid=uid, primary=rec.replica,
                    hedge=rep.replica_id,
                    waited_ms=round((now - rec.t_dispatch) * 1e3, 3))

    # ------------------------------------------------------------------
    # disaggregated handoff
    # ------------------------------------------------------------------

    def _do_handoffs(self):
        """Move prefill-complete slots into decode replicas: allocate at the
        target, transplant the blocks, release the source. A target without
        room right now leaves the slot parked (prefill-side backpressure)."""
        targets = self._healthy(self._decode_roles())
        # a DRAINING prefill replica still unloads its parked slots (that
        # is what draining means); a draining decode replica takes no more
        for prep in self._healthy(("prefill",), include_draining=True):
            for uid in prep.handoff_ready():
                rec = self._pending.get(uid)
                if rec is None:        # cancelled while parked
                    prep.release_handoff(uid)
                    continue
                cands = sorted(targets, key=lambda r: (not r.has_free_slot,
                                                       r.queue_depth +
                                                       r.num_active))
                state = prep.export_handoff(uid)
                for drep in cands:
                    try:
                        ok = drep.receive_handoff(state, prep.pool)
                    except InadmissibleRequestError:
                        # THIS target can never fit it; submit-time
                        # validation guarantees some decode replica can
                        continue
                    if ok:
                        prep.release_handoff(uid)
                        rec.replica = drep.replica_id
                        self._count("handoffs")
                        if self.tracer.enabled and rec.trace is not None:
                            # one flow arrow prefill-track -> decode-track:
                            # the transplant renders as a connected hop
                            t = self._clock()
                            src = self._tids.get(prep.replica_id, 0)
                            dst = self._tids.get(drep.replica_id, 0)
                            self.tracer.flow_begin(rec.trace, t, tid=src)
                            sid = self.tracer.record(
                                rec.trace, "kv_handoff", t, 0.0, tid=0,
                                parent=rec.trace.root_id,
                                attrs={"from": prep.replica_id,
                                       "to": drep.replica_id})
                            rec.trace.parent_id = sid
                            self.tracer.flow_end(rec.trace, t, tid=dst)
                        if self.flightrec.enabled:
                            self.flightrec.record(
                                "handoff", uid=uid, src=prep.replica_id,
                                dst=drep.replica_id)
                        break

    # ------------------------------------------------------------------
    # the router step
    # ------------------------------------------------------------------

    def _step_inner(self) -> List[CompletedRequest]:
        finished: List[CompletedRequest] = []
        now = self._clock()
        self.steps += 1
        self._sweep_ttl(now, finished)
        self._maybe_restart(now)
        self._dispatch()
        for rid in list(self.replicas):
            if rid in self._quarantined or rid in self._dead:
                continue
            rep = self.replicas[rid]
            t0 = self._clock()
            try:
                for done in rep.step():
                    self._complete(done, finished, rid=rid)
            except Exception as e:
                self._quarantine(rid, e)
                continue
            self._watchdog_check(rid, rep, t0)
        if self.config.hedge_after_ms is not None:
            self._maybe_hedge(self._clock())
        if self.disaggregated:
            self._do_handoffs()
        if self.telemetry.enabled:
            self.telemetry.set_gauge("router/queue_depth", len(self.queue))
            self.telemetry.set_gauge("router/in_flight", len(self._pending))
            self.telemetry.set_gauge("router/live_replicas",
                                     len(self._healthy()))
            mem = self.memory_snapshot()
            if mem:
                # pool-aggregate memory ledger (replicas run with
                # telemetry.memscope): total attributed HBM across live
                # replicas, and the TIGHTEST per-replica headroom — the
                # pool is as close to OOM as its fullest member
                self.telemetry.set_gauge("mem/pool_attributed_bytes",
                                         mem["attributed_bytes"])
                if mem.get("headroom_frac") is not None:
                    self.telemetry.set_gauge("mem/pool_headroom_frac",
                                             mem["headroom_frac"])
            # observability pulls piggyback on the export cadence: one
            # pull per replica per export_interval steps, so the wire
            # cost scales with the export rate the operator already chose
            interval = max(1, int(getattr(self.telemetry.config,
                                          "export_interval", 1)))
            if self.steps % interval == 0:
                self._observability_pull_all()
            self.telemetry.maybe_export(self.steps)
        return finished

    def step(self) -> List[CompletedRequest]:
        """One router iteration: TTL sweep -> restarts -> dispatch -> step
        every live replica -> handoffs. Returns every request that finished
        since the last call (including ones finished inside a blocking
        submit)."""
        out = self._finished_buf
        self._finished_buf = []
        out.extend(self._step_inner())
        return out

    @property
    def in_flight(self) -> int:
        """Incomplete requests anywhere in the pool (router queue +
        dispatched)."""
        return len(self._pending)

    def _await_restart_or_raise(self, msg):
        """Stalled with recovery still possible — a replica restart pending
        backoff, or a dispatched-but-silent request whose hedge window has
        not expired yet (a hung primary makes no progress while the hedge
        timer runs) — sleep until the clock reaches it. An INJECTED clock
        that never advances would spin forever here, so a non-moving clock
        raises instead of hanging."""
        if not (self._quarantined or self._hedge_may_fire()):
            raise RuntimeError(msg)
        t0 = self._clock()
        time.sleep(0.005)
        if self._clock() <= t0:
            raise RuntimeError(
                msg + " (a replica restart or hedge is scheduled but the "
                "injected clock never advances — advance it or use "
                "backoff 0)")

    def _hedge_may_fire(self):
        """True while some dispatched request could still earn a hedge —
        the watchdog-off recovery path: the pool looks stalled until
        `hedge_after_ms` elapses, but it is WAITING, not wedged."""
        if self.config.hedge_after_ms is None or self.disaggregated:
            return False                 # _maybe_hedge's mixed-pool gate
        return any(rec.replica is not None and rec.hedge_replica is None
                   for rec in self._pending.values())

    def _progress_mark(self):
        live = self._healthy(include_draining=True)
        work = 0
        for r in live:
            try:
                work += r.progress()
            except ReplicaUnavailableError:
                pass        # its death registers as a quarantine next step
        # hedges count as progress: the launch itself changes no queue or
        # token counter until the target's next admission, and run() must
        # not mistake that one-step gap for a wedged pool
        return (len(self.queue), len(self._pending), len(self._done), work,
                len(live), len(self._quarantined), self.counters["hedges"])

    def run(self, requests: Sequence[Request],
            ttl_s: Optional[float] = None) -> Dict[Any, CompletedRequest]:
        """Submit a batch and drain the pool. Shed/TTL-cancelled requests
        appear in the result with ``finish_reason="cancelled"``."""
        out: Dict[Any, CompletedRequest] = {}
        for r in requests:
            shed = self.submit(r, ttl_s=ttl_s)
            if shed is not None:
                out[shed.uid] = shed
        while self.in_flight or self._finished_buf:
            before = self._progress_mark()
            for done in self.step():
                out[done.uid] = done
            if self._progress_mark() == before:
                self._await_restart_or_raise(
                    f"router made no progress: queue={len(self.queue)} "
                    f"in_flight={self.in_flight} "
                    f"live={len(self._healthy())} dead={sorted(self._dead)}")
        if self.telemetry.enabled:
            self.telemetry.export(self.steps)
        return out

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def _count(self, name, n=1):
        self.counters[name] += n
        self.telemetry.inc(f"router/{name}", n)

    # ---- the pod observability plane (pull side) ----------------------

    def _observability_pull_all(self):
        """Pull every live replica's observability state (piggybacks on
        the telemetry export cadence in `_step_inner`; `observability_
        snapshot(refresh=True)` calls it on demand)."""
        for rid, rep in list(self.replicas.items()):
            if rid in self._dead or rid in self._quarantined:
                continue
            self._observability_pull_one(rid, rep)

    def _observability_pull_one(self, rid, rep):
        cursor = self._obs_cursors.get(rid, 0)
        try:
            reply = rep.observability_pull(cursor=cursor)
        except ReplicaUnavailableError:
            return      # liveness owns the death; the post-mortem drain
                        # recovers the spool tail at quarantine time
        except Exception as e:
            logger.warning(f"router: observability pull from {rid} "
                           f"failed: {e}")
            return
        if not reply or not reply.get("enabled"):
            return
        spans, events = self._ingest_items(rid, reply.get("items") or ())
        # cursor advances ONLY here, after a successful ingest — a pull
        # lost on the wire (and transparently retried: the verb is
        # idempotent) or one that raised above re-asks from the same
        # cursor and the spool answers with identical items
        self._obs_cursors[rid] = int(reply.get("cursor", cursor))
        metrics = reply.get("metrics")
        if metrics is not None:
            # REPLACE, never accumulate: the reply carries the replica's
            # full registry snapshot, so re-pulls cannot double-count
            self._obs_metrics[rid] = metrics
        info = self._obs_info.setdefault(rid, {})
        for key in ("spool_path", "pid"):
            if reply.get(key) is not None:
                info[key] = reply[key]
        info["dropped"] = int(reply.get("dropped", 0))
        if self.telemetry.enabled:
            self.telemetry.inc("obs/pulls")
            if spans:
                self.telemetry.inc("obs/pull_spans", spans)
            if events:
                self.telemetry.inc("obs/pull_events", events)
            if "pid" in reply:      # a wire pull (in-process pulls are free)
                self.telemetry.inc("obs/pull_bytes",
                                   len(json.dumps(reply, default=str)))

    def _ingest_items(self, rid, items):
        spans = events = 0
        for it in items:
            kind = it.get("kind")
            rec = it.get("rec") or {}
            if kind == "span":
                self._import_span(rid, rec)
                spans += 1
            elif kind == "flight":
                self._import_flight(rid, rec)
                events += 1
        return spans, events

    def _import_span(self, rid, rec):
        """Re-parent one remote span into the pool trace: the replica's
        span/parent ids map onto fresh router-tracer ids (consistent
        across pulls), its engine-owned root re-parents under the router's
        root for the same uid, and the span lands on the replica's named
        Perfetto track. Timestamps cross untranslated — every process on
        the host reads the same CLOCK_MONOTONIC (the tracer's documented
        clock domain)."""
        if not self.tracer.enabled:
            return
        tracer = self.tracer
        tid = self._tids.get(rid, 0)
        ctx = self._trace_index.get(rec.get("uid"))
        remap = self._obs_remap.setdefault(rid, {})

        def local_id(remote_id):
            sid = remap.get(remote_id)
            if sid is None:
                sid = next(tracer._ids)
                remap[remote_id] = sid
            return sid

        sid = local_id(rec.get("span"))
        remote_parent = rec.get("parent", 0)
        if remote_parent == 0:
            # the remote engine's root span ("request" on its side)
            # becomes a child of the router's root — ONE trace id from
            # dispatch to completion
            parent = ctx.root_id if ctx is not None else 0
        else:
            parent = local_id(remote_parent)
        if ctx is not None and ctx.flow_id is not None:
            # the dispatch arrow the router opened was never consumed
            # in-process (the replica is remote): close it at the first
            # span arriving on the replica's track
            tracer.flow_end(ctx, rec.get("ts", 0.0), tid=tid)
        shim = ctx if ctx is not None else TraceContext(
            trace_id=f"{rid}:{rec.get('trace')}", root_id=sid,
            uid=rec.get("uid"))
        tracer.record(shim, rec.get("name", "?"), rec.get("ts", 0.0),
                      rec.get("dur", 0.0), tid=tid,
                      attrs=dict(rec.get("attrs") or {}, src=rid),
                      parent=parent, span_id=sid)

    def _import_flight(self, rid, ev):
        """Land one remote flight event in the pool ring, wrapped (kind
        "remote", original event nested) so remote and router field names
        can never collide."""
        if self.flightrec.enabled:
            self.flightrec.record("remote", src=rid, event=dict(ev))

    def _postmortem_drain(self, rid) -> Optional[Dict[str, Any]]:
        """Recover a dying replica's final spool for the quarantine dump:
        a last wire pull while the server still answers, else a direct
        read of its on-disk spool file (the `kill -9` path — the file
        survives the process). Recovered spans join the pool trace;
        recovered flight events ride in the returned summary, which the
        dump embeds as `state["postmortem"]`."""
        if not (self.tracer.enabled or self.flightrec.enabled):
            return None
        rep = self.replicas.get(rid)
        if rep is None:
            return None
        cursor = self._obs_cursors.get(rid, 0)
        items, source = None, None
        try:
            reply = rep.observability_pull(cursor=cursor)
            if reply and reply.get("enabled"):
                items = reply.get("items") or []
                source = "wire"
                if reply.get("metrics") is not None:
                    self._obs_metrics[rid] = reply["metrics"]
        except Exception:
            items = None
        if items is None:
            info = self._obs_info.get(rid, {})
            path = info.get("spool_path") \
                or getattr(rep, "obs_spool_path", None)
            if path:
                from deepspeed_tpu.serving.observability import \
                    read_spool_file
                items = read_spool_file(path, after_cursor=cursor)
                source = "spool_file"
        if not items:
            return None
        spans, events = self._ingest_items(rid, items)
        self._obs_cursors[rid] = max(
            [cursor] + [int(it.get("cursor", 0)) for it in items])
        if self.telemetry.enabled:
            self.telemetry.inc("obs/postmortem_recovered", len(items))
        return {"replica": rid, "source": source,
                "spans": spans,
                "flight_events": [it.get("rec") for it in items
                                  if it.get("kind") == "flight"]}

    def pool_latency(self, merged=None) -> Dict[str, Dict[str, float]]:
        """Pool-level latency percentiles from MERGED per-replica
        histograms — exact (bucket-wise merge over identical log-scale
        buckets), unlike any aggregation of per-replica percentiles.
        This is the pool-level latency source; `replica_ttft` stays
        per-replica."""
        if merged is None:
            merged = self.pool_metrics()
        out = {}
        for name in ("serving/ttft_ms", "serving/tpot_ms",
                     "serving/queue_wait_ms", "serving/e2e_ms"):
            snap = merged.get(name)
            if snap and snap.get("type") == "histogram":
                out[name] = {k: snap[k] for k in
                             ("count", "mean", "p50", "p90", "p99")}
        return out

    def pool_metrics(self) -> Dict[str, Any]:
        """The merged pool snapshot over the most recently pulled
        per-replica registries (counters summed, gauges per-source,
        histograms bucket-merged)."""
        per = {rid: snap for rid, snap in self._obs_metrics.items()
               if rid in self.replicas}
        return merge_snapshots(per) if per else {}

    def observability_snapshot(self, refresh: bool = True) -> Dict[str, Any]:
        """The one pool-level view `bin/dstpu_top` renders: merged metric
        snapshot + pool latency percentiles, per-replica health/load/
        degradation/headroom, router counters, and the recent flight
        events. `refresh=False` serves the cached state from the last
        pull cadence instead of issuing fresh pulls."""
        if refresh:
            self._observability_pull_all()
        merged = self.pool_metrics()
        replicas: Dict[str, Any] = {}
        for rid, rep in self.replicas.items():
            health = ("dead" if rid in self._dead else
                      "quarantined" if rid in self._quarantined else
                      "draining" if rid in self._draining else "up")
            entry: Dict[str, Any] = {"role": rep.role, "health": health,
                                     "restarts": self._budgets[rid].restarts}
            if health in ("up", "draining"):
                try:
                    entry.update(queue=rep.queue_depth,
                                 active=rep.num_active,
                                 available_blocks=rep.available_blocks,
                                 has_free_slot=rep.has_free_slot)
                except ReplicaUnavailableError as e:
                    entry["health"] = "unreachable"
                    entry["error"] = str(e)[:200]
            snap = self._obs_metrics.get(rid) or {}
            for label, metric in (("degradation_level",
                                   "serving/degradation_level"),
                                  ("headroom_frac", "mem/headroom_frac")):
                g = snap.get(metric)
                if g is not None:
                    entry[label] = g.get("value")
            if rid in self._obs_info:
                entry["obs"] = dict(self._obs_info[rid])
            replicas[rid] = entry
        return {"steps": self.steps,
                "queue_depth": len(self.queue),
                "in_flight": len(self._pending),
                "live_replicas": len(self._healthy()),
                "counters": dict(self.counters),
                "pool_latency": self.pool_latency(merged),
                "pool_metrics": merged,
                "replicas": replicas,
                "flight_events": self.flightrec.events()[-32:]
                if self.flightrec.enabled else []}

    @staticmethod
    def _percentile(values, q):
        if not values:
            return None
        v = sorted(values)
        return float(v[min(len(v) - 1, int(q * len(v)))])

    def replica_ttft(self, rid) -> Dict[str, float]:
        """Router-level TTFT percentiles for ONE replica (ms), over the
        last `_ttft_window` completions. Populated only when the replicas
        run with telemetry enabled (the engine stamps first-token times).

        .. deprecated:: as a pool-level latency source. A single
           replica's p99 is not the pool's p99 — and no combination of
           per-replica percentiles is. Read `stats()["pool_latency"]`
           (or `pool_latency()`) instead: exact percentiles over the
           bucket-wise-merged pool histograms."""
        v = list(self._ttft.get(rid, ()))
        return {"count": len(v),
                "p50": self._percentile(v, 0.50),
                "p99": self._percentile(v, 0.99)}

    def memory_snapshot(self) -> Dict[str, Any]:
        """Aggregate the live replicas' HBM ledgers (memscope snapshots):
        replica-owned byte categories (params, pools, temps) summed across
        the pool, headroom as the MINIMUM per-replica fraction (the
        binding constraint), and allocator-global watermarks
        (bytes_in_use/peak/capacity/unattributed) as the MAX — in-process
        replicas all read the same device allocator, so summing those
        would multiply one device by the replica count. Per-replica
        detail under "replicas". {} when no replica runs with
        `telemetry.memscope`."""
        per: Dict[str, Dict[str, Any]] = {}
        for rid, rep in self.replicas.items():
            if rid in self._dead or rid in self._quarantined:
                continue
            try:
                snap = rep.memory_snapshot()
            except Exception:
                snap = None
            if snap:
                per[rid] = snap
        if not per:
            return {}
        device_global = {"bytes_in_use", "peak_bytes", "capacity_bytes",
                         "unattributed_bytes"}
        out: Dict[str, Any] = {"replicas": per}
        for snap in per.values():
            for k, v in snap.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                if k == "headroom_frac":
                    cur = out.get(k)
                    out[k] = v if cur is None else min(cur, v)
                elif k in device_global:
                    out[k] = max(out.get(k, 0), v)
                else:
                    out[k] = out.get(k, 0) + v
        return out

    def stats(self) -> Dict[str, Any]:
        """RouterStats: routing-decision counters, queue depth, and a
        per-replica block (role/health/load/TTFT + the engine's own
        stats())."""
        reps = {}
        for rid, rep in self.replicas.items():
            health = ("dead" if rid in self._dead else
                      "quarantined" if rid in self._quarantined else
                      "draining" if rid in self._draining else "up")
            entry = {"role": rep.role, "health": health,
                     "restarts": self._budgets[rid].restarts,
                     "ttft_ms": self.replica_ttft(rid)}
            if health in ("up", "draining"):
                try:
                    entry.update(queue=rep.queue_depth,
                                 active=rep.num_active,
                                 available_blocks=rep.available_blocks,
                                 engine=rep.stats())
                except ReplicaUnavailableError as e:
                    # stats() must never crash on a half-dead pool — the
                    # flight-recorder dump path depends on it
                    entry["health"] = "unreachable"
                    entry["error"] = str(e)[:200]
            reps[rid] = entry
        out = {"steps": self.steps, "queue_depth": len(self.queue),
               "in_flight": len(self._pending),
               "counters": dict(self.counters),
               "disaggregated": self.disaggregated,
               "replicas": reps}
        # pool-level latency from MERGED histograms (cached pulls — no
        # wire traffic here: stats() runs inside failure paths); {} until
        # the first pull cadence fires or when replicas run telemetry-off
        pool = self.pool_latency()
        if pool:
            out["pool_latency"] = pool
        mem = self.memory_snapshot()
        if mem:
            out["memory"] = mem
        return out

    def audit_pool(self, repair: bool = False) -> Dict[str, Any]:
        """Run the KV-pool invariant auditor on every LIVE replica (the
        chaos soak's final check, and an operator probe between waves).
        Returns rid -> `AuditReport`; replicas with no in-process pool to
        audit (remote backends) are skipped. With `repair=True` a dirty
        pool is rebuilt from its slot tables in place; a replica whose
        repair cannot reach a clean state raises through the caller —
        quarantine it with `kill_replica` if serving must continue."""
        out: Dict[str, Any] = {}
        for rep in self._healthy():
            report = rep.audit(repair=repair)
            if report is not None:
                out[rep.replica_id] = report
        return out

    def dump_flight_recorder(self, reason="operator dump"):
        """Write the black box NOW (operator/test hook). For out-of-band
        dumps wire `router.flightrec.install_signal_handler(
        state_fn=router.stats)` and send SIGUSR2."""
        return self.flightrec.dump(reason, state=self._failure_snapshot())

    def total_prefill_chunks(self) -> int:
        """Prefill chunks executed across live replicas — the quantity
        affinity routing minimizes on shared-prefix traffic."""
        total = 0
        for r in self._healthy(include_draining=True):
            try:
                total += r.stats()["prefill_chunks"]
            except ReplicaUnavailableError:
                pass
        return total
