"""Experiment monitors — TensorBoard / WandB / CSV behind one interface.

Reference: `deepspeed/monitor/monitor.py:29` (`MonitorMaster` fanning out to
TensorBoardMonitor/WandbMonitor/csvMonitor, configs `monitor/config.py:15-63`).
Events are `(tag, value, step)` tuples, written only from process 0.
"""

import csv
import os
import pathlib

from deepspeed_tpu.utils.logging import logger


def _rank():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


class Monitor:
    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, event_list):
        raise NotImplementedError

    def close(self):
        """Release writer resources; safe to call more than once."""


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if self.enabled and _rank() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                log_dir = os.path.join(config.output_path or "./runs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except Exception as e:
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()

    def close(self):
        if self.summary_writer is not None:
            try:
                self.summary_writer.close()
            except Exception as e:
                logger.warning(f"tensorboard close failed: {e}")
            self.summary_writer = None


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.run = None
        if self.enabled and _rank() == 0:
            try:
                import wandb
                self.run = wandb.init(project=config.project, group=config.group,
                                      entity=config.team)
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list):
        if self.run is None:
            return
        import wandb
        for i, (name, value, step) in enumerate(event_list):
            # never-die: a dropped network must not crash the caller (same
            # contract write_events_safe documents — but wandb is the only
            # backend that talks to a REMOTE service per event, so it guards
            # its own loop too: callers going through MonitorMaster directly
            # are just as exposed)
            try:
                wandb.log({name: value}, step=step)
            except Exception as e:
                logger.warning(f"wandb log failed ({e}); dropping the "
                               f"remaining {len(event_list) - i} events")
                break

    def close(self):
        if self.run is not None:
            try:
                self.run.finish()
            except Exception as e:
                logger.warning(f"wandb finish failed: {e}")
            self.run = None


class CsvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self._files = {}    # tag -> (handle, csv.writer): opened once per tag
        if self.enabled and _rank() == 0:
            self.output_path = pathlib.Path(config.output_path or "./csv_monitor") / config.job_name
            self.output_path.mkdir(parents=True, exist_ok=True)
        else:
            self.enabled = False

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            entry = self._files.get(name)
            if entry is None:
                fname = self.output_path / (name.replace("/", "_") + ".csv")
                new = not fname.exists()
                f = open(fname, "a", newline="")
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                entry = self._files[name] = (f, w)
            f, w = entry
            w.writerow([step, value])
            f.flush()

    def close(self):
        for f, _w in self._files.values():
            try:
                f.close()
            except Exception:
                pass
        self._files = {}

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def write_events_safe(monitor, event_list):
    """Best-effort event emission: the ONE guarded entry point for every
    caller that must never die on a monitoring failure — checkpoint/recovery
    paths (Checkpoint/save_ms, Recovery/restarts_total by cause, ...), the
    serving scheduler (Serving/*), and the telemetry monitor bridge. These
    run from contexts where no monitor may exist at all (async save
    finalizer threads, the elastic agent supervisor), so both the lookup and
    the write are guarded, unlike MonitorMaster.write_events."""
    if monitor is None or not getattr(monitor, "enabled", False):
        return
    try:
        monitor.write_events(list(event_list))
    except Exception as e:
        logger.warning(f"monitor event emission failed: {e}")


# Historical aliases (PR 2 recovery events, PR 4 serving events) — one
# implementation, kept importable under both names.
write_recovery_events = write_events_safe
write_serving_events = write_events_safe


class MonitorMaster(Monitor):
    """Fans events out to every enabled monitor (reference same name)."""

    def __init__(self, ds_config):
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = CsvMonitor(ds_config.csv_monitor)
        self.enabled = (self.tb_monitor.enabled or self.wandb_monitor.enabled
                        or self.csv_monitor.enabled)

    def write_events(self, event_list):
        if _rank() != 0:
            return
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if m.enabled:
                m.write_events(event_list)

    def close(self):
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            try:
                m.close()
            except Exception:
                pass
