"""Experiment monitors — TensorBoard / WandB / CSV behind one interface.

Reference: `deepspeed/monitor/monitor.py:29` (`MonitorMaster` fanning out to
TensorBoardMonitor/WandbMonitor/csvMonitor, configs `monitor/config.py:15-63`).
Events are `(tag, value, step)` tuples, written only from process 0.
"""

import csv
import os
import pathlib

from deepspeed_tpu.utils.logging import logger


def _rank():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


class Monitor:
    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, event_list):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.summary_writer = None
        if self.enabled and _rank() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                log_dir = os.path.join(config.output_path or "./runs", config.job_name)
                self.summary_writer = SummaryWriter(log_dir=log_dir)
            except Exception as e:
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.run = None
        if self.enabled and _rank() == 0:
            try:
                import wandb
                self.run = wandb.init(project=config.project, group=config.group,
                                      entity=config.team)
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, event_list):
        if self.run is None:
            return
        import wandb
        for name, value, step in event_list:
            wandb.log({name: value}, step=step)


class CsvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.filenames = {}
        if self.enabled and _rank() == 0:
            self.output_path = pathlib.Path(config.output_path or "./csv_monitor") / config.job_name
            self.output_path.mkdir(parents=True, exist_ok=True)
        else:
            self.enabled = False

    def write_events(self, event_list):
        if not self.enabled:
            return
        for name, value, step in event_list:
            fname = self.output_path / (name.replace("/", "_") + ".csv")
            new = not fname.exists()
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


def write_recovery_events(monitor, event_list):
    """Best-effort emission of checkpoint/recovery observability events
    (Checkpoint/save_ms, Checkpoint/bytes, Recovery/restarts_total by cause,
    Recovery/last_good_step, ...). Recovery paths must never die on a
    monitoring failure — and they run from contexts where no monitor may
    exist (async save finalizer threads, the elastic agent supervisor) — so
    this guards both, unlike MonitorMaster.write_events."""
    if monitor is None or not getattr(monitor, "enabled", False):
        return
    try:
        monitor.write_events(list(event_list))
    except Exception as e:
        logger.warning(f"recovery event emission failed: {e}")


def write_serving_events(monitor, event_list):
    """Serving-engine observability (Serving/prefix_hit_tokens,
    Serving/prefix_evictions, Serving/pool_free_blocks — emitted by
    `ServingEngine.write_monitor_events`) with the same never-die contract
    as the recovery events above: a serving loop must not crash on a
    monitoring failure."""
    write_recovery_events(monitor, event_list)


class MonitorMaster(Monitor):
    """Fans events out to every enabled monitor (reference same name)."""

    def __init__(self, ds_config):
        self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
        self.wandb_monitor = WandbMonitor(ds_config.wandb)
        self.csv_monitor = CsvMonitor(ds_config.csv_monitor)
        self.enabled = (self.tb_monitor.enabled or self.wandb_monitor.enabled
                        or self.csv_monitor.enabled)

    def write_events(self, event_list):
        if _rank() != 0:
            return
        for m in (self.tb_monitor, self.wandb_monitor, self.csv_monitor):
            if m.enabled:
                m.write_events(event_list)
