"""Device mesh & logical topology.

TPU-native replacement for the reference's process-group machinery
(`deepspeed/utils/groups.py:64,113,207,473` — DP/MP/EP/SP group creation — and
`runtime/pipe/topology.py:12,251` ProcessTopology/PipelineParallelGrid): instead of
rank-list group objects, a single `jax.sharding.Mesh` with named axes. Every
"group" query becomes an axis (or tuple of axes) name; every cartesian-rank
computation is the mesh's coordinate system.

Axis order outer→inner = ('pipe', 'data', 'expert', 'sequence', 'tensor') so that
slow/DCN-spanning axes are outermost and bandwidth-hungry axes (tensor) sit on
adjacent ICI neighbors — the standard megascale layout.

ZeRO sharding uses the combined ('data','sequence') axes as its partition domain,
mirroring the reference's seq_data_parallel_group
(`runtime/engine.py:1116-1122` wires seq×DP as the ZeRO dp_process_group).
"""

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger

# Canonical axis names, outermost first.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
ZERO_INNER_AXIS = "zero"     # inner factor of the data domain (MiCS/hpZ sub-groups)
EXPERT_AXIS = "expert"
SEQ_AXIS = "sequence"
TENSOR_AXIS = "tensor"

ALL_AXES: Tuple[str, ...] = (PIPE_AXIS, DATA_AXIS, ZERO_INNER_AXIS, EXPERT_AXIS,
                             SEQ_AXIS, TENSOR_AXIS)

# ZeRO partitions over data×zero×sequence (see module docstring). The `zero`
# axis is 1 unless MiCS (`mics_shard_size`) or hpZ (`zero_hpz_partition_size`)
# confine (part of) the sharding to an inner sub-group that rides ICI
# (reference: `zero/mics.py:55` sub-group sharding, `zero/config.py:256` hpZ).
ZERO_AXES: Tuple[str, ...] = (DATA_AXIS, ZERO_INNER_AXIS, SEQ_AXIS)

# Batch dims of activations shard over the full data domain.
BATCH_AXES: Tuple[str, ...] = (DATA_AXIS, ZERO_INNER_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Resolved logical topology (analog of PipelineParallelGrid, `topology.py:251`)."""
    pipe: int = 1
    data: int = 1
    zero: int = 1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1

    @property
    def world_size(self):
        return (self.pipe * self.data * self.zero * self.expert * self.sequence
                * self.tensor)

    def axis_sizes(self):
        return {
            PIPE_AXIS: self.pipe,
            DATA_AXIS: self.data,
            ZERO_INNER_AXIS: self.zero,
            EXPERT_AXIS: self.expert,
            SEQ_AXIS: self.sequence,
            TENSOR_AXIS: self.tensor,
        }

    @classmethod
    def resolve(cls, mesh_config, n_devices: Optional[int] = None):
        """Fill the -1 ("absorb remaining devices") axis from the device count."""
        n = n_devices or (mesh_config.devices if getattr(mesh_config, "devices", None) else jax.device_count())
        sizes = {
            "pipe": mesh_config.pipe,
            "data": mesh_config.data,
            "zero": getattr(mesh_config, "zero", 1),
            "expert": mesh_config.expert,
            "sequence": mesh_config.sequence,
            "tensor": mesh_config.tensor,
        }
        unknown = [k for k, v in sizes.items() if v == -1]
        assert len(unknown) <= 1, f"at most one mesh axis may be -1, got {unknown}"
        fixed = int(np.prod([v for v in sizes.values() if v != -1]))
        if unknown:
            assert n % fixed == 0, f"{n} devices not divisible by fixed axes product {fixed}"
            sizes[unknown[0]] = n // fixed
        spec = cls(**sizes)
        # A spec smaller than the device count is allowed (uses the first
        # world_size devices) — useful for tests and partial-slice runs.
        assert spec.world_size <= n, (
            f"mesh {spec} needs {spec.world_size} devices but only {n} are present")
        return spec


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    assert len(devices) == spec.world_size, (
        f"need {spec.world_size} devices for {spec}, have {len(devices)}")
    arr = np.asarray(devices).reshape(spec.pipe, spec.data, spec.zero,
                                      spec.expert, spec.sequence, spec.tensor)
    return Mesh(arr, ALL_AXES)


# -------------------- global current mesh (the "cdb" analog) --------------------
# Reference keeps a module-global backend `cdb` (`deepspeed/comm/comm.py:41`); we keep
# the active Mesh + spec the same way.

_CURRENT_MESH: Optional[Mesh] = None
_CURRENT_SPEC: Optional[MeshSpec] = None


def set_mesh(mesh: Mesh, spec: Optional[MeshSpec] = None):
    global _CURRENT_MESH, _CURRENT_SPEC
    _CURRENT_MESH = mesh
    if spec is None:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        spec = MeshSpec(
            pipe=sizes.get(PIPE_AXIS, 1),
            data=sizes.get(DATA_AXIS, 1),
            zero=sizes.get(ZERO_INNER_AXIS, 1),
            expert=sizes.get(EXPERT_AXIS, 1),
            sequence=sizes.get(SEQ_AXIS, 1),
            tensor=sizes.get(TENSOR_AXIS, 1),
        )
    _CURRENT_SPEC = spec


def get_mesh() -> Mesh:
    assert _CURRENT_MESH is not None, "no mesh initialized — call comm.init_distributed()/init_mesh first"
    return _CURRENT_MESH


def get_spec() -> MeshSpec:
    assert _CURRENT_SPEC is not None, "no mesh initialized"
    return _CURRENT_SPEC


def has_mesh() -> bool:
    return _CURRENT_MESH is not None


def clear_mesh():
    """Uninstall the global mesh (engine teardown / test isolation)."""
    global _CURRENT_MESH, _CURRENT_SPEC
    _CURRENT_MESH = None
    _CURRENT_SPEC = None


def init_mesh(mesh_config=None, devices=None, n_devices=None) -> Mesh:
    """Build + install the global mesh from a MeshConfig (or default: all-data)."""
    from deepspeed_tpu.config.core import MeshConfig
    mesh_config = mesh_config or MeshConfig()
    spec = MeshSpec.resolve(mesh_config, n_devices=n_devices or (len(devices) if devices else None))
    devices = list(devices if devices is not None else jax.devices())[:spec.world_size]
    mesh = build_mesh(spec, devices)
    set_mesh(mesh, spec)
    logger.info(f"mesh initialized: {spec} over {spec.world_size} devices")
    return mesh


# -------------------- group-query parity (utils/groups.py analog) --------------------


def axis_size(axis) -> int:
    sizes = get_spec().axis_sizes()
    if isinstance(axis, (tuple, list)):
        return int(np.prod([sizes[a] for a in axis]))
    return sizes[axis]


def get_world_size() -> int:
    return get_spec().world_size if has_mesh() else jax.device_count()


def get_data_parallel_world_size() -> int:
    # ZeRO/data domain = data × sequence (see module docstring)
    return axis_size(ZERO_AXES) if has_mesh() else jax.device_count()


def get_model_parallel_world_size() -> int:
    return axis_size(TENSOR_AXIS) if has_mesh() else 1


def get_pipe_parallel_world_size() -> int:
    return axis_size(PIPE_AXIS) if has_mesh() else 1


def get_expert_parallel_world_size() -> int:
    return axis_size(EXPERT_AXIS) if has_mesh() else 1


def get_sequence_parallel_world_size() -> int:
    return axis_size(SEQ_AXIS) if has_mesh() else 1


def data_parallel_sharding(*per_axis) -> NamedSharding:
    """NamedSharding helper: shard leading dim over the ZeRO domain."""
    return NamedSharding(get_mesh(), P(ZERO_AXES, *per_axis))


def replicated_sharding() -> NamedSharding:
    return NamedSharding(get_mesh(), P())


_CONSTRAINTS_DISABLED = False


class constraints_disabled:
    """Context manager: make shard_constraint a no-op while tracing code that runs
    inside a shard_map body (where outer-mesh constraints are not applicable)."""

    def __enter__(self):
        global _CONSTRAINTS_DISABLED
        self._prev = _CONSTRAINTS_DISABLED
        _CONSTRAINTS_DISABLED = True

    def __exit__(self, *exc):
        global _CONSTRAINTS_DISABLED
        _CONSTRAINTS_DISABLED = self._prev


def shard_constraint(x, *spec_entries):
    """`with_sharding_constraint` against the current global mesh; no-op when no
    mesh is installed (lets model code run standalone) or inside
    `constraints_disabled()` (shard_map bodies)."""
    if not has_mesh() or _CONSTRAINTS_DISABLED:
        return x
    spec = P(*spec_entries)
    return jax.lax.with_sharding_constraint(x, NamedSharding(get_mesh(), spec))
