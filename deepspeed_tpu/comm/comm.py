"""Communication facade.

TPU-native analog of `deepspeed.comm` (`deepspeed/comm/comm.py:13-21,604` — the
torch.distributed-compatible facade with a global backend, `init_distributed`, and
`timed_op` logging). On TPU there is no backend registry: every collective is an XLA
op over the mesh's ICI/DCN links. This module provides

  * `init_distributed()` — multi-host bring-up over `jax.distributed.initialize`
    (env-discovery like the reference's `mpi_discovery`, `comm/comm.py:676`), then
    builds/installs the global mesh;
  * eager collectives over global arrays (`all_reduce`, `all_gather`, ...) addressed
    by mesh-axis name, each wrapped in per-op timing/volume logging
    (`CommsLogger` analog of `deepspeed/utils/comms_logging.py`);
  * in-jit aliases (`psum`, `pmean`, `all_gather_lax`, ...) for use inside
    `shard_map`ped code — the hot path never goes through the eager facade.
"""

import functools
import os
import time
from enum import Enum

import jax
import jax.numpy as jnp
import numpy as np
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import collectives
from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.utils.logging import logger


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4


_INITIALIZED = False


def is_initialized():
    return _INITIALIZED


def init_distributed(dist_backend=None,
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1,
                     mesh_config=None):
    """Bring up multi-process JAX (if needed) and install the global mesh.

    Signature mirrors the reference `init_distributed` (`comm/comm.py:604`); the
    backend arg is accepted and ignored (XLA is the only backend). Multi-host env
    discovery honors the same variables the reference's launcher exports
    (RANK/WORLD_SIZE/MASTER_ADDR/MASTER_PORT, `launcher/launch.py:132`).
    """
    global _INITIALIZED
    if _INITIALIZED:
        if not mesh_mod.has_mesh():
            mesh_mod.init_mesh(mesh_config)
        return

    n_procs = int(os.environ.get("WORLD_SIZE", os.environ.get("DSTPU_NUM_PROCESSES", "1")))
    proc_id = int(os.environ.get("RANK", os.environ.get("DSTPU_PROCESS_ID", "0")))
    coord = os.environ.get("MASTER_ADDR")
    if world_size > 0:
        n_procs = world_size
    if rank >= 0:
        proc_id = rank

    if n_procs > 1:
        coordinator = f"{coord or 'localhost'}:{os.environ.get('MASTER_PORT', distributed_port)}"
        if verbose:
            logger.info(f"jax.distributed.initialize(coordinator={coordinator}, "
                        f"num_processes={n_procs}, process_id={proc_id})")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=n_procs,
                                   process_id=proc_id)
    _INITIALIZED = True
    if not mesh_mod.has_mesh():
        mesh_mod.init_mesh(mesh_config)


def get_rank():
    return jax.process_index()


def get_local_rank():
    return 0  # one process drives all local chips in JAX


def get_world_size():
    """Device-granular world size (reference counts ranks = accelerators)."""
    return mesh_mod.get_world_size()


def barrier():
    jax.effects_barrier()
    if jax.process_count() > 1:
        # cross-host sync: tiny psum over all devices
        x = jnp.zeros((jax.device_count(),))
        # dstpu: ignore[DT001]: barrier() IS the sync — the cross-host fence is this function's contract
        jax.block_until_ready(
            jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh_mod.get_mesh(), P()))(x)
            if mesh_mod.has_mesh() else x.sum())


# ------------------------------------------------------------------
# Comms logging (reference: utils/comms_logging.py + timed_op comm.py:101)
# ------------------------------------------------------------------


class CommsLogger:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.records = {}  # op_name -> list of (bytes, seconds)

    def configure(self, enabled=False, verbose=False, **kw):
        self.enabled = enabled
        self.verbose = verbose

    def append(self, op_name, size_bytes, seconds):
        self.records.setdefault(op_name, []).append((size_bytes, seconds))
        # route the timing log into the facade stats (and, when a Telemetry
        # object is bound there, into comm/<op>_bytes + comm/<op>_ms)
        collectives.stats.record(op_name, size_bytes, seconds)
        if self.verbose:
            logger.info(f"comm op: {op_name} | bytes: {size_bytes} | time (ms): {seconds*1e3:.3f}")

    def log_all(self):
        lines = [f"{'Op':<20}{'Count':>8}{'Total MB':>12}{'Avg ms':>10}{'Alg bw GB/s':>14}"]
        for op, recs in sorted(self.records.items()):
            n = len(recs)
            total_b = sum(r[0] for r in recs)
            total_t = sum(r[1] for r in recs)
            bw = (total_b / total_t / 1e9) if total_t > 0 else 0.0
            lines.append(f"{op:<20}{n:>8}{total_b/1e6:>12.2f}{total_t/n*1e3:>10.3f}{bw:>14.2f}")
        out = "\n".join(lines)
        logger.info("\n" + out)
        return out

    def reset(self):
        self.records.clear()


comms_logger = CommsLogger()


def log_summary():
    return comms_logger.log_all()


def _nbytes(x):
    return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize if hasattr(x, "shape") else 0


def _timed(op_name, fn, x, *args, **kwargs):
    if not comms_logger.enabled:
        out = fn(x, *args, **kwargs)
        # byte/count stats are always on (cheap); wall-time needs the fence
        # below, which only runs when the comms logger is enabled
        collectives.stats.record(op_name, _nbytes(x))
        return out
    t0 = time.perf_counter()
    out = fn(x, *args, **kwargs)
    # dstpu: ignore[DT001]: comms-logger timing fence — only runs when logging is enabled, and a fence is what makes the timing honest
    jax.block_until_ready(out)
    comms_logger.append(op_name, _nbytes(x), time.perf_counter() - t0)
    return out


# ------------------------------------------------------------------
# Eager collectives over global arrays (API-parity layer)
# ------------------------------------------------------------------
# Each op runs a jitted shard_map over the current mesh along `axis`
# (default: the ZeRO data domain). Inputs are global arrays; outputs are global
# arrays with the natural output sharding.


def _axis_tuple(axis):
    if axis is None:
        return mesh_mod.ZERO_AXES
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def _reduce_fn(op):
    table = {
        ReduceOp.SUM: jax.lax.psum,
        ReduceOp.AVG: jax.lax.pmean,
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
    }
    if op not in table:
        raise ValueError(
            f"unsupported reduce op {op}; supported: "
            f"{sorted(o.name for o in table)}")
    return table[op]


@functools.lru_cache(maxsize=256)
def _make_all_reduce(mesh, axes, op, shape, dtype):
    red = _reduce_fn(op)

    def local(x):
        return red(x, axes)

    spec = P(axes)  # input sharded on leading dim across the reduce axes
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False))


def all_reduce(tensor, op=ReduceOp.SUM, axis=None, group=None):
    """Eager allreduce of a global array over mesh axes (default: data domain).

    `group` accepted for signature parity; axis names replace group objects.
    """
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    n = mesh_mod.axis_size(axes)
    if n == 1:
        return tensor
    tensor = jnp.asarray(tensor)
    # operate on replicated/global semantics: reduce across the axis by summing
    # shards of the leading dimension if sharded, else identity * n semantics.
    fn = _make_all_reduce(mesh, axes, op, tensor.shape, str(tensor.dtype))
    return _timed("all_reduce", fn, tensor)


@functools.lru_cache(maxsize=256)
def _make_all_gather(mesh, axes):
    def local(x):
        return jax.lax.all_gather(x, axes, axis=0, tiled=True)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axes),), out_specs=P(), check_vma=False))


def all_gather(tensor, axis=None, tiled=True, group=None):
    """Gather shards along leading dim across `axis` → global concatenation."""
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    if mesh_mod.axis_size(axes) == 1:
        return jnp.asarray(tensor)
    return _timed("all_gather", _make_all_gather(mesh, axes), jnp.asarray(tensor))


@functools.lru_cache(maxsize=256)
def _make_reduce_scatter(mesh, axes):
    def local(x):
        return jax.lax.psum_scatter(x, axes, scatter_dimension=0, tiled=True)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(),), out_specs=P(axes), check_vma=False))


def reduce_scatter(tensor, op=ReduceOp.SUM, axis=None, group=None):
    """Reduce across `axis` then scatter leading dim: global → sharded."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(
            f"reduce_scatter supports ops ('SUM', 'AVG'); got {op}")
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    n = mesh_mod.axis_size(axes)
    if n == 1:
        return jnp.asarray(tensor)
    out = _timed("reduce_scatter", _make_reduce_scatter(mesh, axes), jnp.asarray(tensor))
    return out / n if op == ReduceOp.AVG else out


@functools.lru_cache(maxsize=256)
def _make_all_to_all(mesh, axes, split_axis, concat_axis, ndim):
    def local(x):
        return jax.lax.all_to_all(x, axes, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    spec_in = [None] * ndim
    spec_in[concat_axis] = axes
    spec_out = [None] * ndim
    spec_out[split_axis] = axes
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(*spec_in),),
                             out_specs=P(*spec_out), check_vma=False))


def all_to_all(tensor, axis=None, split_axis=0, concat_axis=0, group=None):
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    if mesh_mod.axis_size(axes) == 1:
        return jnp.asarray(tensor)
    tensor = jnp.asarray(tensor)
    fn = _make_all_to_all(mesh, axes, split_axis, concat_axis, tensor.ndim)
    return _timed("all_to_all", fn, tensor)


@functools.lru_cache(maxsize=8)
def _make_broadcast(mesh):
    return jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))


def broadcast(tensor, src=0, axis=None, group=None):
    """Replicate `tensor` across the mesh (XLA: replicated sharding constraint).
    `src` accepted for parity — global arrays are process-consistent in JAX."""
    return _timed("broadcast", _make_broadcast(mesh_mod.get_mesh()), jnp.asarray(tensor))


# ------------------------------------------------------------------
# In-jit aliases (use these inside shard_map'ped code) — instrumented
# through the collective registry so byte stats accrue under every consumer
# ------------------------------------------------------------------

psum = collectives.psum
pmean = collectives.pmean
pmax = jax.lax.pmax
pmin = jax.lax.pmin
ppermute = collectives.ppermute
axis_index = jax.lax.axis_index


def all_gather_lax(x, axis_name, axis=0, tiled=True):
    return collectives.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter_lax(x, axis_name, scatter_dimension=0, tiled=True):
    return collectives.reduce_scatter(x, axis_name,
                                      scatter_dimension=scatter_dimension,
                                      tiled=tiled)


def all_to_all_lax(x, axis_name, split_axis, concat_axis, tiled=True):
    return collectives.all_to_all(x, axis_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=tiled)


# ------------------------------------------------------------------
# reference-parity surface (deepspeed.comm facade, comm/comm.py:13-21) —
# ops whose distinct CUDA/NCCL semantics collapse under SPMD global arrays
# ------------------------------------------------------------------


def reduce(tensor, dst=0, op=ReduceOp.SUM, axis=None, group=None):
    """Reference `reduce`: result on dst rank. Global arrays are process-
    consistent in JAX, so every process holds the reduced value; `dst` is
    accepted for signature parity."""
    return all_reduce(tensor, op=op, axis=axis, group=group)


def gather(tensor, gather_list=None, dst=0, axis=None, group=None):
    """Reference `gather` (to dst) — SPMD form: all ranks get the concat."""
    return all_gather(tensor, axis=axis, group=group)


def scatter(tensor, scatter_list=None, src=0, axis=None, group=None):
    """Shard across `axis` (reference `scatter(tensor, scatter_list, src)`
    from the src rank; here the global array is simply laid out sharded).
    With `scatter_list`, the per-rank chunks are concatenated and sharded so
    rank i's shard is chunk i; otherwise `tensor`'s leading dim is split."""
    data = (jnp.concatenate([jnp.asarray(t) for t in scatter_list], axis=0)
            if scatter_list is not None else jnp.asarray(tensor))
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    if mesh_mod.axis_size(axes) == 1:
        return data
    sharding = NamedSharding(mesh, P(axes))
    return _timed("scatter", lambda x: jax.device_put(x, sharding), data)


def all_to_all_single(output=None, input=None, output_split_sizes=None,
                      input_split_sizes=None, axis=None, group=None):
    """Reference `all_to_all_single` (one tensor split/concat on dim 0).

    Even splits run the native tiled `lax.all_to_all`. Uneven splits have no
    static-shape SPMD formulation, so they go pad → exchange → slice: in the
    eager facade's global view the input is the concatenation of W per-rank
    blocks (each `sum(split_sizes)` long, chunk r of every block addressed to
    rank r); each chunk pads to `max(split_sizes)`, one even exchange runs,
    and the output re-assembles as the concatenation of W per-rank receive
    blocks (rank r's block is its W received chunks, `split_sizes[r]` each —
    exactly torch's per-rank `output_split_sizes = [in_splits[r]] * W`)."""
    tensor = jnp.asarray(input if input is not None else output)
    if output_split_sizes is None and input_split_sizes is None:
        return all_to_all(tensor, axis=axis, group=group, split_axis=0,
                          concat_axis=0)
    if input_split_sizes is None:
        # torch's output-only form means "input split evenly, receive sizes
        # given" — per-rank receive sizes have no global-view formulation
        # here; fail loudly like the asymmetric case below
        raise NotImplementedError(
            "all_to_all_single: output_split_sizes without input_split_sizes "
            "(per-rank receive sizes) has no global-view formulation — pass "
            "symmetric input_split_sizes")
    splits = [int(s) for s in input_split_sizes]
    axes = _axis_tuple(axis if axis is not None else group)
    W = mesh_mod.axis_size(axes)
    if len(splits) != W:
        raise ValueError(
            f"all_to_all_single: {len(splits)} input splits for axis size {W} "
            "— need exactly one split per rank")
    if output_split_sizes is not None and \
            list(map(int, output_split_sizes)) != splits:
        raise ValueError(
            "all_to_all_single: global-view uneven exchange needs symmetric "
            f"splits (every rank shares one split list); got input "
            f"{splits} vs output {list(map(int, output_split_sizes))}")
    S = sum(splits)
    rest = tensor.shape[1:]
    if tensor.shape[0] != W * S:
        raise ValueError(
            f"all_to_all_single: leading dim {tensor.shape[0]} != axis size "
            f"{W} * sum(splits) {S} — the global view is the concatenation "
            "of one send block per rank")
    m = max(splits)
    if m * W == S:   # actually even
        return all_to_all(tensor, axis=axis, group=group, split_axis=0,
                          concat_axis=0)
    blocks = tensor.reshape(W, S, *rest)
    offs = np.cumsum([0] + splits)
    padded = jnp.stack(
        [jnp.pad(blocks[:, offs[r]:offs[r + 1]],
                 ((0, 0), (0, m - splits[r])) + ((0, 0),) * len(rest))
         for r in range(W)], axis=1)                     # [W_send, W_recv, m, ...]
    ex = all_to_all(padded.reshape(W * W * m, *rest), axis=axis, group=group,
                    split_axis=0, concat_axis=0)         # block transpose
    ex = ex.reshape(W, W, m, *rest)                      # [W_recv, W_send, m]
    return jnp.concatenate(
        [ex[r, :, :splits[r]].reshape(W * splits[r], *rest) for r in range(W)],
        axis=0)


def all_gather_into_tensor(output_tensor=None, input_tensor=None, axis=None,
                           group=None):
    """Reference `all_gather_into_tensor` (flat single-tensor all-gather)."""
    return all_gather(input_tensor, axis=axis, group=group)


def reduce_scatter_tensor(output=None, input=None, op=ReduceOp.SUM, axis=None,
                          group=None):
    """Reference `reduce_scatter_tensor` (flat single-tensor variant)."""
    return reduce_scatter(input, op=op, axis=axis, group=group)


def inference_all_reduce(tensor, op=ReduceOp.SUM, axis=None, group=None):
    """Reference `inference_all_reduce` (comm/torch.py:157): TP-group allreduce
    on the decode path. Defaults to the tensor axis."""
    axes = axis if axis is not None else \
        (group if group is not None else (mesh_mod.TENSOR_AXIS,))
    return all_reduce(tensor, op=op, axis=axes)


@functools.lru_cache(maxsize=128)
def _make_coalesced(mesh, axes, op, n):
    """One compiled program reducing/gathering n tensors together — the
    coalescing is real (single dispatch, XLA schedules the collectives as a
    group), unlike a python loop of eager calls."""
    if op is None:
        def local(*xs):
            return tuple(jax.lax.all_gather(x, axes, axis=0, tiled=True)
                         for x in xs)
        out_spec = (P(),) * n
    else:
        red = _reduce_fn(op)

        def local(*xs):
            return tuple(red(x, axes) for x in xs)
        out_spec = (P(axes),) * n
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axes),) * n,
                             out_specs=out_spec, check_vma=False))


def _coalesced(op_name, tensors, op, axis, group):
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    if mesh_mod.axis_size(axes) == 1 or not tensors:
        return [jnp.asarray(t) for t in tensors]
    fn = _make_coalesced(mesh, axes, op, len(tensors))
    t0 = time.perf_counter()
    outs = fn(*[jnp.asarray(t) for t in tensors])
    if comms_logger.enabled:
        # dstpu: ignore[DT001]: comms-logger timing fence — enabled-only, honest timing needs the drain
        jax.block_until_ready(outs)
        comms_logger.append(op_name, sum(_nbytes(t) for t in tensors),
                            time.perf_counter() - t0)
    return list(outs)


def all_reduce_coalesced(tensors, op=ReduceOp.SUM, axis=None, group=None):
    """Reference `all_reduce_coalesced`: many tensors, ONE compiled dispatch."""
    return _coalesced("all_reduce_coalesced", tensors, op, axis, group)


def all_gather_coalesced(tensors, axis=None, group=None):
    """Reference `all_gather_coalesced`: many tensors, ONE compiled dispatch."""
    return _coalesced("all_gather_coalesced", tensors, None, axis, group)


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """Reference `monitored_barrier` — plain barrier on TPU (XLA collectives
    already fail loudly on rank mismatch)."""
    return barrier()


def _data_domain_is_world() -> bool:
    """True when the mesh has no model-parallel axes, i.e. the data domain
    (ZERO_AXES) spans every device."""
    if not mesh_mod.has_mesh():
        return True
    return all(mesh_mod.axis_size(a) == 1
               for a in (mesh_mod.PIPE_AXIS, mesh_mod.EXPERT_AXIS,
                         mesh_mod.TENSOR_AXIS))


def get_global_rank(group=None, group_rank=0, coords=None):
    """Reference `get_global_rank`: group-local rank → global (device) rank.

    Global ranks are lexicographic positions in `mesh.devices` (the order the
    launcher lays world ranks onto the mesh). A sub-axis group has one
    INSTANCE per coordinate of the non-group axes — information torch carries
    in the group object; pass it as `coords` ({axis_name: coord}, default 0s
    = the first instance, matching the reference's common
    `get_global_rank(tp_group, 0)` leader lookup — reference
    `utils/groups.py:473` derives the same thing from topology)."""
    if group is None or _axis_tuple(group) == tuple(mesh_mod.ALL_AXES):
        return group_rank
    if _axis_tuple(group) == tuple(mesh_mod.ZERO_AXES) and _data_domain_is_world():
        return group_rank
    mesh = mesh_mod.get_mesh()
    names = list(mesh.axis_names)
    shape = [mesh.shape[n] for n in names]
    gaxes = [n for n in names if n in _axis_tuple(group)]
    if not gaxes:
        raise ValueError(
            f"get_global_rank: unknown group axes {group}; mesh axes: {names}")
    gshape = [mesh.shape[n] for n in gaxes]
    total = int(np.prod(gshape))
    if not 0 <= group_rank < total:
        raise ValueError(
            f"get_global_rank: group_rank {group_rank} out of range for "
            f"group {gaxes} of size {total}")
    gcoords = dict(zip(gaxes, np.unravel_index(group_rank, gshape)))
    fixed = dict(coords or {})
    full = [int(gcoords.get(n, fixed.get(n, 0))) for n in names]
    return int(np.ravel_multi_index(full, shape))


def get_world_group():
    """Reference `get_world_group` — all mesh axes (every device), matching
    the reference's all-ranks world-group semantics even when the mesh has
    tensor/pipe/expert axes."""
    return mesh_mod.ALL_AXES


def new_group(ranks=None):
    """Reference `new_group`: process-group objects are replaced by mesh axis
    names here (pass axis="tensor"/"data"/... to any collective). Returns the
    default domain so legacy call sites keep working; configure the mesh
    instead for custom topologies."""
    logger.warning("comm.new_group: groups are mesh axes on TPU; returning the "
                   "default data domain — configure the `mesh` block instead")
    return mesh_mod.ZERO_AXES


# --- p2p (reference deepspeed/comm isend/irecv, runtime/pipe/p2p.py) --------
# Eager cross-rank p2p does not exist under SPMD: a "send" is a ppermute in a
# compiled program. Inside shard_map, use `p2p_shift`; the eager wrappers
# raise with that guidance rather than silently doing the wrong thing.


def p2p_shift(x, axis_name, shift=1):
    """In-jit neighbor exchange: rank i's block goes to rank (i+shift) % n
    (the pipeline engine's SendActivation/RecvActivation pair, fused)."""
    n = mesh_mod.axis_size((axis_name,)) if isinstance(axis_name, str) \
        else mesh_mod.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return collectives.ppermute(x, axis_name, perm)


def _no_eager_p2p(name):
    raise NotImplementedError(
        f"comm.{name}: eager point-to-point does not exist under compiled "
        "SPMD — express the exchange inside the jitted step with "
        "comm.p2p_shift (lax.ppermute), as parallel/pipeline.py does")


def send(tensor, dst, group=None, tag=0):
    _no_eager_p2p("send")


def recv(tensor, src, group=None, tag=0):
    _no_eager_p2p("recv")


def isend(tensor, dst, group=None, tag=0):
    _no_eager_p2p("isend")


def irecv(tensor, src, group=None, tag=0):
    _no_eager_p2p("irecv")


def is_available():
    """Reference `comm.is_available` (torch.distributed availability probe)."""
    return True


def destroy_process_group(group=None):
    """Reference `destroy_process_group`: tear down the installed mesh (and
    multi-process runtime state) so a fresh init_distributed can follow."""
    global _INITIALIZED
    mesh_mod.clear_mesh()
    if jax.process_count() > 1:
        try:
            jax.distributed.shutdown()
        except Exception as e:  # already down / never brought up
            logger.warning(f"jax.distributed.shutdown: {e}")
    _INITIALIZED = False


# ------------------------------------------------------------------
# Register the eager facade under the op registry: collectives.run("x", ...)
# dispatches here; the in-jit forms stay the instrumented lax wrappers.
# ------------------------------------------------------------------

for _name, _eager in (("all_reduce", all_reduce),
                      ("all_gather", all_gather),
                      ("reduce_scatter", reduce_scatter),
                      ("all_to_all", all_to_all)):
    collectives.register_op(_name, lax=collectives.get_op(_name).lax,
                            eager=_eager)
del _name, _eager
