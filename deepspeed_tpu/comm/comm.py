"""Communication facade.

TPU-native analog of `deepspeed.comm` (`deepspeed/comm/comm.py:13-21,604` — the
torch.distributed-compatible facade with a global backend, `init_distributed`, and
`timed_op` logging). On TPU there is no backend registry: every collective is an XLA
op over the mesh's ICI/DCN links. This module provides

  * `init_distributed()` — multi-host bring-up over `jax.distributed.initialize`
    (env-discovery like the reference's `mpi_discovery`, `comm/comm.py:676`), then
    builds/installs the global mesh;
  * eager collectives over global arrays (`all_reduce`, `all_gather`, ...) addressed
    by mesh-axis name, each wrapped in per-op timing/volume logging
    (`CommsLogger` analog of `deepspeed/utils/comms_logging.py`);
  * in-jit aliases (`psum`, `pmean`, `all_gather_lax`, ...) for use inside
    `shard_map`ped code — the hot path never goes through the eager facade.
"""

import functools
import os
import time
from enum import Enum

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.utils.logging import logger


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4


_INITIALIZED = False


def is_initialized():
    return _INITIALIZED


def init_distributed(dist_backend=None,
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1,
                     mesh_config=None):
    """Bring up multi-process JAX (if needed) and install the global mesh.

    Signature mirrors the reference `init_distributed` (`comm/comm.py:604`); the
    backend arg is accepted and ignored (XLA is the only backend). Multi-host env
    discovery honors the same variables the reference's launcher exports
    (RANK/WORLD_SIZE/MASTER_ADDR/MASTER_PORT, `launcher/launch.py:132`).
    """
    global _INITIALIZED
    if _INITIALIZED:
        if not mesh_mod.has_mesh():
            mesh_mod.init_mesh(mesh_config)
        return

    n_procs = int(os.environ.get("WORLD_SIZE", os.environ.get("DSTPU_NUM_PROCESSES", "1")))
    proc_id = int(os.environ.get("RANK", os.environ.get("DSTPU_PROCESS_ID", "0")))
    coord = os.environ.get("MASTER_ADDR")
    if world_size > 0:
        n_procs = world_size
    if rank >= 0:
        proc_id = rank

    if n_procs > 1:
        coordinator = f"{coord or 'localhost'}:{os.environ.get('MASTER_PORT', distributed_port)}"
        if verbose:
            logger.info(f"jax.distributed.initialize(coordinator={coordinator}, "
                        f"num_processes={n_procs}, process_id={proc_id})")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=n_procs,
                                   process_id=proc_id)
    _INITIALIZED = True
    if not mesh_mod.has_mesh():
        mesh_mod.init_mesh(mesh_config)


def get_rank():
    return jax.process_index()


def get_local_rank():
    return 0  # one process drives all local chips in JAX


def get_world_size():
    """Device-granular world size (reference counts ranks = accelerators)."""
    return mesh_mod.get_world_size()


def barrier():
    jax.effects_barrier()
    if jax.process_count() > 1:
        # cross-host sync: tiny psum over all devices
        x = jnp.zeros((jax.device_count(),))
        jax.block_until_ready(
            jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh_mod.get_mesh(), P()))(x)
            if mesh_mod.has_mesh() else x.sum())


# ------------------------------------------------------------------
# Comms logging (reference: utils/comms_logging.py + timed_op comm.py:101)
# ------------------------------------------------------------------


class CommsLogger:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.records = {}  # op_name -> list of (bytes, seconds)

    def configure(self, enabled=False, verbose=False, **kw):
        self.enabled = enabled
        self.verbose = verbose

    def append(self, op_name, size_bytes, seconds):
        self.records.setdefault(op_name, []).append((size_bytes, seconds))
        if self.verbose:
            logger.info(f"comm op: {op_name} | bytes: {size_bytes} | time (ms): {seconds*1e3:.3f}")

    def log_all(self):
        lines = [f"{'Op':<20}{'Count':>8}{'Total MB':>12}{'Avg ms':>10}{'Alg bw GB/s':>14}"]
        for op, recs in sorted(self.records.items()):
            n = len(recs)
            total_b = sum(r[0] for r in recs)
            total_t = sum(r[1] for r in recs)
            bw = (total_b / total_t / 1e9) if total_t > 0 else 0.0
            lines.append(f"{op:<20}{n:>8}{total_b/1e6:>12.2f}{total_t/n*1e3:>10.3f}{bw:>14.2f}")
        out = "\n".join(lines)
        logger.info("\n" + out)
        return out

    def reset(self):
        self.records.clear()


comms_logger = CommsLogger()


def log_summary():
    return comms_logger.log_all()


def _nbytes(x):
    return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize if hasattr(x, "shape") else 0


def _timed(op_name, fn, x, *args, **kwargs):
    if not comms_logger.enabled:
        return fn(x, *args, **kwargs)
    t0 = time.perf_counter()
    out = fn(x, *args, **kwargs)
    jax.block_until_ready(out)
    comms_logger.append(op_name, _nbytes(x), time.perf_counter() - t0)
    return out


# ------------------------------------------------------------------
# Eager collectives over global arrays (API-parity layer)
# ------------------------------------------------------------------
# Each op runs a jitted shard_map over the current mesh along `axis`
# (default: the ZeRO data domain). Inputs are global arrays; outputs are global
# arrays with the natural output sharding.


def _axis_tuple(axis):
    if axis is None:
        return mesh_mod.ZERO_AXES
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def _reduce_fn(op):
    return {
        ReduceOp.SUM: jax.lax.psum,
        ReduceOp.AVG: jax.lax.pmean,
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
    }[op]


@functools.lru_cache(maxsize=256)
def _make_all_reduce(mesh, axes, op, shape, dtype):
    red = _reduce_fn(op)

    def local(x):
        return red(x, axes)

    spec = P(axes)  # input sharded on leading dim across the reduce axes
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False))


def all_reduce(tensor, op=ReduceOp.SUM, axis=None, group=None):
    """Eager allreduce of a global array over mesh axes (default: data domain).

    `group` accepted for signature parity; axis names replace group objects.
    """
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    n = mesh_mod.axis_size(axes)
    if n == 1:
        return tensor
    tensor = jnp.asarray(tensor)
    # operate on replicated/global semantics: reduce across the axis by summing
    # shards of the leading dimension if sharded, else identity * n semantics.
    fn = _make_all_reduce(mesh, axes, op, tensor.shape, str(tensor.dtype))
    return _timed("all_reduce", fn, tensor)


@functools.lru_cache(maxsize=256)
def _make_all_gather(mesh, axes):
    def local(x):
        return jax.lax.all_gather(x, axes, axis=0, tiled=True)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(axes),), out_specs=P(), check_vma=False))


def all_gather(tensor, axis=None, tiled=True, group=None):
    """Gather shards along leading dim across `axis` → global concatenation."""
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    if mesh_mod.axis_size(axes) == 1:
        return jnp.asarray(tensor)
    return _timed("all_gather", _make_all_gather(mesh, axes), jnp.asarray(tensor))


@functools.lru_cache(maxsize=256)
def _make_reduce_scatter(mesh, axes):
    def local(x):
        return jax.lax.psum_scatter(x, axes, scatter_dimension=0, tiled=True)

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(),), out_specs=P(axes), check_vma=False))


def reduce_scatter(tensor, op=ReduceOp.SUM, axis=None, group=None):
    """Reduce across `axis` then scatter leading dim: global → sharded."""
    assert op in (ReduceOp.SUM, ReduceOp.AVG), "reduce_scatter supports SUM/AVG"
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    n = mesh_mod.axis_size(axes)
    if n == 1:
        return jnp.asarray(tensor)
    out = _timed("reduce_scatter", _make_reduce_scatter(mesh, axes), jnp.asarray(tensor))
    return out / n if op == ReduceOp.AVG else out


@functools.lru_cache(maxsize=256)
def _make_all_to_all(mesh, axes, split_axis, concat_axis, ndim):
    def local(x):
        return jax.lax.all_to_all(x, axes, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)

    spec_in = [None] * ndim
    spec_in[concat_axis] = axes
    spec_out = [None] * ndim
    spec_out[split_axis] = axes
    return jax.jit(shard_map(local, mesh=mesh, in_specs=(P(*spec_in),),
                             out_specs=P(*spec_out), check_vma=False))


def all_to_all(tensor, axis=None, split_axis=0, concat_axis=0, group=None):
    axes = _axis_tuple(axis if axis is not None else group)
    mesh = mesh_mod.get_mesh()
    if mesh_mod.axis_size(axes) == 1:
        return jnp.asarray(tensor)
    tensor = jnp.asarray(tensor)
    fn = _make_all_to_all(mesh, axes, split_axis, concat_axis, tensor.ndim)
    return _timed("all_to_all", fn, tensor)


@functools.lru_cache(maxsize=8)
def _make_broadcast(mesh):
    return jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))


def broadcast(tensor, src=0, axis=None, group=None):
    """Replicate `tensor` across the mesh (XLA: replicated sharding constraint).
    `src` accepted for parity — global arrays are process-consistent in JAX."""
    return _timed("broadcast", _make_broadcast(mesh_mod.get_mesh()), jnp.asarray(tensor))


# ------------------------------------------------------------------
# In-jit aliases (use these inside shard_map'ped code)
# ------------------------------------------------------------------

psum = jax.lax.psum
pmean = jax.lax.pmean
pmax = jax.lax.pmax
pmin = jax.lax.pmin
ppermute = jax.lax.ppermute
axis_index = jax.lax.axis_index


def all_gather_lax(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter_lax(x, axis_name, scatter_dimension=0, tiled=True):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def all_to_all_lax(x, axis_name, split_axis, concat_axis, tiled=True):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)
