"""Pluggable collective layer — the comm spine.

One registry of the five collective primitives (all_reduce / all_gather /
reduce_scatter / all_to_all / ppermute), each usable two ways:

  * **eagerly** over global arrays — `run(op, x, ...)` dispatches the eager
    implementation registered by `comm/comm.py` (its jitted `shard_map`
    wrappers), which carries measured wall-time into the stats;
  * **inside `shard_map` bodies** — the instrumented in-jit wrappers below
    (`psum`, `pmean`, `all_gather`, `reduce_scatter`, `all_to_all`,
    `ppermute`) call straight into `jax.lax` and record *trace-time payload
    bytes*: the bytes one participant hands to the wire per execution of the
    traced program at that call site. Re-running an already-compiled program
    records nothing new — `stats.reset()` then retrace (``jit(...).lower``)
    to re-measure, which is exactly what bench.py's scaling lane and
    tests/test_comm_volume.py do. Collectives inside `lax.scan` bodies trace
    once but execute every iteration; pass ``repeats=n_iters`` so the
    accounting matches (parallel/pipeline.py does this for its per-tick
    ppermute handoffs).

Byte convention (kept deliberately simple so ratios are exact): recorded
bytes = payload bytes of the arrays a single participant hands to the
underlying lax op, times ``repeats``; axis size 1 records 0 (no wire). No
hop-count or (n-1)/n algorithm factors are applied — absolute numbers are
payload-proportional, and compressed-vs-fp ratios are exact.

Per-op stats mirror into the telemetry registry once a `Telemetry` object is
bound (`comm/<op>_bytes` + `comm/<op>_calls` counters, `comm/<op>_ms`
histograms — catalog rows in docs/profiling.md; the training engine binds
its telemetry at construction).

**Transform hooks** let compression plug in under every consumer once: a
`WireTransform` is an encode/decode pair over f32 payloads. Registered
transforms:

  * ``"none"``   — identity (fp32 wire);
  * ``"int8"``   — ZeRO++ qwZ/qgZ groupwise symmetric int8 (scale =
    max|x|/127 per group), the same single-definition quant whose on-chip
    form lives in `ops/pallas/quant.py` and whose collective use lives in
    `runtime/quantized_collectives.py` (that module now imports these
    definitions);
  * ``"onebit"`` — 1-bit sign+mean-magnitude compression (the 1-bit Adam
    wire format, `runtime/compressed_grads.py`'s `_sign_compress` rule),
    packed 8 signs/byte; used with error feedback via
    `compressed_all_reduce(..., transform="onebit", err=...)`.
"""

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


OP_NAMES = ("all_reduce", "all_gather", "reduce_scatter", "all_to_all",
            "ppermute")

TRANSFORM_NAMES = ("none", "int8", "onebit")

DEFAULT_GROUP_SIZE = 256  # qwZ/qgZ quantization group (reference default)


# ------------------------------------------------------------------
# Per-op stats, mirrored into telemetry when bound
# ------------------------------------------------------------------


class CommStats:
    """Per-op {calls, bytes, seconds} accumulator.

    `record` is called from two places: the eager facade (comm/comm.py)
    with measured wall-time, and the in-jit wrappers below at trace time
    with `seconds=None` (compiled collectives have no per-op host timer).
    When a `Telemetry` object is bound the same records flow into its
    registry as `comm/<op>_bytes` / `comm/<op>_calls` counters and
    `comm/<op>_ms` histograms.
    """

    def __init__(self):
        self._records: Dict[str, Dict[str, float]] = {}
        self._telemetry = None

    def bind_telemetry(self, telemetry):
        """Mirror subsequent records into `telemetry`'s registry."""
        self._telemetry = telemetry

    def record(self, op_name, nbytes, seconds=None, calls=1):
        rec = self._records.setdefault(
            op_name, {"calls": 0, "bytes": 0, "seconds": 0.0})
        rec["calls"] += int(calls)
        rec["bytes"] += int(nbytes)
        if seconds is not None:
            rec["seconds"] += float(seconds)
        t = self._telemetry
        if t is not None:
            t.inc(f"comm/{op_name}_bytes", int(nbytes))
            t.inc(f"comm/{op_name}_calls", int(calls))
            if seconds is not None:
                t.observe(f"comm/{op_name}_ms", float(seconds) * 1e3)

    def bytes_of(self, op_name):
        return int(self._records.get(op_name, {}).get("bytes", 0))

    def calls_of(self, op_name):
        return int(self._records.get(op_name, {}).get("calls", 0))

    def total_bytes(self):
        return sum(int(r["bytes"]) for r in self._records.values())

    def snapshot(self):
        return {op: dict(rec) for op, rec in self._records.items()}

    def reset(self):
        self._records.clear()


stats = CommStats()


def _payload_bytes(tree):
    """Static payload bytes of a pytree of (possibly traced) arrays."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", ())
        size = 1
        for d in shape:
            size *= int(d)
        total += size * jnp.dtype(getattr(leaf, "dtype", jnp.float32)).itemsize
    return total


def _axis_size(axis_name):
    """Size of a named axis (or tuple of axes) inside a shard_map trace."""
    return int(jax.lax.psum(1, axis_name))


# ------------------------------------------------------------------
# Op registry: one name → eager + in-jit implementations
# ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    name: str
    lax: Callable        # in-shard_map implementation (instrumented)
    eager: Optional[Callable] = None   # global-array facade implementation


_OPS: Dict[str, CollectiveOp] = {}


def register_op(name, *, lax, eager=None):
    """Register (or re-register) a collective under `name`.

    `lax` is the in-shard_map form; `eager` the global-array facade form
    (comm/comm.py registers its timed wrappers at import). Re-registration
    replaces the entry — transform/logging wrappers plug in under every
    consumer by wrapping here once.
    """
    op = CollectiveOp(name=name, lax=lax, eager=eager)
    _OPS[name] = op
    return op


def get_op(name):
    if name not in _OPS:
        raise ValueError(
            f"unknown collective op {name!r}; registered ops: "
            f"{sorted(_OPS)}")
    return _OPS[name]


def op_names():
    return tuple(sorted(_OPS))


def collective(name, *args, **kwargs):
    """In-jit dispatch through the registry (use inside shard_map bodies)."""
    return get_op(name).lax(*args, **kwargs)


def run(name, *args, **kwargs):
    """Eager dispatch through the registry (global arrays in, global out)."""
    op = get_op(name)
    if op.eager is None:
        raise ValueError(
            f"collective op {name!r} has no eager implementation; "
            "use it inside a shard_map body via collective()")
    return op.eager(*args, **kwargs)


# ------------------------------------------------------------------
# Instrumented in-jit primitives (use these inside shard_map bodies)
# ------------------------------------------------------------------


def psum(x, axis_name, *, repeats=1):
    if _axis_size(axis_name) > 1:
        stats.record("all_reduce", _payload_bytes(x) * repeats, calls=repeats)
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name, *, repeats=1):
    if _axis_size(axis_name) > 1:
        stats.record("all_reduce", _payload_bytes(x) * repeats, calls=repeats)
    return jax.lax.pmean(x, axis_name)


def all_gather(x, axis_name, *, axis=0, tiled=False, repeats=1):
    if _axis_size(axis_name) > 1:
        stats.record("all_gather", _payload_bytes(x) * repeats, calls=repeats)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, *, scatter_dimension=0, tiled=True,
                   repeats=1):
    if _axis_size(axis_name) > 1:
        stats.record("reduce_scatter", _payload_bytes(x) * repeats,
                     calls=repeats)
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=tiled)


def all_to_all(x, axis_name, *, split_axis, concat_axis, tiled=False,
               repeats=1):
    if _axis_size(axis_name) > 1:
        stats.record("all_to_all", _payload_bytes(x) * repeats, calls=repeats)
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm, *, repeats=1):
    if _axis_size(axis_name) > 1:
        stats.record("ppermute", _payload_bytes(x) * repeats, calls=repeats)
    return jax.lax.ppermute(x, axis_name, perm)


register_op("all_reduce", lax=psum)
register_op("all_gather", lax=all_gather)
register_op("reduce_scatter", lax=reduce_scatter)
register_op("all_to_all", lax=all_to_all)
register_op("ppermute", lax=ppermute)


# ------------------------------------------------------------------
# Wire transforms (compression hooks)
# ------------------------------------------------------------------


def group_quant_int8(x, group_size=DEFAULT_GROUP_SIZE):
    """x: [..., D] → (int8 [..., D], f32 scales [..., D//group_size]).

    Groupwise symmetric quant, scale = max|group|/127 — the ZeRO++ qwZ/qgZ
    rule and the same semantics `ops/pallas/quant.py` implements on-chip.
    This is the single definition; `runtime/quantized_collectives.py`
    imports it.
    """
    D = x.shape[-1]
    g = max(1, D // group_size) if D % group_size == 0 else 1
    gs = D // g
    xg = x.astype(jnp.float32).reshape(x.shape[:-1] + (g, gs))
    amax = jnp.max(jnp.abs(xg), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xg / scale[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), scale


def group_dequant_int8(q, scale, dtype):
    """Inverse of `group_quant_int8` (reduction happens in f32 downstream)."""
    D = q.shape[-1]
    g = scale.shape[-1]
    gs = D // g
    x = q.astype(jnp.float32).reshape(q.shape[:-1] + (g, gs)) * scale[..., None]
    return x.reshape(q.shape).astype(dtype)


def _pack_signs(bits):
    """bool [..., M] with M % 8 == 0 → uint8 [..., M//8]."""
    b = bits.reshape(bits.shape[:-1] + (-1, 8)).astype(jnp.int32)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))
    return jnp.sum(b * weights, axis=-1).astype(jnp.uint8)


def _unpack_signs(packed, numel):
    """uint8 [..., P] → f32 [..., numel] of ±1 (bit set → +1)."""
    bits = (packed[..., :, None].astype(jnp.int32)
            >> jnp.arange(8, dtype=jnp.int32)) & 1
    flat = bits.reshape(packed.shape[:-1] + (-1,))[..., :numel]
    return (flat * 2 - 1).astype(jnp.float32)


def onebit_encode(x):
    """Flat f32 [N] → (packed signs uint8 [ceil(N/8)], scale f32 [1]).

    sign(x) * mean|x| — the 1-bit Adam compression rule
    (`runtime/compressed_grads.py`'s `_sign_compress`), with sign(0) → +1 so
    every value packs to exactly one bit.
    """
    numel = x.shape[0]
    scale = jnp.mean(jnp.abs(x))[None]
    pad = (-numel) % 8
    bits = x >= 0
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros((pad,), bool)])
    return _pack_signs(bits), scale


def onebit_decode(packed, scale, numel):
    """Inverse of `onebit_encode`: ±scale values, f32 [..., numel]."""
    return _unpack_signs(packed, numel) * scale[..., :1]


@dataclasses.dataclass(frozen=True)
class WireTransform:
    """Encode/decode pair over f32 payloads.

    encode: f32 [..., D] → (payloads: tuple of arrays, meta: dict of static
    values); every payload keeps the input's leading dims so the collective
    moves them uniformly. decode: (payloads, meta) → f32 [..., D].
    """
    name: str
    encode: Callable[[jnp.ndarray], Tuple[Tuple[jnp.ndarray, ...], dict]]
    decode: Callable[[Tuple[jnp.ndarray, ...], dict], jnp.ndarray]


def _none_encode(x):
    return (x.astype(jnp.float32),), {}


def _none_decode(payloads, meta):
    return payloads[0]


def _int8_encode(x, group_size=DEFAULT_GROUP_SIZE):
    q, scale = group_quant_int8(x, group_size)
    return (q, scale), {}


def _int8_decode(payloads, meta):
    q, scale = payloads
    return group_dequant_int8(q, scale, jnp.float32)


def _onebit_encode_t(x):
    packed, scale = onebit_encode(x.reshape(-1))
    return (packed, scale), {"numel": int(x.shape[-1])}


def _onebit_decode_t(payloads, meta):
    packed, scale = payloads
    return onebit_decode(packed, scale, meta["numel"])


_TRANSFORMS: Dict[str, WireTransform] = {}


def register_transform(transform):
    _TRANSFORMS[transform.name] = transform
    return transform


def get_transform(name, group_size=DEFAULT_GROUP_SIZE):
    if name == "int8" and group_size != DEFAULT_GROUP_SIZE:
        return WireTransform(
            name="int8",
            encode=lambda x: _int8_encode(x, group_size),
            decode=_int8_decode)
    if name not in _TRANSFORMS:
        raise ValueError(
            f"unknown wire transform {name!r}; registered transforms: "
            f"{sorted(_TRANSFORMS)}")
    return _TRANSFORMS[name]


def transform_names():
    return tuple(sorted(_TRANSFORMS))


register_transform(WireTransform("none", _none_encode, _none_decode))
register_transform(WireTransform("int8", _int8_encode, _int8_decode))
register_transform(WireTransform("onebit", _onebit_encode_t,
                                 _onebit_decode_t))


# ------------------------------------------------------------------
# Composite compressed collectives (built on the instrumented primitives,
# inside shard_map bodies)
# ------------------------------------------------------------------


def transform_all_gather(x, axis_name, transform="int8",
                         group_size=DEFAULT_GROUP_SIZE, out_dtype=None):
    """All-gather with an encoded wire: local [...] → stacked [n, ...].

    The payloads (e.g. int8 values + f32 group scales) cross the wire;
    decode happens on the receiver. ``transform="none"`` degenerates to a
    plain instrumented all_gather.
    """
    out_dtype = out_dtype or x.dtype
    if transform == "none":
        return all_gather(x.astype(out_dtype), axis_name)
    t = get_transform(transform, group_size)
    flat = x.reshape(-1)
    payloads, meta = t.encode(flat)
    gathered = tuple(all_gather(p, axis_name) for p in payloads)
    deq = t.decode(gathered, meta)                    # [n, numel] f32
    n = deq.shape[0]
    return deq.reshape((n,) + x.shape).astype(out_dtype)


def transform_reduce_scatter(x, axis_name, transform="int8",
                             group_size=DEFAULT_GROUP_SIZE):
    """Reduce-scatter with an encoded wire: flat [N] (N % n == 0) → [N/n] f32
    sum. Encoded chunks move via all_to_all; receivers decode and reduce in
    f32 (the qgZ dequant-reduce). Supported transforms: none, int8 — onebit
    has no scatter form (use `compressed_all_reduce` with error feedback).
    """
    if transform not in ("none", "int8"):
        raise ValueError(
            f"transform_reduce_scatter supports transforms ('none', 'int8'); "
            f"got {transform!r}")
    n = _axis_size(axis_name)
    N = x.shape[0]
    if N % n != 0:
        raise ValueError(
            f"transform_reduce_scatter: leading dim {N} not divisible by "
            f"axis size {n}")
    if transform == "none":
        return reduce_scatter(x.astype(jnp.float32), axis_name)
    t = get_transform(transform, group_size)
    chunks = x.astype(jnp.float32).reshape(n, N // n)
    payloads, meta = t.encode(chunks)
    received = tuple(
        all_to_all(p, axis_name, split_axis=0, concat_axis=0)
        for p in payloads)
    deq = t.decode(received, meta)                    # [n, N//n] f32
    return jnp.sum(deq, axis=0)


def transform_all_to_all(x, axis_name, *, split_axis, concat_axis,
                         tiled=True, transform="none",
                         group_size=DEFAULT_GROUP_SIZE, out_dtype=None):
    """All-to-all with an encoded wire — the MoE expert-dispatch primitive.

    ``transform="none"`` degenerates to the plain instrumented all_to_all.
    With ``"int8"`` the groupwise-quantized payload (int8 values + f32 group
    scales, both keeping the input's leading dims) crosses the wire and the
    receiver dequantizes — the ZeRO++ qgZ rule applied to activation dispatch.
    ``"onebit"`` is rejected: sign+mean-magnitude destroys routed activations
    (it is a gradient wire with error feedback, not an activation codec).
    """
    if transform == "onebit":
        raise ValueError(
            "transform_all_to_all does not support 'onebit' — the 1-bit wire "
            "is an error-feedback gradient codec, not an activation codec; "
            "use transform='int8' for compressed expert dispatch")
    out_dtype = out_dtype or x.dtype
    if transform == "none":
        return all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)
    t = get_transform(transform, group_size)
    payloads, meta = t.encode(x)
    moved = tuple(
        all_to_all(p, axis_name, split_axis=split_axis,
                   concat_axis=concat_axis, tiled=tiled)
        for p in payloads)
    return t.decode(moved, meta).astype(out_dtype)


def compressed_all_reduce(x, axis_name, transform="none",
                          group_size=DEFAULT_GROUP_SIZE, err=None):
    """SUM over `axis_name` with a compressed wire (inside shard_map).

    ``"none"``/``"int8"`` run the 2-hop reduce-scatter + all-gather scheme
    (the qgZ structure); ``"onebit"`` runs the 1-bit Adam error-feedback
    reduce — requires ``err`` (the per-rank f32 compression residual, same
    shape as ``x``) and returns ``(sum, new_err)`` instead of the bare sum.

    Axis size 1 is the identity (onebit still returns its residual pair).
    """
    if transform not in TRANSFORM_NAMES:
        raise ValueError(
            f"compressed_all_reduce supports transforms {TRANSFORM_NAMES}; "
            f"got {transform!r}")
    if transform == "onebit":
        if err is None:
            raise ValueError(
                "compressed_all_reduce(transform='onebit') needs `err`, the "
                "error-feedback residual carried between steps (init zeros)")
        return _onebit_allreduce(x, axis_name, err)
    n = _axis_size(axis_name)
    if n == 1:
        return x.astype(jnp.float32)
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    numel = flat.shape[0]
    pad = (-numel) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    mine = transform_reduce_scatter(flat, axis_name, transform, group_size)
    full = transform_all_gather(mine, axis_name, transform, group_size,
                                out_dtype=jnp.float32)
    return full.reshape(-1)[:numel].reshape(shape)


def _onebit_allreduce(x, axis_name, err):
    """1-bit error-feedback allreduce: compensate → sign+scale → gather →
    decode+sum. The residual (what compression lost this step) feeds back
    next step, keeping the long-run mean unbiased — the 1-bit Adam scheme.
    Wire payload: 1 bit per element + one f32 scale per rank.
    """
    c = x.astype(jnp.float32) + err.astype(jnp.float32)
    shape = c.shape
    flat = c.reshape(-1)
    numel = flat.shape[0]
    packed, scale = onebit_encode(flat)
    decoded_self = onebit_decode(packed, scale, numel)
    new_err = (flat - decoded_self).reshape(shape)
    if _axis_size(axis_name) == 1:
        return decoded_self.reshape(shape), new_err
    p_all = all_gather(packed, axis_name)             # [n, P] uint8
    s_all = all_gather(scale, axis_name)              # [n, 1] f32
    vals = onebit_decode(p_all, s_all, numel)         # [n, numel] f32
    return jnp.sum(vals, axis=0).reshape(shape), new_err


def onebit_error_init(tree):
    """Zero error-feedback residuals matching a grad pytree (f32)."""
    return jax.tree_util.tree_map(
        lambda g: jnp.zeros(g.shape, jnp.float32), tree)
