"""`deepspeed_tpu.zero` — the reference's `deepspeed.zero` namespace.

Reference surface (`deepspeed/runtime/zero/__init__.py` via
`deepspeed/__init__.py`): `zero.Init` (construction-time partitioning,
`zero/partition_parameters.py:723`), `zero.GatheredParameters`
(`partition_parameters.py:2204`), `zero.TiledLinear` (`zero/tiling.py`),
`zero.register_external_parameter` (`zero/partition_parameters.py:85`).

TPU mapping: stage-3 partitioning is a sharding policy, not module surgery, so
most of this namespace collapses into three facts —

  * construction-time partitioning = `ModelSpec(init_fn=...)`: the engine
    materializes each leaf directly into its shard (see
    `utils/init_on_device.py`); `Init` here is the reference-shaped wrapper;
  * a sharded `jax.Array` is LOGICALLY WHOLE: reading it (device_get,
    indexing) is already the "gather", so `GatheredParameters` is a thin
    context that yields host copies; with `modifier_rank` set it writes
    modifications back with the original shardings (without it, reads are
    read-only and edits are discarded — reference semantics);
  * hook-registration (`register_external_parameter`) has no SPMD equivalent
    to register — XLA sees every use of every parameter; kept as a no-op for
    call-site compatibility.
"""

import contextlib

import jax

from deepspeed_tpu.runtime.tiling import TiledLinear  # re-export (zero/tiling.py)
from deepspeed_tpu.utils.init_on_device import OnDevice, abstract_init, \
    materialize_sharded
from deepspeed_tpu.utils.logging import logger

__all__ = ["Init", "GatheredParameters", "TiledLinear",
           "register_external_parameter", "unregister_external_parameter"]


class Init(OnDevice):
    """Reference-shaped `zero.Init` (`zero/partition_parameters.py:723`).

    Idiomatic use on TPU is simply::

        spec = ModelSpec(loss_fn=..., init_fn=my_init_fn)   # or
        engine, *_ = initialize(model=loss_fn, model_parameters=my_init_fn, ...)

    — the engine shards the abstract shapes first and runs the initializer
    with ``out_shardings``, so the full model never materializes. This class
    keeps the reference's context-manager call shape for ported code; the
    reference's CUDA/NVMe placement knobs are accepted and ignored (sharded
    placement is the config's job here).

        with zero.Init(config_dict_or_path=cfg):
            spec = make_gpt_model(cfg=model_cfg, abstract=True)
    """

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear=True, remote_device=None,
                 pin_memory=False, config_dict_or_path=None, config=None,
                 enabled=True, dtype=None, mpu=None, sequence_data_parallel_group=None,
                 param_swapper=None):
        super().__init__(dtype=dtype, device="meta", enabled=enabled)
        self.config = config_dict_or_path if config_dict_or_path is not None else config
        if remote_device not in (None, "cpu", "nvme"):
            logger.warning(f"zero.Init: ignoring remote_device={remote_device!r} "
                           "(sharded placement is the ZeRO policy's job on TPU)")


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank=None, fwd_module=None, enabled=True):
    """Reference `zero.GatheredParameters` (`partition_parameters.py:2204`):
    temporarily gather stage-3 partitioned params for host-side inspection or
    modification.

    On TPU a sharded `jax.Array` is logically whole, so "gathering" for READS
    is free — this context yields host numpy copies. Reference semantics for
    writes (`partition_parameters.py:2258`): with ``modifier_rank=None`` the
    gather is read-only and modifications are DISCARDED on exit; with a rank
    set, modifications persist — here every yielded leaf (mutated in place or
    replaced) is placed back with its original sharding on exit (the
    re-partition step of the reference's exit). Which rank is irrelevant
    under single-program SPMD. Writeback requires a dict or list container
    (in-place update of the caller's reference); other pytrees raise."""
    if not enabled:
        yield params
        return
    if modifier_rank is not None and not isinstance(params, (dict, list)):
        raise TypeError(
            "GatheredParameters(modifier_rank=...): writeback needs a dict "
            "or list container (in-place update of the caller's reference); "
            f"got {type(params).__name__}. Re-partition manually with "
            "jax.device_put(leaf, old.sharding) instead.")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    host = [jax.device_get(l) for l in leaves]
    if modifier_rank is not None:
        # device_get views are read-only; writers get mutable copies
        import numpy as _np
        host = [_np.array(h) for h in host]
    out = jax.tree_util.tree_unflatten(treedef, list(host))
    yield out
    if modifier_rank is None:
        return  # read-only gather: edits discarded (reference parity; the
        #         read-only device_get views make accidental writes raise)
    # device_put every jax.Array leaf: catches both replaced leaves and
    # in-place numpy mutation of the gathered copies (this path is host-side
    # surgery, never hot — upload cost is irrelevant next to silently
    # dropping an edit). Non-device leaves (plain numpy/scalars mixed into
    # the tree) pass through by value.
    new_leaves = jax.tree_util.tree_leaves(out)
    for i, (old, new) in enumerate(zip(leaves, new_leaves)):
        if hasattr(old, "sharding"):
            leaves[i] = jax.device_put(jax.numpy.asarray(new, old.dtype),
                                       old.sharding)
        else:
            leaves[i] = new
    updated = jax.tree_util.tree_unflatten(treedef, leaves)
    if isinstance(params, dict):
        params.update(updated)
    else:
        params[:] = updated


def register_external_parameter(module, parameter):
    """Reference `zero.register_external_parameter`: tells the stage-3 hook
    machinery that a module accesses a parameter it doesn't own. SPMD needs no
    registration — XLA traces every use of every array — so this is a no-op
    kept for call-site compatibility."""
    return None


def unregister_external_parameter(module, parameter):
    """Counterpart no-op (see `register_external_parameter`)."""
    return None
