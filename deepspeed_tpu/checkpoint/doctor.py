"""`dstpu_ckpt_doctor` — offline checkpoint validation & repair.

Validates a checkpoint root (the dir holding `latest` + tag dirs) offline —
all validation logic lives in `checkpoint/manifest.py` (stdlib only; no
device runtime is touched and no state is deserialized). Reports, per tag:

  * committed vs uncommitted (manifest present), step, size,
  * integrity (every manifested file present, sized right, crc32-clean),
  * whether `latest` resolves to a committed, valid tag,

and can repair: `--gc` removes orphaned `.tmp` staging dirs from crashed
saves, `--fix-latest` rewrites a missing/stale `latest` to the newest valid
tag, `--keep-last-n N` applies the retention policy.

Exit code 0 iff at least one valid committed tag exists and `latest` (after
any `--fix-latest`) resolves to a valid tag.
"""

import argparse
import json
import pathlib
import sys

from deepspeed_tpu.checkpoint import manifest as manifest_mod


def _tag_report(ckpt_dir, deep):
    m = manifest_mod.read_manifest(ckpt_dir)
    if m is None:
        return {"tag": ckpt_dir.name, "committed": False, "valid": False,
                "errors": ["no manifest (legacy or interrupted save)"]}
    ok, errors = manifest_mod.verify_manifest(ckpt_dir, deep=deep)
    return {"tag": ckpt_dir.name, "committed": True, "valid": ok,
            "step": m.get("step"), "engine": m.get("engine"),
            "bytes": m.get("total_bytes"),
            "world": m.get("world", {}), "errors": errors}


def diagnose(root, deep=True):
    """Full report dict for a checkpoint root."""
    root = pathlib.Path(root)
    report = {"root": str(root), "tags": [], "orphaned_tmp": [],
              "latest": None, "latest_valid": False, "newest_valid_tag": None}
    if not root.is_dir():
        report["error"] = "not a directory"
        return report
    latest_file = root / manifest_mod.LATEST_FILE
    if latest_file.exists():
        try:
            report["latest"] = latest_file.read_text().strip() or None
        except OSError:
            pass
    for child in sorted(root.iterdir()):
        if not child.is_dir():
            continue
        if child.name.endswith(manifest_mod.TMP_SUFFIX):
            report["orphaned_tmp"].append(child.name)
            continue
        if manifest_mod.read_manifest(child) is None \
                and not (child / "state").exists() \
                and not (child / "client.json").exists():
            continue  # unrelated directory
        report["tags"].append(_tag_report(child, deep))
    valid = [t for t in report["tags"] if t["valid"]]
    if valid:
        report["newest_valid_tag"] = max(
            valid, key=lambda t: t.get("step") or -1)["tag"]
    report["latest_valid"] = any(t["tag"] == report["latest"] and t["valid"]
                                 for t in report["tags"])
    return report


def _print_human(report):
    print(f"checkpoint root: {report['root']}")
    if report.get("error"):
        print(f"  ERROR: {report['error']}")
        return
    for t in sorted(report["tags"], key=lambda t: (t.get("step") is None,
                                                   t.get("step") or 0)):
        status = ("OK" if t["valid"] else
                  "CORRUPT" if t["committed"] else "UNCOMMITTED")
        size = t.get("bytes")
        size_s = f"{size / 2**20:8.1f} MiB" if isinstance(size, (int, float)) \
            else "        ?"
        step = t.get("step")
        print(f"  [{status:11s}] {t['tag']:<24s} step={step!s:<8s} {size_s}")
        for err in t.get("errors", [])[:5]:
            print(f"               - {err}")
        extra = len(t.get("errors", [])) - 5
        if extra > 0:
            print(f"               - (+{extra} more)")
    for name in report["orphaned_tmp"]:
        print(f"  [ORPHANED   ] {name}  (crashed save staging dir)")
    latest = report["latest"]
    if latest is None:
        print("  latest: MISSING", end="")
    else:
        print(f"  latest -> {latest} "
              f"({'valid' if report['latest_valid'] else 'INVALID/stale'})",
              end="")
    nv = report["newest_valid_tag"]
    print(f"  | newest valid tag: {nv if nv else 'NONE'}")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="dstpu_ckpt_doctor",
        description="validate (and optionally repair) a deepspeed-tpu "
                    "checkpoint directory offline")
    parser.add_argument("checkpoint_dir", help="checkpoint root "
                        "(contains `latest` and tag dirs)")
    parser.add_argument("--tag", default=None,
                        help="validate only this tag")
    parser.add_argument("--fast", action="store_true",
                        help="skip crc32 content checksums (existence+size only)")
    parser.add_argument("--gc", action="store_true",
                        help="remove orphaned .tmp staging dirs")
    parser.add_argument("--fix-latest", action="store_true",
                        help="rewrite `latest` to the newest valid tag when "
                             "missing or pointing at an invalid tag")
    parser.add_argument("--keep-last-n", type=int, default=0,
                        help="apply retention: delete committed tags beyond "
                             "the newest N (never touches uncommitted dirs)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the report as JSON")
    args = parser.parse_args(argv)

    root = pathlib.Path(args.checkpoint_dir)
    deep = not args.fast

    if args.tag is not None:
        t = _tag_report(root / args.tag, deep)
        if args.as_json:
            print(json.dumps(t, indent=2))
        else:
            _print_human({"root": str(root), "tags": [t], "orphaned_tmp": [],
                          "latest": None, "latest_valid": False,
                          "newest_valid_tag": t["tag"] if t["valid"] else None})
        return 0 if t["valid"] else 1

    report = diagnose(root, deep=deep)
    actions = {}
    if args.gc:
        actions["removed_tmp"] = manifest_mod.gc_orphaned_tmp(root)
        report["orphaned_tmp"] = []
    if args.fix_latest and not report["latest_valid"] \
            and report["newest_valid_tag"]:
        manifest_mod.atomic_write_text(root / manifest_mod.LATEST_FILE,
                                       report["newest_valid_tag"])
        report["latest"] = report["newest_valid_tag"]
        report["latest_valid"] = True
        actions["fixed_latest"] = report["newest_valid_tag"]
    if args.keep_last_n > 0:
        protect = (report["latest"], report["newest_valid_tag"])
        actions["retention_removed"] = manifest_mod.retention_gc(
            root, args.keep_last_n, protect=protect)
        report = diagnose(root, deep=False) | {"actions": actions}
    if actions:
        report["actions"] = actions

    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        _print_human(report)
        for k, v in actions.items():
            print(f"  action {k}: {v}")

    healthy = report["newest_valid_tag"] is not None and (
        report["latest_valid"] or report["latest"] is None)
    return 0 if healthy else 1


if __name__ == "__main__":
    raise SystemExit(main())
