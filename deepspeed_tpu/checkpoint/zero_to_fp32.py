"""Offline fp32 consolidation — `zero_to_fp32` analog.

Reference: `deepspeed/utils/zero_to_fp32.py` (587 LoC) — a standalone script that
DeepSpeed copies into every checkpoint directory (`runtime/engine.py:3366`) so a
user can reassemble the full fp32 state dict from ZeRO-partitioned shard files
without an engine or a distributed launch.

TPU analog: our checkpoints store the whole TrainState through orbax (sharding
recorded in array metadata) or the npz fallback, so "consolidation" is: restore
on host, pick the fp32 master tree (fall back to params when training was pure
fp32/bf16 without master copies), cast to fp32, and emit one flat
``{path: np.ndarray}`` dict. Works on CPU with no TPU attached.

Usage (CLI, mirrors the reference's):
    python -m deepspeed_tpu.checkpoint.zero_to_fp32 <checkpoint_dir> <output.npz> [--tag TAG]
"""

import argparse
import json
import os
import pathlib

import numpy as np

LATEST_FILE = "latest"


def _read_latest(ckpt_root):
    latest = pathlib.Path(ckpt_root) / LATEST_FILE
    if latest.exists():
        return latest.read_text().strip()
    return None


def _flatten(tree, prefix=()):
    """pytree -> {dot.path: leaf} with stable, human-readable keys."""
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.update(_flatten(tree[k], prefix + (str(k),)))
    elif isinstance(tree, (list, tuple)) and not hasattr(tree, "_fields"):
        for i, v in enumerate(tree):
            out.update(_flatten(v, prefix + (str(i),)))
    elif hasattr(tree, "_fields"):  # NamedTuple
        for name in tree._fields:
            out.update(_flatten(getattr(tree, name), prefix + (name,)))
    elif tree is None:
        pass
    else:
        out[".".join(prefix)] = tree
    return out


def _restore_state_tree(state_path):
    """Load a saved TrainState directory (orbax or npz) as host numpy trees.

    Restores every leaf as np.ndarray (no device placement), so consolidation
    works on ANY machine — including one with a different device count than
    the training job that wrote the checkpoint (the whole point of the
    reference's offline zero_to_fp32 script)."""
    npz = os.path.join(state_path, "state.npz")
    if os.path.exists(npz):
        keys_file = os.path.join(state_path, "keys.json")
        if os.path.exists(keys_file):
            # named npz (NumpyCheckpointEngine's keys.json): rebuild the
            # nested TrainState-shaped dict so conversion sees params/master
            import json as _json
            with open(keys_file) as f:
                names = _json.load(f)
            nested = {}
            with np.load(npz) as data:
                for i, name in enumerate(names):
                    parts = name.split("/")
                    d = nested
                    for p in parts[:-1]:
                        d = d.setdefault(p, {})
                    d[parts[-1]] = data[f"arr_{i}"]
            return nested, "npz-named"
        with np.load(npz) as data:
            return {k: data[k] for k in data.files}, "npz"
    import jax
    import orbax.checkpoint as ocp
    path = os.path.abspath(state_path)
    ckptr = ocp.PyTreeCheckpointer()
    meta = ckptr.metadata(path)
    tree = getattr(meta, "item_metadata", meta)
    restore_args = jax.tree_util.tree_map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree)
    restored = ckptr.restore(path, args=ocp.args.PyTreeRestore(restore_args=restore_args))
    return restored, "orbax"


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Full fp32 params as {path: np.ndarray} (reference
    `get_fp32_state_dict_from_zero_checkpoint`)."""
    tag = tag or _read_latest(checkpoint_dir)
    if tag is None:
        raise FileNotFoundError(f"no '{LATEST_FILE}' file in {checkpoint_dir}; pass --tag")
    state_path = os.path.join(checkpoint_dir, str(tag), "state")
    if not os.path.isdir(state_path):
        raise FileNotFoundError(f"no state dir at {state_path}")
    restored, fmt = _restore_state_tree(state_path)

    if fmt == "npz":
        # legacy npz (no keys.json): flat positional list; param/master split
        # is not recoverable without the engine's treedef — return raw leaves.
        return {k: np.asarray(v, np.float32) for k, v in restored.items()}

    # orbax / named npz: TrainState structure round-trips as a dict-like pytree
    tree = restored
    master = tree.get("master") if isinstance(tree, dict) else getattr(tree, "master", None)
    params = tree.get("params") if isinstance(tree, dict) else getattr(tree, "params", None)
    source = master if master is not None else params
    if source is None:
        raise ValueError("checkpoint has neither 'master' nor 'params' trees")
    flat = _flatten(source)
    return {k: np.asarray(v, np.float32) for k, v in flat.items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file, tag=None):
    """Write the consolidated fp32 dict to one .npz (reference writes a torch
    ``pytorch_model.bin``)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=tag)
    out = pathlib.Path(output_file)
    out.parent.mkdir(parents=True, exist_ok=True)
    np.savez(out, **sd)
    meta = {"num_params": len(sd),
            "total_elems": int(sum(int(np.prod(v.shape)) for v in sd.values()))}
    print(json.dumps(meta))
    return output_file


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Consolidate a deepspeed_tpu checkpoint into one fp32 npz")
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("--tag", default=None)
    args = parser.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, tag=args.tag)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
