"""Legacy state-dict loaders with TP-degree resharding.

Reference: `runtime/state_dict_factory.py:21` (`SDLoaderFactory`) and `:190`
(`MegatronSDLoader`) — at inference load time, N saved tensor-parallel shard
files are merged (N→1), split (1→M), or resharded (N→M) to the serving TP
degree, with qkv tensors needing ordering-aware treatment because the three
projections are interleaved differently per model family.

TPU analog: shards are flat ``{name: np.ndarray}`` dicts; resharding is pure
numpy on host before `jax.device_put` onto the serving mesh. Merge/split axes
come from a rules table (name-pattern → axis / qkv mode), the same role as the
reference's per-architecture policy classes.
"""

import fnmatch
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


@dataclass
class ShardRule:
    """How one parameter reshards across TP ranks.

    axis: concat/split axis; None = replicated (must be identical across shards).
    qkv: None, 'megatron' ([q1 k1 v1 q2 k2 v2 ...] interleaved per head-group) or
         'packed' ([Q; K; V] stacked blocks).
    """
    pattern: str
    axis: Optional[int]
    qkv: Optional[str] = None


# Default rules matching our model families (gpt.py / llama.py / bert.py naming)
DEFAULT_RULES = [
    ShardRule("*attn*qkv*kernel", 1, qkv="packed"),
    ShardRule("*attn*qkv*bias", 0, qkv="packed"),
    ShardRule("*attn*out*kernel", 0),
    ShardRule("*mlp*fc_in*kernel", 1),
    ShardRule("*mlp*fc_in*bias", 0),
    ShardRule("*mlp*gate*kernel", 1),
    ShardRule("*mlp*up*kernel", 1),
    ShardRule("*mlp*fc_out*kernel", 0),
    ShardRule("*mlp*down*kernel", 0),
    ShardRule("*embed*", 0),
    ShardRule("*lm_head*kernel", 1),
]


def match_rule(name: str, rules: List[ShardRule]) -> Optional[ShardRule]:
    for rule in rules:
        if fnmatch.fnmatch(name, rule.pattern):
            return rule
    return None


def _merge_qkv_packed(parts: List[np.ndarray], axis: int) -> np.ndarray:
    """Each shard holds [Q_i; K_i; V_i] stacked on `axis`; the merged tensor must
    be [Q; K; V], i.e. concatenate per-projection then restack (reference
    `MegatronSDLoader.merge_query_key_value`)."""
    segs = [np.split(p, 3, axis=axis) for p in parts]   # [(q,k,v)] per shard
    merged = [np.concatenate([s[j] for s in segs], axis=axis) for j in range(3)]
    return np.concatenate(merged, axis=axis)


def _split_qkv_packed(full: np.ndarray, n: int, rank: int, axis: int) -> np.ndarray:
    q, k, v = np.split(full, 3, axis=axis)
    return np.concatenate([np.array_split(q, n, axis=axis)[rank],
                           np.array_split(k, n, axis=axis)[rank],
                           np.array_split(v, n, axis=axis)[rank]], axis=axis)


class SDLoaderBase:
    """Merge/split/reshard flat state-dict shards to a target TP degree."""

    def __init__(self, rules: Optional[List[ShardRule]] = None):
        self.rules = rules if rules is not None else DEFAULT_RULES

    def merge_state_dicts(self, shards: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        """N TP shards → the full (TP=1) state dict."""
        if len(shards) == 1:
            return dict(shards[0])
        out = {}
        for name in shards[0]:
            parts = [sd[name] for sd in shards]
            rule = match_rule(name, self.rules)
            if rule is None or rule.axis is None:
                out[name] = parts[0]
            elif rule.qkv == "packed":
                out[name] = _merge_qkv_packed(parts, rule.axis)
            else:
                out[name] = np.concatenate(parts, axis=rule.axis)
        return out

    def split_state_dict(self, full: Dict[str, np.ndarray], num_shards: int,
                         rank: int) -> Dict[str, np.ndarray]:
        """Full state dict → shard `rank` of `num_shards`."""
        if num_shards == 1:
            return dict(full)
        out = {}
        for name, tensor in full.items():
            rule = match_rule(name, self.rules)
            if rule is None or rule.axis is None:
                out[name] = tensor
            elif rule.qkv == "packed":
                out[name] = _split_qkv_packed(tensor, num_shards, rank, rule.axis)
            else:
                out[name] = np.array_split(tensor, num_shards, axis=rule.axis)[rank]
        return out

    def reshard(self, shards: List[Dict[str, np.ndarray]],
                target_degree: int) -> List[Dict[str, np.ndarray]]:
        """N→M resharding (reference `SDLoader.get_merge_state_dicts` /
        `get_split_state_dict` dispatch in `check_ckpt_list`-driven load)."""
        full = self.merge_state_dicts(shards)
        return [self.split_state_dict(full, target_degree, r)
                for r in range(target_degree)]


# Megatron-LM state-dict naming (both the old `attention.` and the newer
# `self_attention.` module paths). Torch Linear stores [out, in]:
# column-parallel layers (qkv, dense_h_to_4h) shard axis 0, row-parallel
# layers (attention.dense, dense_4h_to_h) shard axis 1 — the axes in the
# reference's merge/split tables (`state_dict_factory.py:301-402`).
MEGATRON_RULES = [
    ShardRule("*query_key_value.weight", 0, qkv="megatron"),
    ShardRule("*query_key_value.bias", 0, qkv="megatron"),
    ShardRule("*attention.dense.weight", 1),
    ShardRule("*mlp.dense_h_to_4h.weight", 0),
    ShardRule("*mlp.dense_h_to_4h.bias", 0),
    ShardRule("*mlp.dense_4h_to_h.weight", 1),
    ShardRule("*word_embeddings.weight", 0),
    ShardRule("*lm_head.weight", 0),
]


class MegatronSDLoader(SDLoaderBase):
    """Checkpoint-version-aware qkv merge/split (reference
    `MegatronSDLoader.merge_query_key_value` / `split_query_key_value`,
    `state_dict_factory.py:220-299`). Three observed formats:

      version 0:   [(3*np*hn), h] — Q/K/V stacked blocks per shard; merging
                   must concat per projection, then restack ("packed").
      version 1.0: [(np*hn*3), h] — interleaved inside each head group;
      version 2.0: [(np*3*hn), h] — interleaved per head group.
                   For 1.0/2.0 whole head-groups travel with their rank, so
                   plain concat/split along axis 0 preserves ordering.
    """

    def __init__(self, num_heads: int = 0, rules=None, version: float = 0):
        super().__init__(rules if rules is not None else MEGATRON_RULES)
        self.num_heads = num_heads
        self.version = version

    def _qkv_packed(self):
        return self.version == 0

    def merge_state_dicts(self, shards):
        if len(shards) == 1:
            return dict(shards[0])
        out = {}
        for name in shards[0]:
            parts = [sd[name] for sd in shards]
            rule = match_rule(name, self.rules)
            if rule is None or rule.axis is None:
                out[name] = parts[0]
            elif rule.qkv == "megatron":
                out[name] = (_merge_qkv_packed(parts, rule.axis)
                             if self._qkv_packed()
                             else np.concatenate(parts, axis=rule.axis))
            elif rule.qkv == "packed":
                out[name] = _merge_qkv_packed(parts, rule.axis)
            else:
                out[name] = np.concatenate(parts, axis=rule.axis)
        return out

    def split_state_dict(self, full, num_shards, rank):
        if num_shards == 1:
            return dict(full)
        out = {}
        for name, tensor in full.items():
            rule = match_rule(name, self.rules)
            if rule is None or rule.axis is None:
                out[name] = tensor
            elif rule.qkv == "megatron":
                out[name] = (_split_qkv_packed(tensor, num_shards, rank, rule.axis)
                             if self._qkv_packed()
                             else np.array_split(tensor, num_shards,
                                                 axis=rule.axis)[rank])
            elif rule.qkv == "packed":
                out[name] = _split_qkv_packed(tensor, num_shards, rank, rule.axis)
            else:
                out[name] = np.array_split(tensor, num_shards, axis=rule.axis)[rank]
        return out


class SDLoaderFactory:
    """Reference `SDLoaderFactory.get_sd_loader` (`state_dict_factory.py:21`)."""

    @staticmethod
    def get_sd_loader(sd_type: str = "generic", **kwargs):
        sd_type = sd_type.lower()
        if sd_type in ("megatron",):
            return MegatronSDLoader(num_heads=kwargs.get("num_heads", 0),
                                    rules=kwargs.get("rules"),
                                    version=kwargs.get("version", 0))
        return SDLoaderBase(rules=kwargs.get("rules"))
