"""Checkpoint save/load with the reference's directory semantics, made
crash-safe.

Reference: `runtime/engine.py:2982` (`save_checkpoint`: tag dirs, `latest` file,
tag-consistency validation) and `:2653` (`load_checkpoint`), with the pluggable
`CheckpointEngine` ABC (`runtime/checkpoint_engine/checkpoint_engine.py:9`).

Layout:
    <save_dir>/<tag>/state/         — orbax (or npz) sharded TrainState
    <save_dir>/<tag>/client.json    — client_state (step counts, scheduler, user keys)
    <save_dir>/<tag>/manifest.json  — integrity manifest (commit marker)
    <save_dir>/latest               — text file with the most recent tag

Crash-safety contract (checkpoint/manifest.py holds the primitives):

  1. state is saved into a `<tag>.tmp` staging dir,
  2. `client.json` + `manifest.json` (per-leaf shapes/dtypes, per-file crc32,
     step, world/mesh shape, framework version) are written and fsynced there,
  3. the staging dir is rename-committed to `<tag>` (atomic on POSIX),
  4. only then does `latest` advance — itself via tempfile+rename.

A kill at ANY point leaves either a committed tag or an orphaned `.tmp` dir
(GC'd by the next save / the doctor CLI); `latest` always names a fully
committed tag. `load_checkpoint` verifies the manifest and walks back through
retained tags to the newest good one on corruption.

The sharded save/restore rides orbax (async-capable, multi-host aware) — the
TPU-native answer to per-rank `zero_pp_rank_*` shard files: the array metadata
carries the sharding, so load-time resharding to a different mesh is native
(what `ds_to_universal.py` needs offline, orbax does on the fly).
"""

import json
import os
import pathlib
import shutil
import threading
import time

import jax

from deepspeed_tpu.checkpoint import manifest as manifest_mod
from deepspeed_tpu.checkpoint.manifest import (CheckpointCorruptionError,
                                               LATEST_FILE, TMP_SUFFIX)
from deepspeed_tpu.utils.logging import logger, log_dist


# Fault-injection points (deepspeed_tpu/testing/faults.py installs hooks here
# to simulate kills at precise moments of the commit protocol):
#   after_state_save — state durable in the staging dir, metadata not yet
#   before_commit    — manifest written, rename-commit not yet executed
#   after_commit     — tag committed, `latest` not yet advanced
_FAULT_HOOKS = {}


def _fire_fault_hook(point, **ctx):
    hook = _FAULT_HOOKS.get(point)
    if hook is not None:
        hook(point=point, **ctx)


class CheckpointEngine:
    """Pluggable engine ABC (reference `checkpoint_engine.py:9`)."""

    def save(self, state, path):
        raise NotImplementedError

    def load(self, path, template):
        raise NotImplementedError

    def commit(self, tag):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    """Default: orbax StandardCheckpointer (async-capable, sharding-aware).

    `async_save=True` lets `save()` return as soon as the device arrays are
    snapshotted — serialization runs on orbax's background thread and
    `commit()` (`wait_until_finished`) is the only blocking point, which the
    atomic-commit protocol invokes right before writing the manifest.
    """

    def __init__(self, async_save=False):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.async_save = bool(async_save)
        self.checkpointer = ocp.StandardCheckpointer()

    def save(self, state, path):
        self.checkpointer.save(os.path.abspath(path), state, force=True)
        if not self.async_save:
            self.checkpointer.wait_until_finished()

    def load(self, path, template):
        self.checkpointer.wait_until_finished()
        restored = self.checkpointer.restore(os.path.abspath(path), template)
        return restored

    def commit(self, tag):
        self.checkpointer.wait_until_finished()
        return True


def _key_path_str(path):
    """Key path → "params/blocks/attn_qkv_w"-style name (same convention as
    checkpoint/universal.py's _flatten: dict keys and sequence indices as
    path segments, NamedTuple fields by name)."""
    parts = []
    for e in path:
        if hasattr(e, "name"):        # GetAttrKey (NamedTuple / dataclass)
            parts.append(str(e.name))
        elif hasattr(e, "key"):       # DictKey / FlattenedIndexKey
            parts.append(str(e.key))
        elif hasattr(e, "idx"):       # SequenceKey
            parts.append(str(e.idx))
        else:
            parts.append(str(e))
    return "/".join(parts)


def tree_entries(state):
    """Per-leaf {key, shape, dtype} manifest entries (metadata only — reads
    no device buffers)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    entries = []
    for path, leaf in flat:
        entries.append({
            "key": _key_path_str(path),
            "shape": [int(d) for d in getattr(leaf, "shape", ()) or ()],
            "dtype": str(getattr(leaf, "dtype", type(leaf).__name__)),
        })
    return entries


class NumpyCheckpointEngine(CheckpointEngine):
    """Simple single-host .npz fallback (role of TorchCheckpointEngine).

    Leaves are stored positionally (`arr_i`) for exact template round-trips,
    plus a `keys.json` recording each leaf's key path — that's what lets the
    offline universal converter recover the params/master split from an npz
    checkpoint with no engine or treedef at hand."""

    def save(self, state, path):
        import numpy as np
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        arrays = {}
        for i, (_, x) in enumerate(flat):
            arr = np.asarray(jax.device_get(x))
            if arr.dtype.kind == "V":
                # ml_dtypes leaves (bfloat16, fp8) round-trip through npz as
                # raw void — upcast to f32 (exact) and restore the template
                # dtype on load
                arr = arr.astype(np.float32)
            arrays[f"arr_{i}"] = arr
        pathlib.Path(path).mkdir(parents=True, exist_ok=True)
        np.savez(os.path.join(path, "state.npz"), **arrays)
        with open(os.path.join(path, "keys.json"), "w") as f:
            json.dump([_key_path_str(p) for p, _ in flat], f, indent=1)

    def load(self, path, template):
        import numpy as np
        flat_t, treedef = jax.tree_util.tree_flatten(template)
        flat = []
        with np.load(os.path.join(path, "state.npz")) as data:
            for i, t in enumerate(flat_t):
                arr = data[f"arr_{i}"]
                tdt = getattr(t, "dtype", None)
                if tdt is not None and arr.dtype != tdt and arr.dtype.kind != "V":
                    arr = arr.astype(tdt)
                flat.append(arr)
        return jax.tree_util.tree_unflatten(treedef, flat)


class AsyncCheckpointEngine(CheckpointEngine):
    """Async tiered save (reference `NebulaCheckpointEngine`,
    `nebula_checkpoint_engine.py:20`: snapshot fast, persist in background).

    The host copy of the state is taken synchronously (so training can mutate /
    donate device buffers immediately); serialization runs on a worker thread.
    `commit(tag)` blocks until the pending save is durable — the engine-level
    `save_checkpoint` calls it before writing `latest`, preserving the
    reference's "latest is only advanced after persist" semantics.
    """

    def __init__(self, inner: CheckpointEngine):
        self.inner = inner
        self._thread = None
        self._error = None
        self._completions = []

    def add_completion(self, fn):
        """Run `fn()` in the worker after the pending save persists — used for
        metadata whose ordering contract is "only after the state is durable"
        (manifest + rename-commit + the `latest` file)."""
        self._completions.append(fn)

    def save(self, state, path):
        host_state = jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if hasattr(x, "devices") else x, state)
        self.wait()
        completions, self._completions = self._completions, []

        def worker():
            try:
                self.inner.save(host_state, path)
                for fn in completions:
                    fn()
            except Exception as e:  # surfaced on commit/wait
                self._error = e

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def load(self, path, template):
        self.wait()
        return self.inner.load(path, template)

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def commit(self, tag):
        self.wait()
        return True


def _make_engine(config):
    name = getattr(config.checkpoint, "engine", "orbax")
    async_save = bool(getattr(config.checkpoint, "async_save", False))
    if name == "numpy":
        eng = NumpyCheckpointEngine()
    else:
        try:
            eng = OrbaxCheckpointEngine(async_save=async_save)
        except ImportError as e:
            logger.warning(f"orbax not importable ({e}); falling back to the "
                           "numpy checkpoint engine")
            eng = NumpyCheckpointEngine()
        except Exception as e:
            logger.warning(f"orbax unavailable ({e}); falling back to numpy engine")
            eng = NumpyCheckpointEngine()
    # orbax has its own async machinery (wired above); thread-wrap only the
    # numpy engine (whether requested or reached via fallback)
    if async_save and isinstance(eng, NumpyCheckpointEngine):
        eng = AsyncCheckpointEngine(eng)
    return eng


def _engine_for(engine):
    """One checkpoint engine per training engine, so async saves overlap
    training and cross-call wait() semantics hold."""
    ck = getattr(engine, "_ckpt_engine", None)
    if ck is None:
        ck = _make_engine(engine.config)
        engine._ckpt_engine = ck
    return ck


def _register_exit_drain(engine):
    """A clean interpreter exit must not abandon an in-flight async save:
    drain it at atexit (registered after orbax/concurrent.futures' own hooks,
    so it runs before them in LIFO order). A failed final save only logs —
    `latest` still names the previous committed tag by construction."""
    if getattr(engine, "_ckpt_exit_drain", None) is not None:
        return
    import atexit
    import weakref
    ref = weakref.ref(engine)

    def _drain():
        e = ref()
        if e is None:
            return
        try:
            wait_pending_save(e)
        except Exception as ex:
            logger.warning(f"final async checkpoint save failed at exit "
                           f"({ex!r}); `latest` still names the previous "
                           "committed tag")

    atexit.register(_drain)
    engine._ckpt_exit_drain = _drain


def get_latest_tag(load_dir):
    """The newest resumable tag: `latest` when it names a committed tag, else
    a scan of tag dirs (newest committed manifest wins) — a missing, empty or
    stale `latest` no longer strands an otherwise-healthy checkpoint root."""
    return manifest_mod.resolve_latest_tag(load_dir)


def wait_pending_save(engine):
    """Block until any in-flight async save (orbax background commit or the
    thread-wrapped numpy engine) is durable AND finalized (manifest written,
    tag committed, `latest` advanced). Re-raises a failed save's error."""
    t = getattr(engine, "_ckpt_pending", None)
    if t is not None:
        t.join()
        engine._ckpt_pending = None
        err = getattr(engine, "_ckpt_pending_error", None)
        engine._ckpt_pending_error = None
        if err is not None:
            raise err
    ck = getattr(engine, "_ckpt_engine", None)
    if isinstance(ck, AsyncCheckpointEngine):
        ck.wait()


def _world_info(engine):
    info = {"process_count": jax.process_count(),
            "device_count": jax.device_count()}
    mesh = getattr(engine, "mesh", None)
    if mesh is not None:
        try:
            info["mesh_shape"] = {str(a): int(s) for a, s in
                                  zip(mesh.axis_names, mesh.devices.shape)}
        except Exception:
            pass
    return info


def _emit_ckpt_events(engine, events):
    # route through the telemetry registry first (when enabled): save
    # latency becomes a `Checkpoint/save_ms` HISTOGRAM with percentiles
    # instead of a last-write-wins scalar
    telem = getattr(engine, "telemetry", None)
    if telem is not None:
        try:
            telem.record_events(events)
        except Exception as e:
            logger.warning(f"checkpoint telemetry events not recorded: {e}")
    mon = getattr(engine, "monitor", None)
    try:
        from deepspeed_tpu.monitor.monitor import write_recovery_events
        write_recovery_events(mon, events)
    except Exception as e:
        logger.warning(f"checkpoint monitor events not written: {e}")


def save_checkpoint(engine, save_dir, tag=None, client_state=None, save_latest=True):
    tag = tag if tag is not None else f"global_step{engine.global_steps}"
    tag = str(tag)
    save_dir = pathlib.Path(save_dir)
    save_dir.mkdir(parents=True, exist_ok=True)
    wait_pending_save(engine)

    stage_name = tag + TMP_SUFFIX
    if jax.process_index() == 0:
        removed = manifest_mod.gc_orphaned_tmp(save_dir, keep=None)
        if removed:
            logger.warning(f"checkpoint GC: removed orphaned staging dirs "
                           f"{removed} (crashed saves)")
    stage_dir = save_dir / stage_name
    final_dir = save_dir / tag
    stage_dir.mkdir(parents=True, exist_ok=True)

    ck_engine = _engine_for(engine)
    state_path = stage_dir / "state"
    entries = tree_entries(engine.state)
    world = _world_info(engine)
    step = int(engine.global_steps)
    engine_name = type(getattr(ck_engine, "inner", ck_engine)).__name__
    client = dict(client_state or {})
    t0 = time.monotonic()
    ckpt_cfg = getattr(engine.config, "checkpoint", None)
    keep_last_n = int(getattr(ckpt_cfg, "keep_last_n", 0) or 0)

    def finalize():
        """Runs once the state is durable in the staging dir. Order matters:
        metadata -> manifest -> rename-commit -> latest -> retention."""
        total_bytes = 0
        if jax.process_index() == 0:
            _fire_fault_hook("after_state_save", tag=tag, stage_dir=str(stage_dir))
            with open(stage_dir / "client.json", "w") as f:
                json.dump(client, f, indent=2, default=str)
            m = manifest_mod.write_manifest(
                stage_dir, tag=tag, step=step, tree=entries, world=world,
                engine=engine_name,
                extra={"framework_version": _framework_version()})
            total_bytes = m["total_bytes"]
            _fire_fault_hook("before_commit", tag=tag, stage_dir=str(stage_dir))
            aside = None
            if final_dir.exists():
                # re-save under an existing tag: rename the committed copy
                # aside (atomic) rather than rmtree'ing it — a kill between
                # the two renames leaves the old copy recoverable as a .tmp
                # orphan instead of destroying the only committed tag
                aside = save_dir / (tag + ".old" + TMP_SUFFIX)
                if aside.exists():
                    shutil.rmtree(aside)
                os.replace(final_dir, aside)
            os.replace(stage_dir, final_dir)       # COMMIT point
            manifest_mod.fsync_dir(save_dir)
            if aside is not None:
                shutil.rmtree(aside, ignore_errors=True)
            _fire_fault_hook("after_commit", tag=tag, ckpt_dir=str(final_dir))
            # ship the consolidation script next to `latest` at the save_dir
            # root (reference engine.py:3366 copies zero_to_fp32.py into the
            # save dir so `python zero_to_fp32.py . out` works in place)
            try:
                from deepspeed_tpu.checkpoint import zero_to_fp32 as _z2f
                shutil.copyfile(_z2f.__file__, save_dir / "zero_to_fp32.py")
            except Exception as e:
                logger.warning(f"could not ship zero_to_fp32.py: {e}")
            if save_latest:
                # ordering contract: `latest` only advances after the commit
                manifest_mod.atomic_write_text(save_dir / LATEST_FILE, tag)
            if keep_last_n > 0:
                latest_tag = tag if save_latest else get_latest_tag(save_dir)
                dropped = manifest_mod.retention_gc(
                    save_dir, keep_last_n, protect=(tag, latest_tag))
                if dropped:
                    log_dist(f"checkpoint retention (keep_last_n="
                             f"{keep_last_n}): removed {dropped}", ranks=[0])
        engine._last_ckpt_dir = str(save_dir)
        save_ms = (time.monotonic() - t0) * 1000.0
        _emit_ckpt_events(engine, [
            ("Checkpoint/save_ms", save_ms, step),
            ("Checkpoint/bytes", float(total_bytes), step),
            ("Checkpoint/last_good_step", float(step), step),
        ])
        log_dist(f"saved checkpoint {tag} to {final_dir} "
                 f"({total_bytes / 2**20:.1f} MiB, {save_ms:.0f} ms)", ranks=[0])

    if isinstance(ck_engine, AsyncCheckpointEngine):
        # finalization (incl. commit + `latest`) runs on the worker after
        # persist; save() returns as soon as the host snapshot is taken
        _register_exit_drain(engine)
        ck_engine.add_completion(finalize)
        ck_engine.save(engine.state, str(state_path))
    elif getattr(ck_engine, "async_save", False):
        # orbax async: the device snapshot is taken synchronously inside
        # save(); a finalizer thread blocks on orbax's background commit
        # (`wait_until_finished` — only at commit time) and then finalizes
        _register_exit_drain(engine)
        ck_engine.save(engine.state, str(state_path))

        def _commit_and_finalize():
            try:
                ck_engine.commit(tag)
                finalize()
            except Exception as e:
                engine._ckpt_pending_error = e

        engine._ckpt_pending_error = None
        engine._ckpt_pending = threading.Thread(target=_commit_and_finalize,
                                                daemon=True)
        engine._ckpt_pending.start()
    else:
        ck_engine.save(engine.state, str(state_path))
        ck_engine.commit(tag)
        finalize()
    return str(final_dir)


def _framework_version():
    try:
        import deepspeed_tpu
        return deepspeed_tpu.__version__
    except Exception:
        return "unknown"


def _load_prefixes(load_optimizer_states, load_module_only):
    """Which manifest-tree key prefixes must match the restore template: a
    partial load only consumes a subset of the state, so only that subset
    gates validation."""
    if load_module_only:
        return ("params", "master")
    if not load_optimizer_states:
        return ("params", "master", "step", "scaler")
    return None  # full structural match


def _candidate_tags(load_dir, tag):
    """Requested (or latest) tag first, then every other committed tag newest
    first — the rollback-on-corruption walk order."""
    cands = []
    if tag is not None:
        cands.append(str(tag))
    else:
        lt = get_latest_tag(load_dir)
        if lt is not None:
            cands.append(lt)
    for t, _step in manifest_mod.committed_tags(load_dir):
        if t not in cands:
            cands.append(t)
    return cands


def load_checkpoint(engine, load_dir, tag=None, load_optimizer_states=True,
                    load_module_only=False):
    wait_pending_save(engine)
    load_dir = pathlib.Path(load_dir)
    candidates = _candidate_tags(load_dir, tag)
    if not candidates:
        logger.warning(f"no checkpoint found in {load_dir} (no '{LATEST_FILE}' "
                       "file and no committed tag dirs)")
        return None, None

    ck_engine = _engine_for(engine)
    ckpt_cfg = getattr(engine.config, "checkpoint", None)
    deep = bool(getattr(ckpt_cfg, "verify_checksums", True))
    template_tree = tree_entries(engine.state)
    prefixes = _load_prefixes(load_optimizer_states, load_module_only)
    discarded = []

    for cand in candidates:
        ckpt_dir = load_dir / cand
        if not ckpt_dir.exists():
            if tag is not None and cand == str(tag):
                # an explicitly requested tag that simply isn't there is a
                # caller error, not corruption — substituting a different
                # tag here would silently load state the caller never asked
                # for (the corruption walk below only covers tags that
                # EXIST but fail validation)
                logger.warning(f"checkpoint dir {ckpt_dir} does not exist")
                return None, None
            discarded.append((cand, ["directory does not exist"]))
            continue
        m = manifest_mod.read_manifest(ckpt_dir)
        if m is None:
            # legacy pre-manifest checkpoint: accept, but only as the
            # primary candidate (never walk back INTO an unverifiable dir)
            if cand is not candidates[0]:
                discarded.append((cand, ["no manifest (legacy layout)"]))
                continue
            logger.warning(f"checkpoint {ckpt_dir} has no manifest (legacy "
                           "layout): loading without integrity verification")
        else:
            ok, errors = manifest_mod.verify_manifest(
                ckpt_dir, template_tree=template_tree, deep=deep,
                template_prefixes=prefixes)
            if not ok:
                discarded.append((cand, errors))
                logger.warning(
                    f"checkpoint {ckpt_dir} failed integrity verification "
                    f"({len(errors)} error(s): {errors[:3]}...); walking back "
                    "to an older tag")
                continue
        try:
            restored = ck_engine.load(str(ckpt_dir / "state"), engine.state)
        except Exception as e:
            discarded.append((cand, [f"restore failed: {e!r}"]))
            logger.warning(f"checkpoint {ckpt_dir} failed to restore "
                           f"({e!r}); walking back to an older tag")
            continue

        if load_module_only:
            engine.state = engine.state._replace(params=restored.params,
                                                 master=restored.master)
        elif not load_optimizer_states:
            engine.state = engine.state._replace(params=restored.params,
                                                 master=restored.master,
                                                 step=restored.step,
                                                 scaler=restored.scaler)
        else:
            engine.state = restored

        client_state = {}
        client_file = ckpt_dir / "client.json"
        if client_file.exists():
            with open(client_file) as f:
                client_state = json.load(f)
        if m is not None and client_state.get("global_steps") is not None \
                and int(client_state["global_steps"]) != int(m.get("step", -1)):
            logger.warning(
                f"checkpoint {cand}: manifest step {m.get('step')} != "
                f"client_state global_steps {client_state['global_steps']}")
        if discarded:
            names = [c for c, _ in discarded]
            logger.warning(f"recovered from {cand} after discarding corrupted/"
                           f"unusable tag(s) {names}")
            _emit_ckpt_events(engine, [
                ("Recovery/discarded_tags", float(len(discarded)),
                 int(engine.global_steps)),
            ])
        engine._last_ckpt_dir = str(load_dir)
        log_dist(f"loaded checkpoint {cand} from {ckpt_dir}", ranks=[0])
        return str(ckpt_dir), client_state

    detail = "; ".join(f"{c}: {errs[0]}" for c, errs in discarded[:5])
    raise CheckpointCorruptionError(
        f"no loadable checkpoint in {load_dir}: every retained tag failed "
        f"validation ({detail})")
