"""Checkpoint save/load with the reference's directory semantics.

Reference: `runtime/engine.py:2982` (`save_checkpoint`: tag dirs, `latest` file,
tag-consistency validation) and `:2653` (`load_checkpoint`), with the pluggable
`CheckpointEngine` ABC (`runtime/checkpoint_engine/checkpoint_engine.py:9`).

Layout:
    <save_dir>/<tag>/state/         — orbax (or npz) sharded TrainState
    <save_dir>/<tag>/client.json    — client_state (step counts, scheduler, user keys)
    <save_dir>/latest               — text file with the most recent tag

The sharded save/restore rides orbax (async-capable, multi-host aware) — the
TPU-native answer to per-rank `zero_pp_rank_*` shard files: the array metadata
carries the sharding, so load-time resharding to a different mesh is native
(what `ds_to_universal.py` needs offline, orbax does on the fly).
"""

import json
import os
import pathlib

import jax

from deepspeed_tpu.utils.logging import logger, log_dist

LATEST_FILE = "latest"


class CheckpointEngine:
    """Pluggable engine ABC (reference `checkpoint_engine.py:9`)."""

    def save(self, state, path):
        raise NotImplementedError

    def load(self, path, template):
        raise NotImplementedError

    def commit(self, tag):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    """Default: orbax StandardCheckpointer (async-capable, sharding-aware)."""

    def __init__(self, async_save=False):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.checkpointer = ocp.StandardCheckpointer()

    def save(self, state, path):
        self.checkpointer.save(os.path.abspath(path), state, force=True)
        self.checkpointer.wait_until_finished()

    def load(self, path, template):
        restored = self.checkpointer.restore(os.path.abspath(path), template)
        return restored


def _key_path_str(path):
    """Key path → "params/blocks/attn_qkv_w"-style name (same convention as
    checkpoint/universal.py's _flatten: dict keys and sequence indices as
    path segments, NamedTuple fields by name)."""
    parts = []
    for e in path:
        if hasattr(e, "name"):        # GetAttrKey (NamedTuple / dataclass)
            parts.append(str(e.name))
        elif hasattr(e, "key"):       # DictKey / FlattenedIndexKey
            parts.append(str(e.key))
        elif hasattr(e, "idx"):       # SequenceKey
            parts.append(str(e.idx))
        else:
            parts.append(str(e))
    return "/".join(parts)


class NumpyCheckpointEngine(CheckpointEngine):
    """Simple single-host .npz fallback (role of TorchCheckpointEngine).

    Leaves are stored positionally (`arr_i`) for exact template round-trips,
    plus a `keys.json` recording each leaf's key path — that's what lets the
    offline universal converter recover the params/master split from an npz
    checkpoint with no engine or treedef at hand."""

    def save(self, state, path):
        import numpy as np
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        arrays = {f"arr_{i}": np.asarray(jax.device_get(x))
                  for i, (_, x) in enumerate(flat)}
        pathlib.Path(path).mkdir(parents=True, exist_ok=True)
        np.savez(os.path.join(path, "state.npz"), **arrays)
        with open(os.path.join(path, "keys.json"), "w") as f:
            json.dump([_key_path_str(p) for p, _ in flat], f, indent=1)

    def load(self, path, template):
        import numpy as np
        flat_t, treedef = jax.tree_util.tree_flatten(template)
        with np.load(os.path.join(path, "state.npz")) as data:
            flat = [data[f"arr_{i}"] for i in range(len(flat_t))]
        return jax.tree_util.tree_unflatten(treedef, flat)


class AsyncCheckpointEngine(CheckpointEngine):
    """Async tiered save (reference `NebulaCheckpointEngine`,
    `nebula_checkpoint_engine.py:20`: snapshot fast, persist in background).

    The host copy of the state is taken synchronously (so training can mutate /
    donate device buffers immediately); serialization runs on a worker thread.
    `commit(tag)` blocks until the pending save is durable — the engine-level
    `save_checkpoint` calls it before writing `latest`, preserving the
    reference's "latest is only advanced after persist" semantics.
    """

    def __init__(self, inner: CheckpointEngine):
        import threading
        self.inner = inner
        self._thread = None
        self._error = None
        self._threading = threading
        self._completions = []

    def add_completion(self, fn):
        """Run `fn()` in the worker after the pending save persists — used for
        metadata whose ordering contract is "only after the state is durable"
        (the `latest` file)."""
        self._completions.append(fn)

    def save(self, state, path):
        host_state = jax.tree_util.tree_map(
            lambda x: jax.device_get(x) if hasattr(x, "devices") else x, state)
        self.wait()
        completions, self._completions = self._completions, []

        def worker():
            try:
                self.inner.save(host_state, path)
                for fn in completions:
                    fn()
            except Exception as e:  # surfaced on commit/wait
                self._error = e

        self._thread = self._threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def load(self, path, template):
        self.wait()
        return self.inner.load(path, template)

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def commit(self, tag):
        self.wait()
        return True


def _make_engine(config):
    name = getattr(config.checkpoint, "engine", "orbax")
    async_save = bool(getattr(config.checkpoint, "async_save", False))
    if name == "numpy":
        eng = NumpyCheckpointEngine()
    else:
        try:
            eng = OrbaxCheckpointEngine(async_save=async_save)
        except Exception as e:
            logger.warning(f"orbax unavailable ({e}); falling back to numpy engine")
            eng = NumpyCheckpointEngine()
    # orbax has its own async machinery; thread-wrap only the numpy engine
    # (whether requested or reached via fallback)
    if async_save and isinstance(eng, NumpyCheckpointEngine):
        eng = AsyncCheckpointEngine(eng)
    return eng


def _engine_for(engine):
    """One checkpoint engine per training engine, so async saves overlap
    training and cross-call wait() semantics hold."""
    ck = getattr(engine, "_ckpt_engine", None)
    if ck is None:
        ck = _make_engine(engine.config)
        engine._ckpt_engine = ck
    return ck


def get_latest_tag(load_dir):
    latest = pathlib.Path(load_dir) / LATEST_FILE
    if latest.exists():
        return latest.read_text().strip()
    return None


def save_checkpoint(engine, save_dir, tag=None, client_state=None, save_latest=True):
    tag = tag if tag is not None else f"global_step{engine.global_steps}"
    ckpt_dir = pathlib.Path(save_dir) / str(tag)
    ckpt_dir.mkdir(parents=True, exist_ok=True)

    ck_engine = _engine_for(engine)
    state_path = ckpt_dir / "state"

    def write_metadata():
        if jax.process_index() != 0:
            return
        with open(ckpt_dir / "client.json", "w") as f:
            json.dump(client_state or {}, f, indent=2, default=str)
        # ship the consolidation script next to `latest` at the save_dir root
        # (reference engine.py:3366 copies zero_to_fp32.py into the save dir so
        # `python zero_to_fp32.py . out` works in place)
        try:
            import shutil
            from deepspeed_tpu.checkpoint import zero_to_fp32 as _z2f
            shutil.copyfile(_z2f.__file__,
                            pathlib.Path(save_dir) / "zero_to_fp32.py")
        except Exception as e:
            logger.warning(f"could not ship zero_to_fp32.py: {e}")
        if save_latest:
            # ordering contract: `latest` only advances after the state persists
            with open(pathlib.Path(save_dir) / LATEST_FILE, "w") as f:
                f.write(str(tag))

    if isinstance(ck_engine, AsyncCheckpointEngine):
        # metadata (incl. `latest`) written by the worker after persist;
        # save() returns as soon as the host snapshot is taken
        ck_engine.add_completion(write_metadata)
        ck_engine.save(engine.state, str(state_path))
    else:
        ck_engine.save(engine.state, str(state_path))
        ck_engine.commit(tag)
        write_metadata()
    log_dist(f"saved checkpoint {tag} to {ckpt_dir}", ranks=[0])
    return str(ckpt_dir)


def load_checkpoint(engine, load_dir, tag=None, load_optimizer_states=True,
                    load_module_only=False):
    tag = tag or get_latest_tag(load_dir)
    if tag is None:
        logger.warning(f"no checkpoint found in {load_dir} (no '{LATEST_FILE}' file)")
        return None, None
    ckpt_dir = pathlib.Path(load_dir) / str(tag)
    if not ckpt_dir.exists():
        logger.warning(f"checkpoint dir {ckpt_dir} does not exist")
        return None, None

    ck_engine = _engine_for(engine)
    restored = ck_engine.load(str(ckpt_dir / "state"), engine.state)

    if load_module_only:
        engine.state = engine.state._replace(params=restored.params,
                                             master=restored.master)
    elif not load_optimizer_states:
        engine.state = engine.state._replace(params=restored.params,
                                             master=restored.master,
                                             step=restored.step,
                                             scaler=restored.scaler)
    else:
        engine.state = restored

    client_state = {}
    client_file = ckpt_dir / "client.json"
    if client_file.exists():
        with open(client_file) as f:
            client_state = json.load(f)
    log_dist(f"loaded checkpoint {tag} from {ckpt_dir}", ranks=[0])
    return str(ckpt_dir), client_state
