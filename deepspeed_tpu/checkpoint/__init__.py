from deepspeed_tpu.checkpoint.saver import (save_checkpoint, load_checkpoint,
                                            get_latest_tag, wait_pending_save)
from deepspeed_tpu.checkpoint.manifest import (CheckpointCorruptionError,
                                               read_manifest, verify_manifest)
