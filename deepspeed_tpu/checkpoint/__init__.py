from deepspeed_tpu.checkpoint.saver import save_checkpoint, load_checkpoint, get_latest_tag
