"""Universal checkpoints — topology-independent fp32 state.

Reference: `deepspeed/checkpoint/ds_to_universal.py:254` (offline converter:
ZeRO shards → per-param fp32 slices reshardable to new TP/PP/DP) +
`universal_checkpoint.py:12` (loader) + `utils/zero_to_fp32.py` (offline fp32
reconstruction shipped into every checkpoint dir).

On TPU, *mesh-shape* resharding is free (orbax restores to any mesh), so the
universal format's remaining jobs are: (1) parallelism-*form* conversion —
pipeline-stacked vs plain layer layouts, TP-fused vs split qkv; (2) a plain
interoperable artifact (flat name → fp32 array .npz + metadata) that any
engine, any topology, or external tooling can consume.
"""

import json
import pathlib

import numpy as np

from deepspeed_tpu.utils.logging import logger, log_dist

UNIVERSAL_FILE = "universal_fp32.npz"
META_FILE = "universal_meta.json"


def _flatten(tree, prefix=()):
    import jax
    out = {}

    def rec(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                rec(v, path + (str(k),))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                rec(v, path + (str(i),))
        elif node is None:
            pass
        else:
            out["/".join(path)] = node

    rec(tree, prefix)
    return out


def _unflatten_into(template, flat):
    """Place flat name→array entries into a params-like template pytree."""
    def rec(node, path):
        if isinstance(node, dict):
            return {k: rec(v, path + (str(k),)) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v, path + (str(i),)) for i, v in enumerate(node))
        key = "/".join(path)
        if key not in flat:
            raise KeyError(f"universal checkpoint missing param '{key}'")
        return flat[key]

    return rec(template, ())


def _write_universal(flat, out_dir, extra_meta=None):
    """Single writer of the on-disk universal format (npz + meta json)."""
    out_dir = pathlib.Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    np.savez(out_dir / UNIVERSAL_FILE, **flat)
    meta = {
        "format_version": 1,
        "param_shapes": {k: list(v.shape) for k, v in flat.items()},
    }
    meta.update(extra_meta or {})
    with open(out_dir / META_FILE, "w") as f:
        json.dump(meta, f, indent=2)
    log_dist(f"universal checkpoint -> {out_dir} ({len(flat)} tensors)", ranks=[0])
    return str(out_dir)


def save_universal_checkpoint(engine, save_dir, tag="universal"):
    """Gather full fp32 weights from the engine (whatever its ZeRO/TP/PP layout)
    and write the flat npz artifact."""
    fp32 = engine.get_fp32_state_dict()
    flat = {k: np.asarray(v, np.float32) for k, v in _flatten(fp32).items()}
    return _write_universal(flat, pathlib.Path(save_dir) / tag, {
        "global_steps": engine.global_steps,
        "zero_stage": engine.zero_stage,
        "mesh": str(engine.spec),
    })


def load_universal_checkpoint(engine, load_dir, tag="universal", strict=True):
    """Load a universal artifact into an engine of ANY topology: arrays are cast
    to the compute dtype and placed with the engine's own shardings; fp32 master
    rebuilt; optimizer state reset (reference loads fresh states too unless the
    optimizer slices were converted)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.utils.tree import tree_cast

    in_dir = pathlib.Path(load_dir) / tag
    with np.load(in_dir / UNIVERSAL_FILE) as data:
        flat = {k: data[k] for k in data.files}
    params_np = _unflatten_into(engine.state.params, flat)
    # place with engine shardings in compute dtype
    params = jax.tree_util.tree_map(
        lambda leaf, arr: jax.device_put(jnp.asarray(arr, leaf.dtype), leaf.sharding),
        engine.state.params, params_np)
    state = engine.state._replace(params=params)
    if engine.keep_master:
        master = jax.tree_util.tree_map(
            lambda leaf, arr: jax.device_put(jnp.asarray(arr, jnp.float32), leaf.sharding),
            engine.state.master, params_np)
        state = state._replace(master=master)
    engine.state = state
    meta = {}
    meta_file = in_dir / META_FILE
    if meta_file.exists():
        with open(meta_file) as f:
            meta = json.load(f)
    log_dist(f"loaded universal checkpoint from {in_dir}", ranks=[0])
    return meta


def convert_to_universal(ckpt_dir, out_dir, engine):
    """Offline `ds_to_universal` analog: load a tagged checkpoint into `engine`,
    then emit the universal artifact."""
    from deepspeed_tpu.checkpoint.saver import load_checkpoint
    path, _ = load_checkpoint(engine, ckpt_dir)
    assert path is not None, f"no checkpoint found in {ckpt_dir}"
    return save_universal_checkpoint(engine, out_dir)


def get_fp32_state_dict_from_universal(load_dir, tag="universal"):
    """zero_to_fp32-style accessor: plain dict of fp32 numpy arrays."""
    in_dir = pathlib.Path(load_dir) / tag
    with np.load(in_dir / UNIVERSAL_FILE) as data:
        return {k: data[k] for k in data.files}


def convert_checkpoint_to_universal(ckpt_dir, out_dir, tag=None, out_tag="universal"):
    """Fully offline converter (no engine needed) — the `ds_to_universal.py`
    CLI role (`checkpoint/ds_to_universal.py:254`): reconstruct the fp32 param
    tree from a saved checkpoint and write the flat universal artifact.

    Restores the checkpoint's structured TrainState directly — orbax format,
    or the numpy engine's npz (whose `keys.json` records every leaf's key
    path) — so keys match `save_universal_checkpoint` /
    `load_universal_checkpoint` exactly."""
    import os
    from deepspeed_tpu.checkpoint.zero_to_fp32 import (_read_latest,
                                                       _restore_state_tree)
    tag = tag or _read_latest(ckpt_dir)
    if tag is None:
        raise FileNotFoundError(f"no 'latest' file in {ckpt_dir}; pass --tag")
    state_path = os.path.join(ckpt_dir, str(tag), "state")
    restored, fmt = _restore_state_tree(state_path)
    if fmt not in ("orbax", "npz-named"):
        raise ValueError(
            "offline universal conversion needs an orbax-format checkpoint or "
            "a named npz (keys.json, written by this version's numpy engine); "
            "legacy positional npz cannot be mapped back to parameter names "
            "offline — use convert_to_universal(ckpt_dir, out_dir, engine)")
    master = restored.get("master") if isinstance(restored, dict) \
        else getattr(restored, "master", None)
    params = restored.get("params") if isinstance(restored, dict) \
        else getattr(restored, "params", None)
    source = master if master is not None else params
    if source is None:
        raise ValueError("checkpoint has neither 'master' nor 'params' trees")
    flat = {k: np.asarray(v, np.float32) for k, v in _flatten(source).items()}
    return _write_universal(flat, pathlib.Path(out_dir) / out_tag,
                            {"source_checkpoint": str(ckpt_dir), "tag": str(tag)})


def main(argv=None):
    """`ds_to_universal` CLI (reference bin-level converter)."""
    import argparse
    parser = argparse.ArgumentParser(
        description="convert a deepspeed-tpu checkpoint to a universal "
                    "(topology-independent) checkpoint")
    parser.add_argument("--input_folder", required=True,
                        help="checkpoint root (contains `latest` / tag dirs)")
    parser.add_argument("--output_folder", required=True,
                        help="where to write the universal artifact")
    parser.add_argument("--tag", default=None, help="checkpoint tag (default: latest)")
    parser.add_argument("--out_tag", default="universal")
    args = parser.parse_args(argv)
    out = convert_checkpoint_to_universal(args.input_folder, args.output_folder,
                                          tag=args.tag, out_tag=args.out_tag)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
