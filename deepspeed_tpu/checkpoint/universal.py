"""Universal checkpoints — topology-independent fp32 state.

Reference: `deepspeed/checkpoint/ds_to_universal.py:254` (offline converter:
ZeRO shards → per-param fp32 slices reshardable to new TP/PP/DP) +
`universal_checkpoint.py:12` (loader) + `utils/zero_to_fp32.py` (offline fp32
reconstruction shipped into every checkpoint dir).

On TPU, *mesh-shape* resharding is free (orbax restores to any mesh), so the
universal format's remaining jobs are: (1) parallelism-*form* conversion —
pipeline-stacked vs plain layer layouts, TP-fused vs split qkv; (2) a plain
interoperable artifact (flat name → fp32 array .npz + metadata) that any
engine, any topology, or external tooling can consume.
"""

import json
import pathlib

import numpy as np

from deepspeed_tpu.utils.logging import logger, log_dist

UNIVERSAL_FILE = "universal_fp32.npz"
META_FILE = "universal_meta.json"


def _flatten(tree, prefix=()):
    import jax
    out = {}

    def rec(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                rec(v, path + (str(k),))
        elif isinstance(node, tuple) and hasattr(node, "_fields"):
            # NamedTuple (optax states): field names, not indices — orbax
            # round-trips these as field-keyed dicts, so the offline converter
            # and the live engine produce identical paths
            for k, v in zip(node._fields, node):
                rec(v, path + (str(k),))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                rec(v, path + (str(i),))
        elif node is None:
            pass
        else:
            out["/".join(path)] = node

    rec(tree, prefix)
    return out


def _unflatten_into(template, flat):
    """Place flat name→array entries into a template pytree (params or
    optimizer state — handles dicts, lists, tuples, NamedTuples like optax
    states, and None leaves, mirroring `_flatten`)."""
    def rec(node, path):
        if isinstance(node, dict):
            return {k: rec(v, path + (str(k),)) for k, v in node.items()}
        if isinstance(node, tuple) and hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(rec(v, path + (str(k),))
                                for k, v in zip(node._fields, node)))
        if isinstance(node, (list, tuple)):
            return type(node)(rec(v, path + (str(i),)) for i, v in enumerate(node))
        if node is None:
            return None
        key = "/".join(path)
        if key not in flat:
            raise KeyError(f"universal checkpoint missing param '{key}'")
        return flat[key]

    return rec(template, ())


def _write_universal(flat, out_dir, extra_meta=None):
    """Single writer of the on-disk universal format (npz + meta json)."""
    out_dir = pathlib.Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    np.savez(out_dir / UNIVERSAL_FILE, **flat)
    meta = {
        "format_version": 1,
        "param_shapes": {k: list(v.shape) for k, v in flat.items()},
    }
    meta.update(extra_meta or {})
    with open(out_dir / META_FILE, "w") as f:
        json.dump(meta, f, indent=2)
    log_dist(f"universal checkpoint -> {out_dir} ({len(flat)} tensors)", ranks=[0])
    return str(out_dir)


OPT_PREFIX = "__opt__"


def save_universal_checkpoint(engine, save_dir, tag="universal",
                              save_optimizer_states=True):
    """Gather full fp32 weights AND optimizer state from the engine (whatever
    its ZeRO/TP/PP layout) and write the flat npz artifact.

    v2 format (reference `ds_to_universal.py:254`, which merges fp32 weights
    *and* exp_avg/exp_avg_sq into reshardable slices): optimizer-state leaves
    (the optax tree — Adam mu/nu, step counts, ...) are stored fp32 under
    `__opt__/<structural path>` next to the fp32 params, plus the global step,
    so a topology-changing resume continues the SAME optimization trajectory
    instead of resetting moments."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    fp32 = engine.get_fp32_state_dict()
    flat = {k: np.asarray(v, np.float32) for k, v in _flatten(fp32).items()}
    has_opt = False
    if save_optimizer_states and engine.state.opt_state is not None:
        opt = engine.state.opt_state
        try:
            # replicate-then-fetch (same mechanism as get_fp32_state_dict):
            # ZeRO-sharded optimizer state in a multi-process run is not fully
            # addressable, so a bare device_get would fail exactly on the
            # large runs universal checkpoints exist for
            rep = jax.tree_util.tree_map(
                lambda _: NamedSharding(engine.mesh, P()), opt)
            opt_host = jax.device_get(
                jax.jit(lambda t: t, out_shardings=rep)(opt))
        except Exception:
            # host-tier/pinned state (or numpy leaves) — already addressable
            opt_host = jax.device_get(opt)
        flat.update({k: np.asarray(v, np.float32)
                     for k, v in _flatten(opt_host, (OPT_PREFIX,)).items()})
        has_opt = True
    scaler = engine.state.scaler
    return _write_universal(flat, pathlib.Path(save_dir) / tag, {
        "format_version": 2,
        "has_optimizer_state": has_opt,
        "global_steps": engine.global_steps,
        "step": int(engine.state.step),
        # fp16 dynamic loss-scaler scalars (reference ds_to_universal keeps
        # them with the optimizer slices); harmless constants under bf16
        "scaler": {"scale": float(scaler.scale),
                   "good_steps": int(scaler.good_steps),
                   "overflows": int(scaler.overflows),
                   "hysteresis_left": int(scaler.hysteresis_left)},
        "zero_stage": engine.zero_stage,
        "mesh": str(engine.spec),
    })


def load_universal_checkpoint(engine, load_dir, tag="universal", strict=True,
                              load_optimizer_states=True):
    """Load a universal artifact into an engine of ANY topology: arrays are cast
    to each template leaf's dtype and placed with the engine's own shardings
    (params, fp32 master, AND — v2 — the optimizer-state tree, so Adam moments
    survive a mesh/TP/PP refactoring; reference `universal_checkpoint.py:12`).
    v1 artifacts without optimizer slices fall back to fresh optimizer state."""
    import jax
    import jax.numpy as jnp

    in_dir = pathlib.Path(load_dir) / tag
    with np.load(in_dir / UNIVERSAL_FILE) as data:
        flat = {k: data[k] for k in data.files}
    opt_flat = {k[len(OPT_PREFIX) + 1:]: v for k, v in flat.items()
                if k.startswith(OPT_PREFIX + "/")}
    param_flat = {k: v for k, v in flat.items()
                  if not k.startswith(OPT_PREFIX + "/")}
    params_np = _unflatten_into(engine.state.params, param_flat)

    def place_like(leaf, arr):
        return jax.device_put(jnp.asarray(arr, leaf.dtype), leaf.sharding)

    params = jax.tree_util.tree_map(place_like, engine.state.params, params_np)
    state = engine.state._replace(params=params)
    if engine.keep_master:
        master = jax.tree_util.tree_map(place_like, engine.state.master, params_np)
        state = state._replace(master=master)
    if load_optimizer_states and opt_flat and state.opt_state is not None:
        # the fresh opt_state is the structural+sharding template: every leaf
        # takes the saved full array, cast to the leaf dtype, placed with the
        # leaf's sharding (that mapping IS the reshard — on a different mesh
        # factoring the same full array just splits differently)
        template = state.opt_state
        named = _flatten(template)
        if set(named) != set(opt_flat):
            missing = sorted(set(named) - set(opt_flat))[:5]
            extra = sorted(set(opt_flat) - set(named))[:5]
            msg = ("universal optimizer state does not match this engine's "
                   f"optimizer structure (missing {missing}, unexpected "
                   f"{extra})")
            if strict:
                raise KeyError(msg + "; pass strict=False to reset moments "
                               "instead, or load_optimizer_states=False")
            logger.warning(msg + " — optimizer state reset (strict=False)")
            opt_flat = {}
        if opt_flat:
            opt_np = _unflatten_into(template, opt_flat)
            opt_state = jax.tree_util.tree_map(place_like, template, opt_np)
            state = state._replace(opt_state=opt_state)
    elif load_optimizer_states and not opt_flat:
        log_dist("universal checkpoint has no optimizer slices (v1 artifact): "
                 "optimizer state reset", ranks=[0])
    meta = {}
    meta_file = in_dir / META_FILE
    if meta_file.exists():
        with open(meta_file) as f:
            meta = json.load(f)
    if meta.get("step") is not None and load_optimizer_states:
        # counters ride with the optimizer state: a weights-only warm start
        # keeps fresh step/LR-schedule counters (reference module-only load
        # semantics, `runtime/engine.py` load_module_only)
        state = state._replace(step=jax.device_put(
            jnp.asarray(meta["step"], state.step.dtype), state.step.sharding))
    if meta.get("scaler") and load_optimizer_states:
        # scaler rides with the optimizer slices (reference keeps them
        # together); a weights-only load keeps the engine's fresh scale
        sc = meta["scaler"]
        old = state.scaler
        state = state._replace(scaler=type(old)(
            scale=jax.device_put(jnp.asarray(sc["scale"], old.scale.dtype),
                                 old.scale.sharding),
            good_steps=jax.device_put(
                jnp.asarray(sc["good_steps"], old.good_steps.dtype),
                old.good_steps.sharding),
            overflows=jax.device_put(
                jnp.asarray(sc["overflows"], old.overflows.dtype),
                old.overflows.sharding),
            hysteresis_left=jax.device_put(
                jnp.asarray(sc["hysteresis_left"], old.hysteresis_left.dtype),
                old.hysteresis_left.sharding)))
    engine.state = state
    if meta.get("global_steps") is not None and load_optimizer_states \
            and hasattr(engine, "global_steps"):
        engine.global_steps = int(meta["global_steps"])  # keep counters in sync
    log_dist(f"loaded universal checkpoint from {in_dir} "
             f"(optimizer state {'restored' if opt_flat else 'reset'})",
             ranks=[0])
    return meta


def convert_to_universal(ckpt_dir, out_dir, engine):
    """Offline `ds_to_universal` analog: load a tagged checkpoint into `engine`,
    then emit the universal artifact."""
    from deepspeed_tpu.checkpoint.saver import load_checkpoint
    path, _ = load_checkpoint(engine, ckpt_dir)
    assert path is not None, f"no checkpoint found in {ckpt_dir}"
    return save_universal_checkpoint(engine, out_dir)


def get_fp32_state_dict_from_universal(load_dir, tag="universal"):
    """zero_to_fp32-style accessor: plain dict of fp32 numpy arrays."""
    in_dir = pathlib.Path(load_dir) / tag
    with np.load(in_dir / UNIVERSAL_FILE) as data:
        return {k: data[k] for k in data.files}


def convert_checkpoint_to_universal(ckpt_dir, out_dir, tag=None, out_tag="universal"):
    """Fully offline converter (no engine needed) — the `ds_to_universal.py`
    CLI role (`checkpoint/ds_to_universal.py:254`): reconstruct the fp32 param
    tree from a saved checkpoint and write the flat universal artifact.

    Restores the checkpoint's structured TrainState directly — orbax format,
    or the numpy engine's npz (whose `keys.json` records every leaf's key
    path) — so keys match `save_universal_checkpoint` /
    `load_universal_checkpoint` exactly."""
    import os
    from deepspeed_tpu.checkpoint.zero_to_fp32 import (_read_latest,
                                                       _restore_state_tree)
    tag = tag or _read_latest(ckpt_dir)
    if tag is None:
        raise FileNotFoundError(f"no 'latest' file in {ckpt_dir}; pass --tag")
    state_path = os.path.join(ckpt_dir, str(tag), "state")
    restored, fmt = _restore_state_tree(state_path)
    if fmt not in ("orbax", "npz-named"):
        raise ValueError(
            "offline universal conversion needs an orbax-format checkpoint or "
            "a named npz (keys.json, written by this version's numpy engine); "
            "legacy positional npz cannot be mapped back to parameter names "
            "offline — use convert_to_universal(ckpt_dir, out_dir, engine)")
    def field(name):
        return restored.get(name) if isinstance(restored, dict) \
            else getattr(restored, name, None)

    master, params = field("master"), field("params")
    source = master if master is not None else params
    if source is None:
        raise ValueError("checkpoint has neither 'master' nor 'params' trees")
    flat = {k: np.asarray(v, np.float32) for k, v in _flatten(source).items()}
    opt_state = field("opt_state")
    has_opt = opt_state is not None and _flatten(opt_state)
    if has_opt:  # v2: exp_avg/exp_avg_sq slices too (ds_to_universal.py:254)
        flat.update({k: np.asarray(v, np.float32)
                     for k, v in _flatten(opt_state, (OPT_PREFIX,)).items()})
    step = field("step")
    extra = {"format_version": 2, "has_optimizer_state": bool(has_opt),
             "source_checkpoint": str(ckpt_dir), "tag": str(tag)}
    if step is not None and np.ndim(step) == 0:
        extra["step"] = int(step)
    scaler = field("scaler")
    if scaler is not None:
        def sfield(name, idx):
            v = (scaler.get(name) if isinstance(scaler, dict)
                 else getattr(scaler, name, None))
            if v is None and not isinstance(scaler, dict):
                try:
                    v = scaler[idx]
                except Exception:
                    v = None
            return v
        vals = {n: sfield(n, i) for i, n in enumerate(
            ("scale", "good_steps", "overflows", "hysteresis_left"))}
        if all(v is not None for v in vals.values()):
            extra["scaler"] = {"scale": float(np.asarray(vals["scale"])),
                               "good_steps": int(np.asarray(vals["good_steps"])),
                               "overflows": int(np.asarray(vals["overflows"])),
                               "hysteresis_left": int(np.asarray(vals["hysteresis_left"]))}
    return _write_universal(flat, pathlib.Path(out_dir) / out_tag, extra)


def main(argv=None):
    """`ds_to_universal` CLI (reference bin-level converter)."""
    import argparse
    parser = argparse.ArgumentParser(
        description="convert a deepspeed-tpu checkpoint to a universal "
                    "(topology-independent) checkpoint")
    parser.add_argument("--input_folder", required=True,
                        help="checkpoint root (contains `latest` / tag dirs)")
    parser.add_argument("--output_folder", required=True,
                        help="where to write the universal artifact")
    parser.add_argument("--tag", default=None, help="checkpoint tag (default: latest)")
    parser.add_argument("--out_tag", default="universal")
    args = parser.parse_args(argv)
    out = convert_checkpoint_to_universal(args.input_folder, args.output_folder,
                                          tag=args.tag, out_tag=args.out_tag)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
