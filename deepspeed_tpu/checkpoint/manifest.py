"""Checkpoint integrity manifests + commit/retention helpers.

Every committed checkpoint tag carries a `manifest.json` written INSIDE the
tag's staging dir before the rename-commit, recording:

  * per-leaf tree entries (key path, global shape, dtype),
  * a per-file content checksum (crc32) + byte size for every file in the tag,
  * the step, world/mesh shape and framework version that produced it.

A tag directory is *committed* iff it parses a manifest — the saver renames
`<tag>.tmp` -> `<tag>` only after the manifest (and everything it describes)
is durable, so a mid-save crash can never leave a committed-looking tag with
half-written state. Loaders use `verify_manifest` to detect corruption and
`committed_tags` to walk back to the newest good tag.

This module is deliberately stdlib-only (no jax imports) so the offline
doctor CLI (`checkpoint/doctor.py`) can validate checkpoints without touching
a device runtime or deserializing any state.
"""

import json
import os
import pathlib
import shutil
import tempfile
import time
import zlib

MANIFEST_FILE = "manifest.json"
MANIFEST_FORMAT_VERSION = 1
TMP_SUFFIX = ".tmp"
LATEST_FILE = "latest"

_CHUNK = 4 * 2**20


class CheckpointCorruptionError(RuntimeError):
    """Raised when every retained checkpoint tag fails integrity validation."""


# ----------------------------------------------------------------------
# low-level durability primitives
# ----------------------------------------------------------------------


def fsync_file(path):
    fd = os.open(str(path), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path):
    """Persist directory entries (the rename itself) — no-op where unsupported."""
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path, text):
    """Write a small text file via tempfile + rename so readers never observe
    a half-written (or empty) file — the `latest` pointer race fix."""
    path = pathlib.Path(path)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=path.name + ".",
                               suffix=TMP_SUFFIX)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, str(path))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(path.parent)


def file_crc32(path):
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


# ----------------------------------------------------------------------
# manifest write / read / verify
# ----------------------------------------------------------------------


def _walk_files(root):
    """Relative paths of every regular file under root, sorted."""
    root = pathlib.Path(root)
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            out.append(rel.replace(os.sep, "/"))
    return sorted(out)


def write_manifest(ckpt_dir, tag, step, tree=None, world=None, engine=None,
                   extra=None):
    """Checksum every file already present in `ckpt_dir` (the staging dir) and
    write + fsync `manifest.json` next to them. Must run BEFORE the
    rename-commit: the manifest's presence is the commit marker."""
    ckpt_dir = pathlib.Path(ckpt_dir)
    files = {}
    total = 0
    for rel in _walk_files(ckpt_dir):
        if rel == MANIFEST_FILE:
            continue
        p = ckpt_dir / rel
        size = p.stat().st_size
        files[rel] = {"bytes": size, "crc32": f"{file_crc32(p):08x}"}
        total += size
        fsync_file(p)
    manifest = {
        "format_version": MANIFEST_FORMAT_VERSION,
        "tag": str(tag),
        "step": int(step),
        "created_unix": time.time(),
        "engine": engine,
        "world": world or {},
        "tree": tree or [],
        "files": files,
        "total_bytes": total,
    }
    if extra:
        manifest["extra"] = dict(extra)
    mpath = ckpt_dir / MANIFEST_FILE
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    fsync_dir(ckpt_dir)
    return manifest


def read_manifest(ckpt_dir):
    """Parse `<ckpt_dir>/manifest.json`; None if absent or unparseable."""
    mpath = pathlib.Path(ckpt_dir) / MANIFEST_FILE
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_committed(ckpt_dir):
    return read_manifest(ckpt_dir) is not None


def verify_manifest(ckpt_dir, template_tree=None, deep=True,
                    template_prefixes=None):
    """Validate a committed tag dir against its manifest.

    Checks: manifest parses; every listed file exists with the recorded size
    and (deep=True) crc32; optionally the recorded leaf tree matches
    `template_tree` (a list of {key, shape, dtype} entries — what the restore
    target expects). `template_prefixes` restricts the tree comparison to key
    prefixes (partial loads: module-only restores only care about params).

    Returns (ok: bool, errors: list[str]).
    """
    ckpt_dir = pathlib.Path(ckpt_dir)
    errors = []
    manifest = read_manifest(ckpt_dir)
    if manifest is None:
        return False, [f"{ckpt_dir}: missing or unparseable {MANIFEST_FILE}"]
    for rel, meta in manifest.get("files", {}).items():
        p = ckpt_dir / rel
        if not p.is_file():
            errors.append(f"missing file: {rel}")
            continue
        size = p.stat().st_size
        if size != meta.get("bytes"):
            errors.append(f"size mismatch: {rel} ({size} != {meta.get('bytes')})")
            continue
        if deep:
            crc = f"{file_crc32(p):08x}"
            if crc != meta.get("crc32"):
                errors.append(f"checksum mismatch: {rel} "
                              f"({crc} != {meta.get('crc32')})")
    if template_tree is not None and manifest.get("tree"):
        errors.extend(compare_trees(manifest["tree"], template_tree,
                                    prefixes=template_prefixes))
    return not errors, errors


def compare_trees(saved_tree, template_tree, prefixes=None):
    """Structural diff of two leaf-entry lists ({key, shape, dtype} each)."""
    def index(entries):
        out = {}
        for e in entries:
            k = e.get("key")
            if prefixes is not None and not any(
                    k == p or k.startswith(p + "/") for p in prefixes):
                continue
            out[k] = (list(e.get("shape") or []), e.get("dtype"))
        return out

    saved, tmpl = index(saved_tree), index(template_tree)
    errors = []
    for k in sorted(set(tmpl) - set(saved)):
        errors.append(f"leaf missing from checkpoint: {k}")
    for k in sorted(set(saved) - set(tmpl)):
        errors.append(f"unexpected leaf in checkpoint: {k}")
    for k in sorted(set(saved) & set(tmpl)):
        if saved[k][0] != tmpl[k][0]:
            errors.append(f"shape mismatch at {k}: "
                          f"saved {saved[k][0]} != expected {tmpl[k][0]}")
        elif saved[k][1] != tmpl[k][1]:
            errors.append(f"dtype mismatch at {k}: "
                          f"saved {saved[k][1]} != expected {tmpl[k][1]}")
    return errors


# ----------------------------------------------------------------------
# tag discovery / latest resolution
# ----------------------------------------------------------------------


def committed_tags(save_dir):
    """[(tag, step)] for every committed tag dir, newest (highest step,
    then mtime) first."""
    save_dir = pathlib.Path(save_dir)
    if not save_dir.is_dir():
        return []
    out = []
    for child in save_dir.iterdir():
        if not child.is_dir() or child.name.endswith(TMP_SUFFIX):
            continue
        m = read_manifest(child)
        if m is None:
            continue
        out.append((child.name, int(m.get("step", -1)), child.stat().st_mtime))
    out.sort(key=lambda t: (t[1], t[2]), reverse=True)
    return [(tag, step) for tag, step, _ in out]


def uncommitted_dirs(save_dir):
    """Tag-shaped dirs with NO manifest: in-flight `.tmp` staging dirs and
    legacy (pre-manifest) tags. Retention GC never touches these."""
    save_dir = pathlib.Path(save_dir)
    if not save_dir.is_dir():
        return []
    out = []
    for child in save_dir.iterdir():
        if child.is_dir() and read_manifest(child) is None:
            if child.name.endswith(TMP_SUFFIX) or (child / "state").exists() \
                    or (child / "client.json").exists():
                out.append(child.name)
    return sorted(out)


def resolve_latest_tag(save_dir):
    """Best-effort newest tag. The commit marker (manifest) is the source of
    truth, the `latest` pointer a hint: a committed tag with a HIGHER step
    than the pointed one wins (a crash between rename-commit and the pointer
    advance must not silently discard the newest committed checkpoint). The
    pointer is honored when it names the newest committed tag, when no newer
    committed tag exists, or for legacy manifest-less dirs. Returns None when
    nothing tag-like exists."""
    save_dir = pathlib.Path(save_dir)
    latest = save_dir / LATEST_FILE
    pointed = None
    if latest.exists():
        try:
            pointed = latest.read_text().strip() or None
        except OSError:
            pointed = None
    tags = committed_tags(save_dir)
    if pointed:
        pm = read_manifest(save_dir / pointed)
        if pm is not None:
            if tags and tags[0][0] != pointed \
                    and tags[0][1] > int(pm.get("step", -1)):
                return tags[0][0]  # newer committed tag than the pointer
            return pointed
    if tags:
        return tags[0][0]
    if pointed and (save_dir / pointed).is_dir():
        return pointed  # legacy pre-manifest layout
    legacy = [save_dir / t for t in uncommitted_dirs(save_dir)
              if not t.endswith(TMP_SUFFIX)]
    if legacy:
        return max(legacy, key=lambda p: p.stat().st_mtime).name
    return None


# ----------------------------------------------------------------------
# garbage collection / retention
# ----------------------------------------------------------------------


def gc_orphaned_tmp(save_dir, keep=None):
    """Remove `.tmp` staging dirs orphaned by crashed saves. `keep` names the
    staging dir of a save currently in flight. Returns removed names."""
    save_dir = pathlib.Path(save_dir)
    if not save_dir.is_dir():
        return []
    removed = []
    for child in save_dir.iterdir():
        if not child.is_dir() or not child.name.endswith(TMP_SUFFIX):
            continue
        if keep is not None and child.name == str(keep):
            continue
        shutil.rmtree(child, ignore_errors=True)
        removed.append(child.name)
    return removed


def retention_gc(save_dir, keep_last_n, protect=()):
    """Delete the oldest COMMITTED tags beyond `keep_last_n`. Uncommitted /
    legacy dirs are never deleted (they may be a save in flight, or the only
    copy of a pre-manifest checkpoint). Returns removed tags."""
    if keep_last_n is None or keep_last_n <= 0:
        return []
    save_dir = pathlib.Path(save_dir)
    protect = {str(p) for p in protect if p}
    removed = []
    for tag, _step in committed_tags(save_dir)[keep_last_n:]:
        if tag in protect:
            continue
        shutil.rmtree(save_dir / tag, ignore_errors=True)
        removed.append(tag)
    return removed
