"""`deepspeed_tpu.pipe` — the reference's `deepspeed.pipe` namespace
(`deepspeed/pipe/__init__.py` re-exports `PipelineModule`, `LayerSpec`,
`TiedLayerSpec` from `runtime/pipe/module.py`).

TPU mapping: a pipeline "module" is three pure functions + stacked stage
params (`parallel/pipeline.py`), not an nn.Sequential split. `PipelineModule`
here is the reference-shaped constructor over those primitives; `LayerSpec`'s
role (deferred layer construction so each rank builds only its stages) is
subsumed by construction-time sharding — params materialize into their pipe
shard directly (ModelSpec.init_fn / zero.Init).
"""

from deepspeed_tpu.parallel.pipeline import (partition_layers,
                                             pipeline_loss_fn,
                                             pipeline_grad_fn,
                                             pipeline_forward_fn,
                                             make_gpt_pipeline_model)
from deepspeed_tpu.runtime.engine import ModelSpec


class PipelineModule:
    """Reference-shaped `PipelineModule` (`runtime/pipe/module.py:92`).

    Args mirror the reference where they translate:
      * embed_fn/block_fn/head_loss_fn — the stage functions (the reference's
        `layers=[LayerSpec...]` list collapses into one scanned block fn over
        stacked params);
      * params — {"embed", "blocks" [PP*Lp, ...], "head"} pytree;
      * num_stages — pipe depth (reference `num_stages`);
      * num_microbatches — schedule width;
      * partition_method — kept for signature parity; stage assignment of
        stacked blocks is uniform by construction (use `partition_layers` to
        compute assignments for uneven costs);
      * schedule — "1f1b" (reference TrainSchedule) or "gpipe" (fill-drain).

    `.to_model_spec()` yields the engine input; the instance itself is also
    accepted by `deepspeed_tpu.initialize` via duck-typing of ModelSpec
    fields.
    """

    def __init__(self, embed_fn, block_fn, head_loss_fn, params,
                 num_stages=2, num_microbatches=4, partition_method="uniform",
                 schedule="1f1b", remat_blocks=True, param_specs=None,
                 name="pipeline", remat_prevent_cse=False):
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.partition_method = partition_method
        loss_fn = pipeline_loss_fn(embed_fn, block_fn, head_loss_fn,
                                   num_stages=num_stages,
                                   num_microbatches=num_microbatches,
                                   remat_blocks=remat_blocks,
                                   remat_prevent_cse=remat_prevent_cse)
        schedule = schedule.lower()
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"unknown schedule {schedule!r}")
        grad_fn = None
        if schedule == "1f1b":
            grad_fn = pipeline_grad_fn(embed_fn, block_fn, head_loss_fn,
                                       num_stages=num_stages,
                                       num_microbatches=num_microbatches,
                                       remat_blocks=remat_blocks,
                                       remat_prevent_cse=remat_prevent_cse)
        self._spec = ModelSpec(loss_fn=loss_fn, params=params,
                               param_specs=param_specs, grad_fn=grad_fn,
                               name=name)

    def to_model_spec(self) -> ModelSpec:
        return self._spec

    # duck-typed ModelSpec surface so initialize(model=PipelineModule(...)) works
    def __getattr__(self, item):
        return getattr(self.__dict__["_spec"], item)


__all__ = ["PipelineModule", "partition_layers", "pipeline_loss_fn",
           "pipeline_grad_fn", "pipeline_forward_fn", "make_gpt_pipeline_model"]
