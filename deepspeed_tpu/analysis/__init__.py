"""`deepspeed_tpu.analysis` — the `dstpu_lint` static-analysis subsystem.

A stdlib-`ast` rule framework that mechanically enforces the invariants
the serving/training stack runs on (see docs/static_analysis.md):

====== ===================== ==========================================
DT001  host-sync-in-hot-path no `.item()` / `jax.device_get` /
                             `block_until_ready` / `np.asarray`-on-
                             device-values in the dispatch paths
DT002  clock-injection       serving-tier time flows through the
                             injectable clock the chaos harness swaps
DT003  donation-safety       a donated buffer is never read again
                             before being rebound
DT004  recompile-hazard      `jax.jit` is constructed once per program
                             lifetime, not per step/loop iteration
DT005  metric-catalog        docs/profiling.md and the recording sites
                             agree (one implementation, shared with
                             tests/test_telemetry.py)
====== ===================== ==========================================

DT000 is reserved for the framework itself (pragma hygiene, unparsable
files). Suppress a finding with `# dstpu: ignore[DTnnn]: reason` (the
reason is mandatory); grandfathered findings live in the shrink-only
`lint_baseline.json`. CLI: `bin/dstpu_lint` (`--json`, `--baseline`,
`--rules`); the tier-1 self-check is `tests/test_lint.py`.
"""

from deepspeed_tpu.analysis.core import (     # noqa: F401
    Finding, LintReport, ModuleContext, ProjectContext, Rule, all_rules,
    register, run_lint)
from deepspeed_tpu.analysis import baseline   # noqa: F401

__all__ = ["Finding", "LintReport", "ModuleContext", "ProjectContext",
           "Rule", "all_rules", "register", "run_lint", "baseline"]
