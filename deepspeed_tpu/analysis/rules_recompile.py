"""DT004 — recompile-hazard.

`jax.jit` is cheap to CALL and ruinously expensive to CONSTRUCT-and-miss:
a fresh `jax.jit(fn)` wrapper starts with an empty compile cache, so
building one per step / per request / per loop iteration recompiles the
program every time (seconds of XLA work on a real chip, and exactly the
failure mode the PR 8 compile watchdog catches at RUNTIME — this rule
catches it at review time). The codebase's sanctioned patterns:

* construct at module level, in `__init__`, or in a `_build_*`/`_make_*`
  builder called once per engine lifetime;
* construct lazily under a caching guard (`if self._prog is None:`), the
  degradation ladder's `decode_step_w1` idiom;
* a factory that RETURNS the jitted callable (`build_draft_program`) —
  its call sites hold the persistent handle;
* a PROGRAM REGISTRY registration (the attention dispatch layer's idiom,
  `ops/attention_dispatch.py`): a `jax.jit(...)` constructed inside the
  arguments of a `register_*(...)` call is stored once in the registry —
  registration is a once-per-lifetime construction context wherever it
  happens (ring/quant attention programs register like the scheduler's
  persistent programs).

Anything else — a `jax.jit(...)` in a loop body, or in a plain function
that is re-entered per step/request — fires. A jitted function whose
`static_argnums` parameter carries an unhashable (list/dict/set) default
also fires: every call with the default raises or misses the cache.
"""

from __future__ import annotations

import ast
import re

from deepspeed_tpu.analysis.core import Rule, register
from deepspeed_tpu.analysis.jaxmodel import dotted, static_argnums_of

# function names that mean "runs once per engine/program lifetime"
_BUILD_CONTEXT = re.compile(
    r"^(__init__|__post_init__|__new__)$"
    r"|^_?(build|make|init|create|setup|register|compile|factory|wrap)")

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


def _is_cache_guard(test: ast.expr) -> bool:
    """`if X is None:` / `if not X:` — the lazy-build idiom."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return True
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.ops[0], ast.Is) \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        return True
    return False


@register
class RecompileHazardRule(Rule):
    id = "DT004"
    name = "recompile-hazard"
    description = (
        "jax.jit constructed where it is re-built per call (loop body / "
        "per-step function without a caching guard), or jitted with an "
        "unhashable static_argnums default — each one recompiles")

    def check_module(self, ctx):
        findings = []
        # parent links + enclosing chains, one pass
        parents = {}
        for node in ast.walk(ctx.tree):
            for ch in ast.iter_child_nodes(node):
                parents[ch] = node

        local_defs = {n.name: n for n in ast.walk(ctx.tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}

        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and dotted(node.func) == "jax.jit"):
                continue
            findings.extend(self._check_static_defaults(ctx, node,
                                                        local_defs))
            # climb to find enclosing functions / loops / guards
            chain = []
            cur = node
            while cur in parents:
                cur = parents[cur]
                chain.append(cur)
            funcs = [n for n in chain
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            if not funcs:
                continue                      # module level: persistent
            guarded = any(isinstance(n, ast.If) and _is_cache_guard(n.test)
                          for n in chain)
            if guarded:
                continue                      # lazy-build idiom
            # program-registry idiom: the jit CALLABLE (not its result —
            # `register_x(jax.jit(f)(v))` invokes per call and stays a
            # hazard) flows into a register_*() call's arguments and is
            # stored once, called forever
            in_registry = any(
                isinstance(n, ast.Call)
                and (dotted(n.func) or "").split(".")[-1]
                .startswith("register")
                for n in chain)
            invoked = any(isinstance(n, ast.Call) and n.func is node
                          for n in chain)
            if in_registry and not invoked:
                continue
            in_loop = any(isinstance(n, (ast.For, ast.While))
                          for n in chain[:chain.index(funcs[0])])
            if in_loop:
                findings.append(ctx.finding(
                    self.id, node,
                    f"jax.jit constructed inside a loop body in "
                    f"'{funcs[0].name}' — a fresh wrapper per iteration "
                    f"recompiles every time; hoist it or guard it "
                    f"(`if prog is None:`)"))
                continue
            if any(_BUILD_CONTEXT.match(f.name) for f in funcs):
                continue                      # builder/ctor: once per life
            if self._returns_this_jit(funcs[0], node):
                continue                      # factory: caller holds it
            findings.append(ctx.finding(
                self.id, node,
                f"jax.jit constructed inside '{funcs[0].name}', which "
                f"is not a builder (`_build_*`/`_make_*`/`__init__`), "
                f"has no caching guard, and does not return the jitted "
                f"callable — if this function runs per step/request, "
                f"every call recompiles"))
        return findings

    @staticmethod
    def _returns_this_jit(fn, jit_call):
        """Factory exemption: the RETURNED value carries the jit
        callable out (possibly wrapped). `return jax.jit(f)(x)` does
        not qualify — that returns the invocation result and rebuilds
        the wrapper per call."""
        from deepspeed_tpu.analysis.jaxmodel import find_returned_jit
        for ret in ast.walk(fn):
            if isinstance(ret, ast.Return) and ret.value is not None:
                if find_returned_jit(ret.value) is jit_call:
                    return True
        return False

    def _check_static_defaults(self, ctx, jit_call, local_defs):
        statics = static_argnums_of(jit_call)
        if not statics or not jit_call.args:
            return []
        target = jit_call.args[0]
        name = dotted(target)
        fn = local_defs.get(name) if name else None
        if fn is None:
            return []
        args = fn.args
        params = list(args.posonlyargs) + list(args.args)
        # defaults align to the TAIL of the positional params
        offset = len(params) - len(args.defaults)
        findings = []
        for i in statics:
            if i < offset or i >= len(params):
                continue
            default = args.defaults[i - offset]
            if isinstance(default, _UNHASHABLE):
                findings.append(ctx.finding(
                    self.id, default,
                    f"static_argnums position {i} "
                    f"('{params[i].arg}' of '{fn.name}') has an "
                    f"unhashable default — jit hashes static args; "
                    f"calls relying on this default fail or miss the "
                    f"cache"))
        return findings
