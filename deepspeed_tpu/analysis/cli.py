"""`dstpu_lint` — run the DT rule set over the repo.

Usage::

    dstpu_lint                       # full rule set, package tree
    dstpu_lint deepspeed_tpu/serving # scope to a subtree / file
    dstpu_lint --rules DT001,DT004   # subset of rules
    dstpu_lint --json                # stable, sorted machine output
    dstpu_lint --baseline            # shrink lint_baseline.json
    dstpu_lint --list-rules

Exit codes: 0 = clean (every finding fixed, pragma'd with a reason, or
baselined); 1 = non-baselined findings OR stale baseline entries (the
ratchet: run `--baseline` to shrink); 2 = usage error.

JSON output is deterministic — findings sorted by (path, line, col,
rule) — so CI diffs and golden tests are reviewable.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from deepspeed_tpu.analysis import baseline as baseline_mod
from deepspeed_tpu.analysis.core import all_rules, run_lint

SCHEMA_VERSION = 1


def repo_root_default() -> pathlib.Path:
    """The tree the package was imported from: <root>/deepspeed_tpu/
    analysis/cli.py -> <root>. Running from a source checkout (the only
    place linting makes sense) this is the repo root."""
    return pathlib.Path(__file__).resolve().parents[2]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu_lint",
        description="TPU/JAX-aware static analysis for deepspeed_tpu "
                    "(rules DT001-DT005; see docs/static_analysis.md)")
    ap.add_argument("targets", nargs="*",
                    help="repo-relative files/dirs to scan "
                         "(default: deepspeed_tpu)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the tree this package "
                         "was imported from)")
    ap.add_argument("--rules", default=None, metavar="DT001,DT002",
                    help="comma-separated rule subset")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine output (stable, sorted)")
    ap.add_argument("--baseline", action="store_true", dest="update",
                    help="shrink the ratcheting baseline to the "
                         "still-present findings (never grows it)")
    ap.add_argument("--baseline-file", default=None,
                    help=f"baseline path (default: "
                         f"analysis/{baseline_mod.BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for rule in rules.values():
            scope = ", ".join(rule.paths) if rule.paths else "whole tree"
            kind = "project" if rule.project_level else "per-file"
            print(f"{rule.id}  {rule.name}  [{kind}; {scope}]")
            print(f"       {rule.description}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in rules]
        if unknown:
            print(f"dstpu_lint: unknown rule id(s) {unknown}; known: "
                  f"{list(rules)}", file=sys.stderr)
            return 2

    if args.update and args.no_baseline:
        print("dstpu_lint: --baseline and --no-baseline are "
              "contradictory", file=sys.stderr)
        return 2

    root = pathlib.Path(args.root).resolve() if args.root \
        else repo_root_default()
    targets = args.targets or None
    try:
        report = run_lint(root, targets=targets, rule_ids=rule_ids)
    except (FileNotFoundError, KeyError) as e:
        print(f"dstpu_lint: {e}", file=sys.stderr)
        return 2

    bl_path = args.baseline_file or baseline_mod.default_path()
    baseline = {} if args.no_baseline else baseline_mod.load(bl_path)
    # a scoped run (--rules / path targets) only sees part of the tree:
    # baseline entries outside that scope are neither stale nor
    # shrinkable — partition them out before diffing (project-level
    # rules scan the whole tree, so their entries are always in scope)
    project_ran = {rid for rid in report.rules_run
                   if rules[rid].project_level}
    scanned = set(report.scanned)
    in_scope = {k: v for k, v in baseline.items()
                if k[0] in report.rules_run
                and (k[1] in scanned or k[0] in project_ran)}
    out_scope = {k: v for k, v in baseline.items() if k not in in_scope}
    new, grandfathered, stale = baseline_mod.split(
        report.sorted_findings(), in_scope)

    if args.update:
        if not pathlib.Path(bl_path).exists():
            # initial adoption: the one time the file may be CREATED
            # from current findings; from then on it only shrinks
            seed = {}
            for f in report.sorted_findings():
                seed[f.key()] = seed.get(f.key(), 0) + 1
            baseline_mod.write(seed, bl_path)
            print(f"dstpu_lint: seeded baseline {bl_path} with "
                  f"{sum(seed.values())} grandfathered finding(s) — "
                  f"the file only shrinks from here")
            new, grandfathered = [], report.sorted_findings()
        else:
            shrunk = baseline_mod.shrink(report.sorted_findings(),
                                         in_scope)
            merged = {**out_scope, **shrunk}
            baseline_mod.write(merged, bl_path)
            dropped = sum(in_scope.values()) - sum(shrunk.values())
            kept = sum(merged.values())
            print(f"dstpu_lint: baseline {bl_path}: "
                  f"{kept} entr{'y' if kept == 1 else 'ies'} kept, "
                  f"{dropped} dropped (shrink-only: new findings are "
                  f"never added; out-of-scope entries untouched)")
        stale = []                        # just shrunk/seeded away

    if args.as_json:
        payload = {
            "schema_version": SCHEMA_VERSION,
            "rules_run": report.rules_run,
            "findings": [f.to_dict() for f in new],
            "grandfathered": [f.to_dict() for f in grandfathered],
            "stale_baseline": [{"rule": r, "path": p, "snippet": s}
                               for r, p, s in stale],
            "suppressed": len(report.suppressed),
            "ok": not new and not stale,
        }
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        if grandfathered:
            print(f"dstpu_lint: {len(grandfathered)} grandfathered "
                  f"finding(s) in the baseline (shrink with --baseline "
                  f"after fixing)")
        for r, p, s in stale:
            print(f"dstpu_lint: stale baseline entry {r} at {p} "
                  f"({s!r}) — the finding is gone; run "
                  f"`dstpu_lint --baseline` to shrink", file=sys.stderr)
        if new:
            by_rule = {}
            for f in new:
                by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
            summary = ", ".join(f"{k}: {v}"
                                for k, v in sorted(by_rule.items()))
            print(f"dstpu_lint: {len(new)} finding(s) ({summary}); fix "
                  f"them or suppress with "
                  f"`# dstpu: ignore[DTnnn]: reason`", file=sys.stderr)
        elif not stale:
            supp = len(report.suppressed)
            print(f"dstpu_lint: clean ({', '.join(report.rules_run)}; "
                  f"{supp} reasoned suppression(s), "
                  f"{len(grandfathered)} baselined)")

    return 1 if (new or stale) else 0


if __name__ == "__main__":
    raise SystemExit(main())
