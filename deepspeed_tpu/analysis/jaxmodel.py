"""Light AST model of a module's jit/donation structure.

DT001 (host-sync), DT003 (donation-safety) and DT004 (recompile-hazard)
all need the same facts about a module: which callables are persistent
jitted programs, which argument positions they donate, and which local
names hold device values. This module derives those facts from the three
idioms the codebase actually uses:

1. direct assignment — ``self._decode = jax.jit(fn, donate_argnums=(3,))``
   (the watchdog-wrapped form ``wd.wrap("name", jax.jit(...))`` counts:
   the jit call is found anywhere inside the assigned expression, and
   `CompileWatchdog.wrap` preserves the wrapped signature);
2. module-level rebinding — ``_fn = jax.jit(_fn, donate_argnums=(2,))``;
3. factories — a function/method whose ``return`` expression contains a
   ``jax.jit(...)`` call registers assignments from its call sites:
   ``self._draft_steps = build_draft_program(...)``;
4. program registries — the attention dispatch layer's idiom
   (``ops/attention_dispatch.py``): ``prog = register_program(
   AttentionProgram(..., runner=jax.jit(f)))`` binds a program OBJECT
   whose ``.runner`` is the persistent jitted callable; the registry
   records both the object name and its ``.runner`` path, so calls
   through ``prog.runner(...)`` taint like any jitted program's.

This is intentionally a heuristic model, not an import-time one: it never
executes the module, so dynamically constructed programs (dict registries
of jitted fns, cross-module factories) are invisible. The rules err on
the side of silence for what the model cannot see.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, Optional, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """'self.pool' / 'jax.jit' / 'np' for Name/Attribute chains, else
    None (subscripts, calls and literals have no stable name)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def find_jax_jit(expr: ast.AST) -> Optional[ast.Call]:
    """The first `jax.jit(...)` call inside `expr`, if any."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and dotted(node.func) == "jax.jit":
            return node
    return None


def find_returned_jit(expr: ast.AST) -> Optional[ast.Call]:
    """A `jax.jit(...)` call inside `expr` whose CALLABLE flows out —
    i.e. not immediately invoked. `return jax.jit(f)` and
    `return wrap(jax.jit(f))` qualify; `return jax.jit(f)(x)` returns
    the invocation RESULT, so the wrapper dies with the call."""
    jit = find_jax_jit(expr)
    if jit is None:
        return None
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and node.func is jit:
            return None                   # immediately invoked
    return jit


def donate_argnums_of(jit_call: ast.Call) -> Tuple[int, ...]:
    """Literal donate_argnums of a jax.jit call — (3,), 3, or absent.
    Non-literal values come back empty (the model stays silent)."""
    for kw in jit_call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if not (isinstance(el, ast.Constant)
                        and isinstance(el.value, int)):
                    return ()
                out.append(el.value)
            return tuple(out)
    return ()


def static_argnums_of(jit_call: ast.Call) -> Tuple[int, ...]:
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(el.value for el in v.elts
                             if isinstance(el, ast.Constant)
                             and isinstance(el.value, int))
    return ()


@dataclasses.dataclass
class JitProgram:
    name: str                       # dotted callee name ('self._decode')
    donate: Tuple[int, ...]
    line: int


class JitRegistry:
    """Dotted callee name -> JitProgram for one module."""

    def __init__(self):
        self.programs: Dict[str, JitProgram] = {}
        # factory fn name -> donate tuple of the jit it returns
        self.factories: Dict[str, Tuple[int, ...]] = {}

    @classmethod
    def collect(cls, tree: ast.Module) -> "JitRegistry":
        reg = cls()
        # pass 1: factories — any def whose return contains jax.jit
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for ret in ast.walk(node):
                    if isinstance(ret, ast.Return) and ret.value is not None:
                        jit = find_returned_jit(ret.value)
                        if jit is not None:
                            d = donate_argnums_of(jit)
                            reg.factories[node.name] = d
                            reg.factories[f"self.{node.name}"] = d
                            break
        # pass 2: assignments binding a jitted program to a stable name
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            jit = find_jax_jit(value)
            donate: Optional[Tuple[int, ...]] = None
            if jit is not None:
                donate = donate_argnums_of(jit)
            elif isinstance(value, ast.Call):
                callee = dotted(value.func)
                if callee in reg.factories:
                    donate = reg.factories[callee]
            if donate is None:
                continue
            # registry idiom (4): `prog = register_*(... jax.jit(f) ...)`
            # binds a program object carrying the jitted callable as
            # `.runner` — record that path too so DT001's taint follows
            # calls made through the registered program. The jit CALLABLE
            # must flow in un-invoked (find_returned_jit):
            # `register_x(jax.jit(f)(v))` passes the RESULT, the wrapper
            # dies with the call, and `.runner` would be a phantom
            registry_call = (isinstance(value, ast.Call)
                             and (dotted(value.func) or "").split(".")[-1]
                             .startswith("register")
                             and find_returned_jit(value) is not None)
            for tgt in node.targets:
                name = dotted(tgt)
                if name:
                    reg.programs[name] = JitProgram(name, donate,
                                                    node.lineno)
                    if registry_call:
                        reg.programs[f"{name}.runner"] = JitProgram(
                            f"{name}.runner", donate, node.lineno)
        return reg

    def lookup(self, call: ast.Call) -> Optional[JitProgram]:
        name = dotted(call.func)
        return self.programs.get(name) if name else None


def iter_functions(tree: ast.Module) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def assign_target_names(stmt: ast.stmt) -> Tuple[str, ...]:
    """Dotted names (re)bound by an assignment statement, tuple targets
    flattened: `a, self.pool = ...` -> ('a', 'self.pool')."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    out = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            out.extend(n for el in t.elts if (n := dotted(el)))
        else:
            n = dotted(t)
            if n:
                out.append(n)
    return tuple(out)


def statements_in_order(fn: ast.FunctionDef):
    """Flatten a function body to (statement, loop_depth) in source
    order, recursing into compound statements but NOT into nested
    function/class definitions (their scopes are analyzed separately)."""
    out = []

    def visit(stmts, depth):
        for s in stmts:
            out.append((s, depth))
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                visit(getattr(s, field, []) or [], depth
                      + (1 if isinstance(s, (ast.For, ast.While))
                         and field == "body" else 0))
            for h in getattr(s, "handlers", []) or []:
                visit(h.body, depth)
    visit(fn.body, 0)
    return out


def own_calls(stmt: ast.stmt):
    """Every Call node in one statement's OWN expressions, in source
    order — child statements and nested lambda scopes excluded (the
    former are visited separately, the latter run in another scope)."""
    def walk(node):
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, (ast.stmt, ast.Lambda)):
                continue
            if isinstance(ch, ast.Call):
                yield ch
            yield from walk(ch)
    yield from walk(stmt)


def loads_in(stmt: ast.stmt):
    """Every dotted-name Load in one statement's OWN expressions (with
    the node). Child statements of compound statements are skipped —
    `statements_in_order` visits them separately — as are nested
    function/lambda/class scopes."""
    def walk(node):
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, (ast.stmt, ast.Lambda)):
                continue
            name = dotted(ch)
            if name is not None and isinstance(
                    getattr(ch, "ctx", None), ast.Load):
                yield name, ch
                # don't descend into an Attribute chain we already named
                continue
            yield from walk(ch)
    yield from walk(stmt)
